#!/usr/bin/env python
"""Parse training logs into a table (reference tools/parse_log.py):
extracts per-epoch train/validation accuracy and throughput from the log
format emitted by Module.fit/Speedometer."""
import argparse
import re
import sys


def parse(fname):
    train_re = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.eE+-]+)")
    val_re = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.eE+-]+)")
    time_re = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.]+)")
    speed_re = re.compile(r"Epoch\[(\d+)\].*Speed: ([\d.]+) samples/sec")
    rows = {}
    speeds = {}
    with open(fname) as fin:
        for line in fin:
            for regex, key in [(train_re, "train"), (val_re, "val")]:
                m = regex.search(line)
                if m:
                    epoch = int(m.group(1))
                    rows.setdefault(epoch, {})["%s-%s" % (key, m.group(2))] = \
                        float(m.group(3))
            m = time_re.search(line)
            if m:
                rows.setdefault(int(m.group(1)), {})["time"] = float(m.group(2))
            m = speed_re.search(line)
            if m:
                speeds.setdefault(int(m.group(1)), []).append(float(m.group(2)))
    for epoch, sp in speeds.items():
        rows.setdefault(epoch, {})["speed"] = sum(sp) / len(sp)
    return rows


def main():
    parser = argparse.ArgumentParser(description="parse training log")
    parser.add_argument("logfile")
    parser.add_argument("--metric", default=None,
                        help="print only this column (e.g. val-accuracy)")
    args = parser.parse_args()
    rows = parse(args.logfile)
    if not rows:
        print("no epochs found", file=sys.stderr)
        sys.exit(1)
    cols = sorted({c for r in rows.values() for c in r})
    if args.metric:
        for epoch in sorted(rows):
            if args.metric in rows[epoch]:
                print("%d\t%g" % (epoch, rows[epoch][args.metric]))
        return
    print("epoch\t" + "\t".join(cols))
    for epoch in sorted(rows):
        print("%d\t" % epoch + "\t".join(
            "%g" % rows[epoch].get(c, float("nan")) for c in cols))


if __name__ == "__main__":
    main()
