#!/usr/bin/env python
"""Pack an image list into recordio (reference tools/im2rec.{cc,py}).

List format (same as the reference): ``index\tlabel[\tlabel...]\tpath``.
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels[0] if len(labels) == 1 else labels, parts[-1]


def main():
    from PIL import Image

    from mxnet_tpu import recordio as rio

    parser = argparse.ArgumentParser(description="image list -> recordio")
    parser.add_argument("prefix", help="output prefix (writes prefix.rec/.idx)")
    parser.add_argument("root", help="image root dir")
    parser.add_argument("--list", required=True, help="image list file")
    parser.add_argument("--resize", type=int, default=0,
                        help="resize shorter edge")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--shuffle", action="store_true")
    parser.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    args = parser.parse_args()

    items = list(read_list(args.list))
    if args.shuffle:
        random.shuffle(items)
    record = rio.MXIndexedRecordIO(args.prefix + ".idx", args.prefix + ".rec",
                                   "w")
    count = 0
    for idx, label, fname in items:
        path = os.path.join(args.root, fname)
        img = Image.open(path).convert("RGB")
        if args.resize > 0:
            w, h = img.size
            scale = args.resize / min(w, h)
            img = img.resize((int(round(w * scale)), int(round(h * scale))))
        header = rio.IRHeader(0, label, idx, 0)
        packed = rio.pack_img(header, np.asarray(img),
                              quality=args.quality, img_fmt=args.encoding)
        record.write_idx(idx, packed)
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    record.close()
    print("wrote %d records to %s.rec" % (count, args.prefix))


if __name__ == "__main__":
    main()
