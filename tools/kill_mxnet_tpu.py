#!/usr/bin/env python
"""Kill stray training processes on this machine (reference
tools/kill-mxnet.py)."""
import argparse
import os
import signal
import subprocess


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pattern", default="mxnet_tpu",
                        help="process cmdline substring to kill")
    args = parser.parse_args()
    out = subprocess.run(["pgrep", "-f", args.pattern],
                         capture_output=True, text=True)
    me = os.getpid()
    for pid in out.stdout.split():
        pid = int(pid)
        if pid == me:
            continue
        try:
            os.kill(pid, signal.SIGTERM)
            print("killed", pid)
        except ProcessLookupError:
            pass


if __name__ == "__main__":
    main()
