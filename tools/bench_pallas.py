#!/usr/bin/env python
"""Benchmark the Pallas fast-path kernels against plain XLA on the
current device (VERDICT round-1 item 9: enable MXNET_TPU_PALLAS where it
wins, document parity where it doesn't).

Prints one JSON line per case:
  {"kernel": "fused_linear", "shape": "...", "pallas_us": N,
   "xla_us": N, "speedup": N}

Run on the TPU (the default platform); results are recorded in
docs/pallas.md.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _time(fn, *args, iters=50):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    tic = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - tic) / iters * 1e6  # us


def bench_fused_linear():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    on_cpu = jax.devices()[0].platform == "cpu"
    cases = [(128, 128, 256, "relu")] if on_cpu else [
        (256, 512, 1024, "relu"),
        (1024, 1024, 1024, "relu"),
        (4096, 2048, 2048, "none"),
        (8192, 4096, 4096, "relu")]
    results = []
    for m, k, n, act in cases:
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(m, k).astype(np.float32))
        w = jnp.asarray(rng.randn(n, k).astype(np.float32))
        b = jnp.asarray(rng.randn(n).astype(np.float32))

        def xla(x, w, b):
            out = x @ w.T + b
            return jnp.maximum(out, 0) if act == "relu" else out

        xla_jit = jax.jit(xla)
        pallas_fn = jax.jit(
            lambda x, w, b: pk.fused_linear(x, w, b, act=act))
        try:
            p = np.asarray(pallas_fn(x, w, b))
            np.testing.assert_allclose(p, np.asarray(xla_jit(x, w, b)),
                                       rtol=2e-2, atol=2e-2)
            pallas_us = _time(pallas_fn, x, w, b)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"kernel": "fused_linear",
                              "shape": "%dx%dx%d" % (m, k, n),
                              "error": str(e)[:200]}))
            continue
        xla_us = _time(xla_jit, x, w, b)
        results.append(("fused_linear", "%dx%dx%d/%s" % (m, k, n, act),
                        pallas_us, xla_us))
    return results


def bench_flash_attention():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_kernels as pk

    on_cpu = jax.devices()[0].platform == "cpu"
    cases = [(1, 2, 128, 32)] if on_cpu else [
        (4, 8, 512, 64), (2, 8, 2048, 64), (1, 8, 8192, 64)]
    results = []
    for b, h, t, d in cases:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32) * 0.1)
        k = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32) * 0.1)
        v = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32) * 0.1)
        scale = 1.0 / np.sqrt(d)

        def xla(q, k, v):
            s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhts,bshd->bthd", p, v)

        xla_jit = jax.jit(xla)
        pallas_fn = jax.jit(lambda q, k, v: pk.flash_attention(q, k, v))
        try:
            p = np.asarray(pallas_fn(q, k, v))
            np.testing.assert_allclose(p, np.asarray(xla_jit(q, k, v)),
                                       rtol=2e-2, atol=2e-2)
            pallas_us = _time(pallas_fn, q, k, v, iters=20)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"kernel": "flash_attention",
                              "shape": "b%d h%d t%d d%d" % (b, h, t, d),
                              "error": str(e)[:200]}))
            continue
        xla_us = _time(xla_jit, q, k, v, iters=20)
        results.append(("flash_attention", "b%d h%d t%d d%d" % (b, h, t, d),
                        pallas_us, xla_us))
    return results


def main():
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    dev = jax.devices()[0]
    print(json.dumps({"device": getattr(dev, "device_kind", dev.platform)}))
    for name, shape, pallas_us, xla_us in (bench_fused_linear()
                                           + bench_flash_attention()):
        print(json.dumps({"kernel": name, "shape": shape,
                          "pallas_us": round(pallas_us, 1),
                          "xla_us": round(xla_us, 1),
                          "speedup": round(xla_us / pallas_us, 3)}))


if __name__ == "__main__":
    main()
