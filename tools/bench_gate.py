#!/usr/bin/env python
"""bench_gate: the perf-regression gate over the checked-in bench
artifact trajectory.

Every chip window leaves artifacts behind — ``BENCH_r*.json`` (the
training headline trajectory), ``SERVE_bench.json``,
``FLEET_bench.json``, ``MULTICHIP_scaling.json`` — but until now nobody
compared a new record against the old ones. This tool does, per
headline metric:

* **Trajectory headlines** (``BENCH_r*.json``): the latest record's
  accelerator-truth ``resnet50_train_imgs_per_sec`` (a cpu-fallback
  record carries it in ``parsed.last_accelerator_result``) against the
  best prior record. The internal baseline IS the trajectory.
* **Single-artifact headlines** (goodput, p99, occupancy, imgs/sec,
  dispatches/step): the artifact's current value against the checked-in
  baseline file (``tools/bench_baselines.json``), refreshed with
  ``--update-baselines`` after an accepted perf change.

A metric regresses when it moves in the WRONG direction by more than
its tolerance (relative); improvements always pass and never fail the
gate. A missing artifact or one stamped ``"incomplete"`` reports
INCOMPLETE — exit 0, so an unattended chip_watch window that produced
no artifact does not page anyone (``--strict`` upgrades INCOMPLETE to
failure for interactive use).

Exit codes: 0 pass/incomplete, 1 regression (each one named: metric,
artifact, baseline, current, measured delta), 2 usage error. The full
verdict lands in ``BENCH_GATE.json``; ``--progress FILE`` appends a
one-line verdict record (the obs-gate Make target points it at
PROGRESS.jsonl).

Usage::

    python tools/bench_gate.py                      # gate the repo root
    python tools/bench_gate.py --dir D --json       # machine-readable
    python tools/bench_gate.py --update-baselines   # accept current perf
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Callable, List, Optional

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mxnet_tpu.checkpoint import atomic_writer  # noqa: E402

DEFAULT_TOLERANCE = 0.10
GATE_ARTIFACT = "BENCH_GATE.json"
BASELINES = os.path.join("tools", "bench_baselines.json")


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _dig(rec: dict, path: str):
    node = rec
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _bench_headline(rec: dict) -> Optional[float]:
    """Accelerator-truth training headline from one BENCH_r*.json: a
    cpu-fallback record gates on the accelerator result it carries
    forward, never on the fallback number (cpu img/s vs TPU img/s is
    not a regression, it is a different machine)."""
    parsed = rec.get("parsed") or {}
    lar = parsed.get("last_accelerator_result") or {}
    if lar.get("value") is not None:
        return float(lar["value"])
    if parsed.get("platform", "").startswith("cpu"):
        return None
    if parsed.get("value") is not None:
        return float(parsed["value"])
    return None


class Spec:
    """One gated headline: where it lives, which way is better."""

    def __init__(self, metric: str, artifact: str, path: str,
                 direction: str, tolerance: float = DEFAULT_TOLERANCE):
        assert direction in ("higher", "lower")
        self.metric = metric
        self.artifact = artifact
        self.path = path
        self.direction = direction
        self.tolerance = tolerance

    def extract(self, rec: dict) -> Optional[float]:
        v = _dig(rec, self.path)
        return None if v is None else float(v)

    def regressed(self, current: float, baseline: float,
                  tolerance: Optional[float] = None) -> bool:
        tol = self.tolerance if tolerance is None else tolerance
        if baseline == 0:
            return False
        delta = (current - baseline) / abs(baseline)
        return (delta < -tol) if self.direction == "higher" \
            else (delta > tol)


SPECS: List[Spec] = [
    Spec("serve_goodput_rps", "SERVE_bench.json", "value", "higher"),
    Spec("serve_p99_ms", "SERVE_bench.json", "p99_ms", "lower"),
    Spec("serve_mean_batch_occupancy", "SERVE_bench.json",
         "mean_batch_occupancy", "higher"),
    # tensor-parallel serving (bench.py serve --tp), merged under the
    # ``tp`` key: goodput at tp>=2 with in-graph resharding, and the
    # delta-aware weight stream — moved bytes over full-pack bytes
    # when one param changed; a drift toward 1.0 means the diff
    # stopped skipping resident shards
    Spec("serve_tp_goodput_rps", "SERVE_bench.json",
         "tp.goodput_rps", "higher"),
    Spec("refresh_delta_bytes_ratio", "SERVE_bench.json",
         "tp.refresh.delta_bytes_ratio", "lower"),
    Spec("fleet_goodput_rps", "FLEET_bench.json", "value", "higher"),
    Spec("fleet_socket_goodput_rps", "FLEET_bench.json",
         "socket.goodput_rps", "higher"),
    Spec("fleet_feed_stall_p99_ms", "FLEET_bench.json",
         "socket.netfeed.feed_stall_p99_ms", "lower", tolerance=0.5),
    Spec("obswatch_fleet_goodput_rps", "OBS_fleet.json", "value",
         "higher"),
    Spec("multichip_imgs_per_sec", "MULTICHIP_scaling.json", "value",
         "higher"),
    Spec("multichip_dispatches_per_step", "MULTICHIP_scaling.json",
         "dispatches_per_step", "lower"),
    # FSDP recipe (bench.py multichip --fsdp): per-device params +
    # opt-state bytes vs replicated — 0.25 at fsdp=4 when every dim 0
    # divides; a ratio drift upward means the recipe stopped sharding
    Spec("fsdp_param_bytes_ratio", "MULTICHIP_scaling.json",
         "fsdp.param_bytes_ratio", "lower"),
    Spec("fsdp_dispatches_per_step", "MULTICHIP_scaling.json",
         "fsdp.dispatches_per_step", "lower"),
    # the checked-in baseline is the CONTRACT (3% overhead), not a
    # measurement; tolerance 1.0 sizes the trip point (>2x the bar) to
    # the one-core host's program-placement noise floor — the exact
    # one-dispatch/one-trace contract is pinned by tier-1 tests, this
    # gate catches gross slowdowns
    Spec("numwatch_overhead_pct", "NUMWATCH_health.json", "value",
         "lower", tolerance=1.0),
    Spec("numwatch_dispatches_per_step", "NUMWATCH_health.json",
         "dispatches_per_step", "lower"),
]


def _check_trajectory(root: str, tolerance: Optional[float],
                      checks: list):
    """BENCH_r*.json: latest accelerator-truth headline vs the best
    prior record — the trajectory is its own baseline."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    points = []
    for p in paths:
        rec = _load_json(p)
        if rec is None:
            continue
        v = _bench_headline(rec)
        if v is not None:
            points.append((os.path.basename(p), v))
    check = {"metric": "resnet50_train_imgs_per_sec",
             "artifact": "BENCH_r*.json", "direction": "higher"}
    if not paths:
        check.update(status="incomplete",
                     detail="no BENCH_r*.json trajectory")
    elif len(points) < 2:
        check.update(status="incomplete",
                     detail="fewer than 2 gateable trajectory points")
    else:
        name, current = points[-1]
        base_name, baseline = max(points[:-1], key=lambda nv: nv[1])
        spec = Spec("resnet50_train_imgs_per_sec", name,
                    "unused", "higher")
        tol = DEFAULT_TOLERANCE if tolerance is None else tolerance
        delta = (current - baseline) / abs(baseline) if baseline else 0.0
        check.update(artifact=name, baseline=baseline,
                     baseline_artifact=base_name, current=current,
                     delta=round(delta, 4), tolerance=tol,
                     status=("fail" if spec.regressed(current, baseline,
                                                      tolerance)
                             else "pass"))
    checks.append(check)


def run_gate(root: str = _ROOT, baselines_path: Optional[str] = None,
             tolerance: Optional[float] = None, strict: bool = False,
             clock: Callable[[], float] = time.time) -> dict:
    """Evaluate every headline; returns the verdict record::

        {"ts", "verdict": "pass"|"fail"|"incomplete", "checks": [...],
         "regressions": [names]}

    ``tolerance`` overrides every spec's tolerance when given;
    ``clock`` is injectable so tests stamp deterministic verdicts."""
    baselines_path = baselines_path or os.path.join(root, BASELINES)
    baselines = _load_json(baselines_path) or {}
    checks: list = []
    _check_trajectory(root, tolerance, checks)
    cache: dict = {}
    for spec in SPECS:
        path = os.path.join(root, spec.artifact)
        if spec.artifact not in cache:
            cache[spec.artifact] = _load_json(path)
        rec = cache[spec.artifact]
        check = {"metric": spec.metric, "artifact": spec.artifact,
                 "direction": spec.direction}
        if rec is None:
            check.update(status="incomplete",
                         detail="artifact missing/unreadable")
            checks.append(check)
            continue
        if rec.get("incomplete"):
            check.update(status="incomplete",
                         detail=str(rec["incomplete"]))
            checks.append(check)
            continue
        current = spec.extract(rec)
        if current is None:
            check.update(status="incomplete",
                         detail="headline %r absent" % spec.path)
            checks.append(check)
            continue
        base = (baselines.get(spec.artifact) or {}).get(spec.metric)
        if base is None or base.get("value") is None:
            check.update(status="no-baseline", current=current)
            checks.append(check)
            continue
        baseline = float(base["value"])
        tol = (base.get("tolerance", spec.tolerance)
               if tolerance is None else tolerance)
        delta = (current - baseline) / abs(baseline) if baseline else 0.0
        check.update(baseline=baseline, current=current,
                     delta=round(delta, 4), tolerance=tol,
                     status=("fail" if spec.regressed(current, baseline,
                                                      tol)
                             else "pass"))
        checks.append(check)
    regressions = [c for c in checks if c["status"] == "fail"]
    incomplete = [c for c in checks if c["status"] == "incomplete"]
    if regressions:
        verdict = "fail"
    elif incomplete and (strict or not any(
            c["status"] == "pass" for c in checks)):
        verdict = "fail" if strict else "incomplete"
    else:
        verdict = "pass"
    return {"ts": round(clock(), 6), "verdict": verdict,
            "tolerance_override": tolerance,
            "checks": checks,
            "regressions": ["%s (%s)" % (c["metric"], c["artifact"])
                            for c in regressions],
            "incomplete": ["%s (%s)" % (c["metric"], c["artifact"])
                           for c in incomplete]}


def update_baselines(root: str = _ROOT,
                     baselines_path: Optional[str] = None) -> dict:
    """Rewrite the checked-in baseline file from the current artifacts
    (atomic replace). Artifacts that are missing or incomplete keep
    their previous baseline entry."""
    baselines_path = baselines_path or os.path.join(root, BASELINES)
    out = _load_json(baselines_path) or {}
    for spec in SPECS:
        rec = _load_json(os.path.join(root, spec.artifact))
        if rec is None or rec.get("incomplete"):
            continue
        v = spec.extract(rec)
        if v is None:
            continue
        out.setdefault(spec.artifact, {})[spec.metric] = {
            "value": v, "direction": spec.direction,
            "tolerance": spec.tolerance,
            "smoke": bool(rec.get("smoke"))}
    data = (json.dumps(out, indent=2, sort_keys=True) + "\n").encode()
    with atomic_writer(baselines_path) as f:
        f.write(data)
    return out


def _render(verdict: dict) -> str:
    lines = ["bench_gate: %s" % verdict["verdict"].upper()]
    for c in verdict["checks"]:
        status = c["status"]
        if status in ("pass", "fail"):
            arrow = {"higher": ">=", "lower": "<="}[c["direction"]]
            lines.append(
                "  [%s] %-32s %s: current=%.4g baseline=%.4g "
                "delta=%+.1f%% (want %s baseline within %.0f%%)"
                % (status.upper(), c["metric"], c["artifact"],
                   c["current"], c["baseline"], 100 * c["delta"],
                   arrow, 100 * c["tolerance"]))
        else:
            lines.append("  [%s] %-32s %s: %s"
                         % (status.upper(), c["metric"], c["artifact"],
                            c.get("detail", "")))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_gate",
                                 description=__doc__.split("\n\n")[0])
    ap.add_argument("--dir", default=_ROOT,
                    help="artifact directory (default: repo root)")
    ap.add_argument("--baselines", default=None,
                    help="baseline file (default: <dir>/%s)" % BASELINES)
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override every headline's relative tolerance")
    ap.add_argument("--strict", action="store_true",
                    help="treat INCOMPLETE as failure (interactive use)")
    ap.add_argument("--json", action="store_true",
                    help="print the verdict record as JSON")
    ap.add_argument("--update-baselines", action="store_true",
                    help="accept current artifact values as baselines")
    ap.add_argument("--no-artifact", action="store_true",
                    help="skip writing %s" % GATE_ARTIFACT)
    ap.add_argument("--progress", default=None,
                    help="append a one-line verdict record to this "
                         "JSONL file")
    args = ap.parse_args(argv)
    if args.update_baselines:
        out = update_baselines(args.dir, args.baselines)
        print("bench_gate: baselines updated (%d artifacts)" % len(out))
        return 0
    verdict = run_gate(args.dir, args.baselines, args.tolerance,
                       strict=args.strict)
    if not args.no_artifact:
        try:
            with open(os.path.join(args.dir, GATE_ARTIFACT), "w") as f:
                json.dump(verdict, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError:
            pass
    if args.progress:
        line = json.dumps({
            "ts": verdict["ts"], "kind": "bench_gate",
            "verdict": verdict["verdict"],
            "checks": len(verdict["checks"]),
            "regressions": verdict["regressions"]}) + "\n"
        fd = os.open(args.progress,
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
    print(json.dumps(verdict) if args.json else _render(verdict))
    return 1 if verdict["verdict"] == "fail" else 0


if __name__ == "__main__":
    sys.exit(main())
