#!/usr/bin/env python
"""AccNN: accelerate a trained CNN by low-rank factorization.

Equivalent of the reference's ``tools/accnn/`` (accnn.py, acc_conv.py,
acc_fc.py, rank_selection.py): decompose expensive layers of a saved
checkpoint into pairs of cheaper layers, preserving the function
approximately, to cut test-time FLOPs and parameters.

* Convolution ``(N,C,y,x)`` → vertical conv ``(K,C,y,1)`` + horizontal
  conv ``(N,K,1,x)`` (Jaderberg-style VH decomposition). The 4-D kernel
  is flattened to a ``(C*y, N*x)`` matrix, SVD'd, and the two factors
  become the two kernels.
* FullyConnected ``(N,D)`` → ``(K,D)`` + ``(N,K)`` via truncated SVD.

Rank selection: the reference ran a dynamic program over per-layer
speedup/accuracy trade-offs; here ranks come from a closed-form cost
model — pick the largest ``K`` with
``decomposed_cost(K) <= original_cost / ratio`` — or from an explicit
``--config`` JSON ``{layer_name: K}``.

Usage:
    python tools/accnn.py -m model_prefix --epoch 1 --save-model new \
        --ratio 2 [--config ranks.json] [--layers conv1,fc1]
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _svd_factor(mat, K):
    """Rank-K factorization mat ≈ A @ B with A:(rows,K), B:(K,cols)."""
    U, S, Vt = np.linalg.svd(mat, full_matrices=False)
    K = max(1, min(K, S.size))
    sq = np.sqrt(S[:K])
    return U[:, :K] * sq[None, :], sq[:, None] * Vt[:K, :]


def decompose_conv_weights(W, K):
    """VH-decompose conv kernel W:(N,C,y,x) → V:(K,C,y,1), H:(N,K,1,x)."""
    N, C, y, x = W.shape
    # M[(c,i),(n,j)] = W[n,c,i,j]
    M = W.transpose(1, 2, 0, 3).reshape(C * y, N * x)
    A, B = _svd_factor(M, K)
    K = A.shape[1]
    V = A.reshape(C, y, K).transpose(2, 0, 1)[..., None]        # (K,C,y,1)
    H = B.reshape(K, N, x).transpose(1, 0, 2)[:, :, None, :]    # (N,K,1,x)
    return V.astype(W.dtype), H.astype(W.dtype)


def decompose_fc_weights(W, K):
    """SVD-decompose FC weight W:(N,D) → W1:(K,D), W2:(N,K)."""
    A, B = _svd_factor(W, K)  # W ≈ A @ B ; A:(N,K), B:(K,D)
    return B.astype(W.dtype), A.astype(W.dtype)


def select_rank_conv(C, N, ky, kx, ratio):
    orig = N * C * ky * kx
    per_k = C * ky + N * kx
    return max(1, min(int(orig / (ratio * per_k)), min(C * ky, N * kx)))


def select_rank_fc(D, N, ratio):
    orig = D * N
    per_k = D + N
    return max(1, min(int(orig / (ratio * per_k)), min(D, N)))


def _infer_input_channels(sym, json_nodes, data_shapes):
    """Per-conv input channel counts via shape inference on the graph."""
    import mxnet_tpu as mx  # noqa: F401
    internals = sym.get_internals()
    out_names = internals.list_outputs()
    _, out_shapes, _ = internals.infer_shape(**data_shapes)
    shape_of = dict(zip(out_names, out_shapes))
    chans = {}
    for node in json_nodes:
        if node["op"] not in ("Convolution", "FullyConnected"):
            continue
        src_idx = node["inputs"][0][0]
        src = json_nodes[src_idx]
        key = src["name"] if src["op"] == "null" \
            else src["name"] + "_output"
        shp = shape_of.get(key)
        if shp is not None:
            chans[node["name"]] = shp
    return chans


def decompose_model(sym, arg_params, ranks):
    """Rewrite graph + params. ``ranks``: {layer_name: K}.

    Returns (new_sym, new_arg_params). Layers not in ``ranks`` pass
    through untouched.
    """
    import mxnet_tpu as mx

    graph = json.loads(sym.tojson())
    old_nodes = graph["nodes"]
    new_nodes = []
    new_heads = []
    ref_map = {}          # old node idx -> new node idx
    new_params = dict(arg_params)
    # null nodes consumed ONLY by decomposed layers get dropped; a weight
    # shared with an untouched layer must survive
    consumers = {}
    for idx, node in enumerate(old_nodes):
        for (i, _) in node["inputs"]:
            consumers.setdefault(i, set()).add(idx)
    decomposed = set()
    for idx, node in enumerate(old_nodes):
        if node["op"] in ("Convolution", "FullyConnected") and \
                node["name"] in ranks:
            p = node["param"]
            if node["op"] == "Convolution":
                if int(p.get("num_group", "1")) != 1:
                    raise ValueError("%s: grouped conv not supported"
                                     % node["name"])
                dil = p.get("dilate")
                if dil and tuple(ast.literal_eval(dil)) != (1, 1):
                    raise ValueError("%s: dilated conv not supported"
                                     % node["name"])
            decomposed.add(idx)
    drop = set()
    for idx in decomposed:
        for (i, _) in old_nodes[idx]["inputs"][1:]:  # weight (+ bias)
            if consumers[i] <= decomposed:
                drop.add(i)

    def add(node):
        new_nodes.append(node)
        return len(new_nodes) - 1

    def null(name):
        return add({"op": "null", "name": name, "param": {},
                    "inputs": [], "attr": {}})

    for idx, node in enumerate(old_nodes):
        if idx in drop:
            continue
        name = node["name"]
        if node["op"] in ("Convolution", "FullyConnected") and name in ranks:
            K = ranks[name]
            data_ref = [ref_map[node["inputs"][0][0]], node["inputs"][0][1]]
            p = dict(node["param"])
            no_bias = p.get("no_bias", "False") in ("True", "1", True)
            w_idx = node["inputs"][1][0]
            w_name = old_nodes[w_idx]["name"]
            w_val = arg_params[w_name]
            W = np.asarray(w_val.asnumpy() if hasattr(w_val, "asnumpy")
                           else w_val)
            if w_idx in drop:
                new_params.pop(w_name, None)
            bias_val = None
            if not no_bias:
                b_idx = node["inputs"][2][0]
                b_name = old_nodes[b_idx]["name"]
                bias_val = arg_params[b_name]
                if b_idx in drop:
                    new_params.pop(b_name, None)
            if node["op"] == "Convolution":
                ky, kx = ast.literal_eval(p["kernel"])
                sy, sx = ast.literal_eval(p.get("stride", "(1, 1)"))
                py, px = ast.literal_eval(p.get("pad", "(0, 0)"))
                V, H = decompose_conv_weights(W, K)
                K = V.shape[0]
                wv = null(name + "_v_weight")
                v_idx = add({"op": "Convolution", "name": name + "_v",
                             "param": {"num_filter": str(K),
                                       "kernel": str((ky, 1)),
                                       "stride": str((sy, 1)),
                                       "pad": str((py, 0)),
                                       "no_bias": "True"},
                             "inputs": [data_ref, [wv, 0]], "attr": {}})
                wh = null(name + "_h_weight")
                inputs = [[v_idx, 0], [wh, 0]]
                hparam = {"num_filter": p["num_filter"],
                          "kernel": str((1, kx)),
                          "stride": str((1, sx)),
                          "pad": str((0, px)),
                          "no_bias": str(no_bias)}
                if not no_bias:
                    hb = null(name + "_h_bias")
                    inputs.append([hb, 0])
                    new_params[name + "_h_bias"] = bias_val
                h_idx = add({"op": "Convolution", "name": name + "_h",
                             "param": hparam, "inputs": inputs, "attr": {}})
                new_params[name + "_v_weight"] = V
                new_params[name + "_h_weight"] = H
                ref_map[idx] = h_idx
            else:  # FullyConnected
                W1, W2 = decompose_fc_weights(W, K)
                K = W1.shape[0]
                w1 = null(name + "_red_weight")
                r_idx = add({"op": "FullyConnected", "name": name + "_red",
                             "param": {"num_hidden": str(K),
                                       "no_bias": "True"},
                             "inputs": [data_ref, [w1, 0]], "attr": {}})
                w2 = null(name + "_rec_weight")
                inputs = [[r_idx, 0], [w2, 0]]
                rparam = {"num_hidden": p["num_hidden"],
                          "no_bias": str(no_bias)}
                if not no_bias:
                    b2 = null(name + "_rec_bias")
                    inputs.append([b2, 0])
                    new_params[name + "_rec_bias"] = bias_val
                rec_idx = add({"op": "FullyConnected", "name": name + "_rec",
                               "param": rparam, "inputs": inputs,
                               "attr": {}})
                new_params[name + "_red_weight"] = W1
                new_params[name + "_rec_weight"] = W2
                ref_map[idx] = rec_idx
        else:
            remapped = dict(node)
            remapped["inputs"] = [[ref_map[i], oi]
                                  for i, oi in node["inputs"]]
            ref_map[idx] = add(remapped)

    for i, oi in graph["heads"]:
        new_heads.append([ref_map[i], oi])
    new_graph = {
        "nodes": new_nodes,
        "arg_nodes": [i for i, n in enumerate(new_nodes)
                      if n["op"] == "null"],
        "heads": new_heads,
    }
    new_sym = mx.sym.load_json(json.dumps(new_graph))
    return new_sym, new_params


def auto_ranks(sym, json_nodes, data_shapes, ratio, only=None):
    """Closed-form rank selection for every conv/FC layer."""
    shape_of = _infer_input_channels(sym, json_nodes, data_shapes)
    ranks = {}
    for node in json_nodes:
        name = node["name"]
        if only and name not in only:
            continue
        in_shape = shape_of.get(name)
        if in_shape is None:
            continue
        if node["op"] == "Convolution":
            p = node["param"]
            ky, kx = ast.literal_eval(p["kernel"])
            if ky == 1 or kx == 1:
                continue  # already cheap in one direction
            if int(p.get("num_group", "1")) != 1:
                continue  # grouped convs not decomposable here
            dil = p.get("dilate")
            if dil and tuple(ast.literal_eval(dil)) != (1, 1):
                continue
            N = int(node["param"]["num_filter"])
            C = in_shape[1]
            ranks[name] = select_rank_conv(C, N, ky, kx, ratio)
        elif node["op"] == "FullyConnected":
            N = int(node["param"]["num_hidden"])
            D = int(np.prod(in_shape[1:]))
            ranks[name] = select_rank_fc(D, N, ratio)
    return ranks


def main(argv=None):
    import mxnet_tpu as mx

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-m", "--model", required=True, help="checkpoint prefix")
    p.add_argument("--epoch", type=int, default=1)
    p.add_argument("--save-model", required=True, help="output prefix")
    p.add_argument("--ratio", type=float, default=2.0,
                   help="target per-layer FLOP reduction")
    p.add_argument("--config", default=None,
                   help="JSON file {layer: K}; skips rank selection")
    p.add_argument("--layers", default=None,
                   help="comma list of layers to decompose (default: all)")
    p.add_argument("--data-shape", default="(1,3,224,224)")
    args = p.parse_args(argv)

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.model, args.epoch)
    json_nodes = json.loads(sym.tojson())["nodes"]
    if args.config:
        with open(args.config) as f:
            ranks = {k: int(v) for k, v in json.load(f).items()}
    else:
        only = set(args.layers.split(",")) if args.layers else None
        shapes = {"data": ast.literal_eval(args.data_shape)}
        ranks = auto_ranks(sym, json_nodes, shapes, args.ratio, only)
        with open(args.save_model + "-ranks.json", "w") as f:
            json.dump(ranks, f, indent=2)
    print("decomposing: %s" % ranks)
    new_sym, new_params = decompose_model(sym, arg_params, ranks)
    new_params = {k: (v if isinstance(v, mx.nd.NDArray) else mx.nd.array(v))
                  for k, v in new_params.items()}
    mx.model.save_checkpoint(args.save_model, 0, new_sym, new_params,
                             aux_params)
    print("saved %s-symbol.json / %s-0000.params"
          % (args.save_model, args.save_model))


if __name__ == "__main__":
    main()
