#!/usr/bin/env python
"""Aggregate a jax profiler trace into a per-op device-time table.

The workflow that found the round-2 BatchNorm win (docs/performance.md):

    MXNET_TPU_BENCH_TRACE=/tmp/t python bench.py
    python tools/trace_top.py /tmp/t            # or the .trace.json.gz

Reads the chrome-trace JSON the profiler writes
(``<dir>/plugins/profile/<run>/*.trace.json.gz``), filters complete
events on device tracks, and prints total ms/step by HLO fusion-name
prefix (``--by-op`` for individual ops). This needs no tensorboard —
the profile plugin's converters are not required.

Reference analogue: the reference had no trace profiler (SURVEY.md §5);
its observability was Monitor + Speedometer + parse_log. This tool is
the TPU-native extension of that family.
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys


def find_trace_file(path: str) -> str:
    if os.path.isfile(path):
        return path
    hits = sorted(glob.glob(os.path.join(
        path, "plugins", "profile", "*", "*.trace.json.gz")))
    if not hits:
        hits = sorted(glob.glob(os.path.join(path, "*.trace.json.gz")))
    if not hits:
        raise SystemExit("no *.trace.json.gz under %s" % path)
    return hits[-1]  # newest run


def load_events(trace_file: str):
    opener = gzip.open if trace_file.endswith(".gz") else open
    with opener(trace_file, "rt") as f:
        return json.load(f)["traceEvents"]


def device_pids(events):
    """pids whose process_name metadata looks like an accelerator."""
    pids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    dev = {p for p, n in pids.items()
           if "TPU" in n or "GPU" in n or "device" in n.lower()}
    # CPU-only traces: fall back to every non-host pid, else all
    if not dev:
        dev = {p for p, n in pids.items() if "host" not in n.lower()} \
            or set(pids)
    return dev, pids


def aggregate(events, steps: int, by_op: bool):
    dev, _ = device_pids(events)
    agg = collections.defaultdict(float)
    count = collections.Counter()
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev:
            continue
        name = e.get("name", "")
        # skip the enclosing program event and bare step-number markers
        if name.startswith("jit_") or re.fullmatch(r"\d+", name):
            continue
        key = name if by_op else re.sub(r"[.\d]+$", "", name)
        dur = e.get("dur", 0.0)
        agg[key] += dur
        count[key] += 1
        total += dur
    rows = [(v / steps / 1e3, 100.0 * v / total if total else 0.0,
             count[k], k) for k, v in agg.items()]
    rows.sort(reverse=True)
    return rows, total / steps / 1e3


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-op device-time table from a jax profiler trace")
    ap.add_argument("trace", help="trace dir or .trace.json.gz file")
    ap.add_argument("--steps", type=int, default=1,
                    help="divide totals by this many steps")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--by-op", action="store_true",
                    help="individual HLO ops instead of name-prefix groups")
    args = ap.parse_args(argv)

    events = load_events(find_trace_file(args.trace))
    rows, total_ms = aggregate(events, args.steps, args.by_op)
    print("device op time: %.2f ms/step over %d steps"
          % (total_ms, args.steps))
    print("%10s %7s %6s  %s" % ("ms/step", "share", "count", "op"))
    for ms, share, n, name in rows[:args.top]:
        print("%10.2f %6.1f%% %6d  %s" % (ms, share, n, name))
    return 0


if __name__ == "__main__":
    sys.exit(main())
