#!/usr/bin/env python
"""Chip watchdog: stop losing TPU tunnel windows (round-3 verdict #1).

The on-chip evidence suite (bench tiers, MFU experiments, op
consistency, e2e input-fed bench) has been armed since round 2 but the
tunnel has been dead whenever a builder or judge was looking. This
watchdog makes the window-catching automatic:

  probe   a killable child executes a tiny jitted computation on the
          default (accelerator) backend — the same probe bench.py uses
          (bench.py:59, a half-alive tunnel answers device enumeration
          but never completes a dispatch, so listing devices is not
          enough)
  fire    the moment the probe passes, run the armed sequence, one
          process at a time (concurrent chip users contend):
            1. bench.py                   -> BENCH_watch.json
                                             + .bench_cache.json
                                             + .bench_trace_summary.json
            2. bench.py e2e input tier    -> appended to BENCH_watch.json
               (MXNET_TPU_BENCH_INPUT=1)
            3. tools/mfu_experiments.py   -> MFU_EXPERIMENTS.jsonl
               (baseline/nhwc/s2d + latency-hiding flag sweep)
            4. tools/tpu_consistency.py   -> TPU_CONSISTENCY.txt
            5. xprof device-time merge    -> XPROF_DEVICE_TIME.json
               (profiler-trace op table x analytic FLOP breakdown)
  commit  git-commit the artifacts so the evidence survives even if the
          tunnel dies again before round end.

Usage:
  python tools/chip_watch.py --once            # single probe+fire
  python tools/chip_watch.py --interval 2700   # loop until killed
Exit codes (--once): 0 = chip answered and suite ran, 3 = tunnel dead.

Reference analogue: the GPU suite ran on every CI box with a GPU
(tests/python/gpu/test_operator_gpu.py); here the chip is intermittent
so the suite must fire itself.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# sibling tools (mfu_experiments.validate) resolve even when this file
# is imported as a module rather than run as a script
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def log(msg):
    sys.stderr.write("[chip_watch %s] %s\n"
                     % (time.strftime("%H:%M:%S"), msg))
    sys.stderr.flush()


def probe(timeout_s=240):
    from bench import _accelerator_reachable

    return _accelerator_reachable(timeout_s)


def _scrub_jsonl(text):
    """Last line of defense for measurement artifacts: drop physically
    impossible rows (mfu_pct > 100, step time below the analytic floor)
    from jsonl-bound stdout. mfu_experiments refuses to print them
    itself, but an older checkout or a hand-run child could still emit
    one — the artifact stays garbage-free either way."""
    from mfu_experiments import validate

    kept = []
    for line in text.splitlines():
        if line.strip():
            try:
                row = json.loads(line)
            except ValueError:
                row = None
            if isinstance(row, dict) and row.get("valid") is not False:
                reason = validate(row)
                if reason:
                    log("DROPPING physically impossible row (%s): %s"
                        % (reason, line.strip()))
                    continue
        kept.append(line)
    return "".join(l + "\n" for l in kept)


def _run(cmd, timeout_s, env_overrides=None, outfile=None,
         keep_output=False):
    """Run one suite stage; never let a hang wedge the watchdog."""
    env = dict(os.environ)
    env.update(env_overrides or {})
    log("run: %s" % " ".join(cmd))
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=timeout_s, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        log("TIMEOUT after %ds: %s" % (timeout_s, cmd))
        if keep_output and e.stdout:
            # the per-case lines completed before the hang are the
            # evidence this watchdog exists to save — marked INCOMPLETE
            # in the artifact itself so a reader can't mistake a
            # truncated sweep for a clean one
            out = e.stdout
            if isinstance(out, bytes):
                out = out.decode("utf-8", "replace")
            return out + ("\n[chip_watch] INCOMPLETE: stage timed out "
                          "after %ds; cases below never ran\n"
                          % timeout_s)
        return None
    if r.stderr:
        sys.stderr.write(r.stderr[-2000:])
    if outfile and r.stdout.strip():
        out = r.stdout
        if outfile.endswith(".jsonl"):
            out = _scrub_jsonl(out)
        if out.strip():
            with open(os.path.join(REPO, outfile), "a") as f:
                f.write(out)
    if r.returncode != 0:
        log("stage failed rc=%d" % r.returncode)
        if keep_output and r.stdout:
            # a partially-failing sweep (e.g. tpu_consistency with one
            # FAIL case, rc=1) is still evidence — per-case PASS/FAIL
            # lines must reach the artifact, not vanish with the rc.
            # Empty stdout (crash before any case) is NOT evidence.
            return r.stdout + ("\n[chip_watch] stage exited rc=%d\n"
                               % r.returncode)
        return None
    return r.stdout


ARTIFACTS = ["BENCH_watch.json", ".bench_cache.json",
             ".bench_trace_summary.json", "MFU_EXPERIMENTS.jsonl",
             "TPU_CONSISTENCY.txt", "TPU_CONSISTENCY_verdict.json",
             "XPROF_DEVICE_TIME.json",
             "MULTICHIP_scaling.json", "SERVE_bench.json",
             "AUTOTUNE_search.json", ".autotune_cache.json",
             "FLEET_bench.json", "FLEET_trace.json",
             "OBS_fleet.json", "NUMWATCH_health.json",
             "BENCH_GATE.json"]


def tpu_consistency_verdict(out, stamp):
    """Distill the sweep's final ``TPU_CONSISTENCY ok=N fail=M`` line
    into a machine-checkable verdict row (TPU_CONSISTENCY_verdict.json)
    so the hardware-truth gate is one jq away instead of a 400-line
    scrape. INCOMPLETE-safe: a sweep that died before the summary (or
    never saw a chip) still writes a row saying exactly that — a stale
    verdict can't pass as this window's."""
    row = {"stamp": stamp}
    summary = None
    for line in (out or "").splitlines():
        if line.startswith("TPU_CONSISTENCY ok="):
            summary = line.strip()
    if summary is not None:
        try:
            parts = dict(p.split("=", 1) for p in summary.split()[1:])
            row["ok"] = int(parts["ok"])
            row["fail"] = int(parts["fail"])
            row["verdict"] = "PASS" if row["fail"] == 0 else "FAIL"
        except (ValueError, KeyError):
            row["incomplete"] = "unparseable summary line: %s" % summary
    elif out and "skipped: no accelerator" in out:
        row["incomplete"] = "skipped: no accelerator in this window"
    else:
        row["incomplete"] = ("sweep died before the summary line "
                             "(timeout/crash); any per-case lines are "
                             "in TPU_CONSISTENCY.txt")
    with open(os.path.join(REPO, "TPU_CONSISTENCY_verdict.json"),
              "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    log("tpu_consistency verdict: %s"
        % (row.get("verdict") or "INCOMPLETE (%s)" % row["incomplete"]))


def xprof_device_time(stamp):
    """Stage 5: merge the profiler-trace device-time summary
    (.bench_trace_summary.json, written by bench.py from its
    jax.profiler.trace window) with the analytic op-category FLOP
    breakdown from the newest BENCH xprof record into one
    XPROF_DEVICE_TIME.json line.  INCOMPLETE-safe: a missing trace
    summary (profiler capture needs the chip) still emits a row with
    the analytic half and an `incomplete` marker, so a CPU run or a
    half-dead window never produces a silently empty artifact."""
    from trace_report import (categorize_op, latest_xprof_record,
                              load_bench_records, _main_site)

    row = {"stamp": stamp}
    ts_path = os.path.join(REPO, ".bench_trace_summary.json")
    if os.path.exists(ts_path):
        try:
            with open(ts_path) as f:
                summary = json.load(f)
            cats = {}
            for op in summary.get("top_ops") or []:
                c = categorize_op(op.get("op", ""))
                cats[c] = cats.get(c, 0.0) + float(
                    op.get("ms_per_step", 0.0))
            row["device_time_by_category"] = {
                c: round(ms, 4) for c, ms in cats.items()}
            row["device_ms_per_step"] = summary.get("device_ms_per_step")
            row["chip"] = summary.get("chip")
        except (ValueError, OSError) as e:
            row["incomplete"] = "trace summary unreadable: %s" % e
    else:
        row["incomplete"] = ("no .bench_trace_summary.json — profiler "
                             "capture did not run (CPU, or the window "
                             "died before the trace stage)")
    bw_path = os.path.join(REPO, "BENCH_watch.json")
    if os.path.exists(bw_path):
        rec = latest_xprof_record(load_bench_records(bw_path))
        if rec is not None:
            site, s = _main_site(rec.get("xprof") or {})
            last = (s.get("last") or {})
            row["analytic_site"] = site
            row["analytic_flops_by_category"] = {
                c: v.get("flops", 0)
                for c, v in (last.get("op_breakdown") or {}).items()}
            row["analytic_mfu"] = rec.get("analytic_mfu")
            row["peak_hbm_bytes"] = rec.get("peak_hbm_bytes")
    with open(os.path.join(REPO, "XPROF_DEVICE_TIME.json"), "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    log("xprof device-time row: %s" % (
        "INCOMPLETE (%s)" % row["incomplete"] if "incomplete" in row
        else "%d categories" % len(row.get("device_time_by_category",
                                           {}))))


def _commit(stage, stamp):
    """Commit whatever artifacts exist RIGHT NOW: a tunnel window can
    die mid-sequence, and evidence from completed stages must survive
    it (a single end-of-sequence commit would lose everything)."""
    present = [a for a in ARTIFACTS
               if os.path.exists(os.path.join(REPO, a))]
    if not present:
        return
    add = subprocess.run(["git", "add", "--"] + present,
                         capture_output=True, text=True, cwd=REPO)
    if add.returncode != 0:        # e.g. index.lock held by another git
        log("add[%s] FAILED rc=%d %s" % (stage, add.returncode,
                                         add.stderr.strip()[-160:]))
        return
    # pathspec'd commit: anything ELSE staged in the shared repo must
    # not be swept into an evidence commit
    r = subprocess.run(
        ["git", "commit", "-m",
         "On-chip evidence: %s (chip_watch %s)" % (stage, stamp),
         "--"] + present,
        capture_output=True, text=True, cwd=REPO)
    log("commit[%s] rc=%d %s" % (stage, r.returncode,
                                 r.stdout.strip()[-160:]))


def fire():
    """Run the armed sequence, committing after every stage."""
    py = sys.executable
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "BENCH_watch.json"), "a") as f:
        f.write('{"chip_watch_fired_at": "%s"}\n' % stamp)

    # 1. headline bench (includes NHWC + CIFAR tiers + trace summary)
    _run([py, os.path.join(REPO, "bench.py")], 3000,
         outfile="BENCH_watch.json")
    _commit("headline bench", stamp)
    # 2. end-to-end recordio-fed tier (synthetic input, real decode path)
    _run([py, os.path.join(REPO, "bench.py")], 3000,
         env_overrides={"MXNET_TPU_BENCH_INPUT": "1"},
         outfile="BENCH_watch.json")
    _commit("e2e input-fed bench", stamp)
    # 2b. cache-fed e2e tier (hardware-truth gate, ROADMAP 5b): decode
    # once into the on-disk uint8 cache, then feed the chip from it —
    # the steady-state input path a resumed production run restarts on.
    # INCOMPLETE contract as below: a wedged/crashed stage writes its
    # own marker record so a stale number can't pass as this window's.
    out = _run([py, os.path.join(REPO, "bench.py")], 3000,
               env_overrides={"MXNET_TPU_BENCH_INPUT": "1",
                              "MXNET_TPU_BENCH_CACHE": "1"},
               outfile="BENCH_watch.json")
    if out is None:
        with open(os.path.join(REPO, "BENCH_watch.json"), "a") as f:
            f.write(json.dumps(
                {"metric": "e2e_cached_imgs_per_sec", "value": 0,
                 "incomplete": "chip_watch e2e_cached stage timed out "
                               "or crashed",
                 "chip_watch_stamp": stamp}, sort_keys=True) + "\n")
    _commit("e2e cache-fed bench", stamp)
    # 3. MFU experiments: all variants, then the latency-hiding flag
    mfu = os.path.join(REPO, "tools", "mfu_experiments.py")
    _run([py, mfu], 4000, outfile="MFU_EXPERIMENTS.jsonl")
    _commit("mfu variants", stamp)
    # paired same-session baseline-vs-flag comparison (the sweep
    # re-runs the variant with and without each flag)
    _run([py, mfu, "--variant", "baseline",
          "--sweep-flags=--xla_tpu_enable_latency_hiding_scheduler=true"],
         4000, outfile="MFU_EXPERIMENTS.jsonl")
    # batch scaling: 512 amortizes per-step overhead if HBM allows
    # (bf16 ResNet-50 activations at 512x224x224 fit a v5e's 16 GB
    # with donation; an OOM here just logs and moves on)
    _run([py, mfu, "--variant", "baseline", "--batch", "512"],
         3000, outfile="MFU_EXPERIMENTS.jsonl")
    _commit("mfu flag sweep + batch scaling", stamp)
    # 4. operator consistency sweep (the hardware-validation tier);
    # keep_output: rc=1 means "ran, some case FAILED" — that per-case
    # evidence is exactly what the artifact is for
    out = _run([py, os.path.join(REPO, "tools", "tpu_consistency.py")],
               3000, keep_output=True)
    with open(os.path.join(REPO, "TPU_CONSISTENCY.txt"), "a") as f:
        if out is not None:
            f.write("== chip_watch %s ==\n%s" % (stamp, out))
        else:
            # crash before any case printed: the artifact still records
            # that THIS window attempted the sweep and got nothing
            f.write("== chip_watch %s ==\n[chip_watch] INCOMPLETE: "
                    "sweep produced no output (crashed before any "
                    "case)\n" % stamp)
    tpu_consistency_verdict(out, stamp)
    _commit("op consistency sweep", stamp)
    # 5. op-category device-time table: profiler trace window merged
    # with the analytic xprof breakdown (INCOMPLETE-safe on its own)
    try:
        xprof_device_time(stamp)
    except Exception as e:                       # noqa: BLE001
        log("xprof device-time stage failed: %s" % e)
    _commit("xprof device-time", stamp)
    # 6. multichip dp-scaling tier (simulated devices, so it runs in
    # any window): sharded fused step measured at dp=1,2,4,8 ->
    # MULTICHIP_scaling.json. bench.py marks the record "incomplete"
    # itself when its child dies; a wedged/timed-out orchestrator gets
    # one written here so a stale record can't pass as this window's
    out = _run([py, os.path.join(REPO, "bench.py"), "multichip"], 2000)
    if out is None:
        with open(os.path.join(REPO, "MULTICHIP_scaling.json"),
                  "w") as f:
            json.dump({"metric": "multichip_imgs_per_sec", "value": 0,
                       "incomplete": "chip_watch multichip stage timed "
                                     "out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("multichip dp scaling", stamp)
    # 6b. FSDP tier (same simulated 8-device mesh, factored
    # dp=2 x fsdp=4): per-device params+opt-state byte ratio, the
    # one-dispatch proof and the exact-parity witness, MERGED under the
    # "fsdp" key of MULTICHIP_scaling.json. On a wedged orchestrator
    # the incomplete record is merged the same way — never clobbering
    # the plain multichip record stage 6 just wrote
    out = _run([py, os.path.join(REPO, "bench.py"), "multichip",
                "--fsdp"], 2000)
    if out is None:
        mc_path = os.path.join(REPO, "MULTICHIP_scaling.json")
        try:
            with open(mc_path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = {}
        rec["fsdp"] = {"metric": "fsdp_param_bytes_ratio", "value": 0,
                       "incomplete": "chip_watch fsdp stage timed out "
                                     "or crashed",
                       "chip_watch_stamp": stamp}
        with open(mc_path, "w") as f:
            json.dump(rec, f, indent=2, sort_keys=True)
            f.write("\n")
    _commit("fsdp sharding tier", stamp)
    # 7. serving tier: continuous-batching goodput sweep against the
    # tail-latency SLO, with the adaptive deadline-aware scheduler and
    # the mixed interactive/batch lane workload -> SERVE_bench.json
    # (occupancy, adaptive-wait trajectory, per-lane goodput). Same
    # INCOMPLETE contract as the multichip stage: bench.py stamps its
    # own record when the child dies; a wedged orchestrator gets one
    # written here.
    out = _run([py, os.path.join(REPO, "bench.py"), "serve",
                "--lanes"], 2000)
    if out is None:
        with open(os.path.join(REPO, "SERVE_bench.json"), "w") as f:
            json.dump({"metric": "serve_goodput_rps", "value": 0,
                       "incomplete": "chip_watch serving stage timed "
                                     "out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("serving goodput sweep", stamp)
    # 7b. tensor-parallel serving tier (same 8-device group factored
    # dp=4 x tp=2): per-device param byte ratio, the preflight
    # bigger-than-one-chip proof, the in-graph collective bucket, and
    # the delta-aware weight-stream record, MERGED under the "tp" key
    # of SERVE_bench.json. On a wedged orchestrator the incomplete
    # record is merged the same way — never clobbering the plain
    # serving record stage 7 just wrote.
    out = _run([py, os.path.join(REPO, "bench.py"), "serve",
                "--tp"], 2000)
    if out is None:
        sv_path = os.path.join(REPO, "SERVE_bench.json")
        try:
            with open(sv_path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = {}
        rec["tp"] = {"metric": "serve_tp_goodput_rps", "value": 0,
                     "incomplete": "chip_watch tp-serving stage timed "
                                   "out or crashed",
                     "chip_watch_stamp": stamp}
        with open(sv_path, "w") as f:
            json.dump(rec, f, indent=2, sort_keys=True)
            f.write("\n")
    _commit("tensor-parallel serving tier", stamp)
    # 8. autotune tier: the closed-loop kernel/config search on the
    # real chip -> AUTOTUNE_search.json + fenced rows appended to
    # MFU_EXPERIMENTS.jsonl + winners into .autotune_cache.json, so the
    # next tuned BENCH record needs no human in the loop. Same
    # INCOMPLETE contract: bench.py stamps its own record when the
    # child dies; a wedged orchestrator gets one written here.
    out = _run([py, os.path.join(REPO, "bench.py"), "autotune"], 2000)
    if out is None:
        with open(os.path.join(REPO, "AUTOTUNE_search.json"), "w") as f:
            json.dump({"metric": "autotune_speedup_vs_default",
                       "value": 0,
                       "incomplete": "chip_watch autotune stage timed "
                                     "out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("autotune search", stamp)
    # 9. fleet tier: fault-tolerant routing over replicas — goodput vs
    # replica count, the killed-replica recovery window, the rolling
    # swap purity proof -> FLEET_bench.json, plus the distributed-trace
    # phase's merged span trees -> FLEET_trace.json. Same INCOMPLETE
    # contract: bench.py stamps its own record when the child dies; a
    # wedged orchestrator gets one written here.
    out = _run([py, os.path.join(REPO, "bench.py"), "fleet"], 2000)
    if out is None:
        with open(os.path.join(REPO, "FLEET_bench.json"), "w") as f:
            json.dump({"metric": "fleet_goodput_rps", "value": 0,
                       "incomplete": "chip_watch fleet stage timed "
                                     "out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    if not os.path.exists(os.path.join(REPO, "FLEET_trace.json")):
        with open(os.path.join(REPO, "FLEET_trace.json"), "w") as f:
            json.dump({"traceEvents": [],
                       "incomplete": "fleet trace phase did not run",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    if not os.path.exists(os.path.join(REPO, "OBS_fleet.json")):
        with open(os.path.join(REPO, "OBS_fleet.json"), "w") as f:
            json.dump({"metric": "obswatch_fleet_goodput_rps",
                       "value": 0,
                       "incomplete": "fleet obswatch phase did not run",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("fleet fault tolerance", stamp)

    # 9b. socket-fleet stage: the fleet bench's socket phase (zero-copy
    # transport + netfeed epoch) rides inside FLEET_bench.json; a
    # record that came back without one (older bench, child died before
    # the phase) gets an INCOMPLETE socket stamp so --view wire and the
    # gate report "didn't run" instead of crashing or silently passing.
    fleet_path = os.path.join(REPO, "FLEET_bench.json")
    try:
        with open(fleet_path) as f:
            fleet_rec = json.load(f)
    except (OSError, ValueError):
        fleet_rec = None
    if isinstance(fleet_rec, dict) and "socket" not in fleet_rec:
        fleet_rec["socket"] = {
            "incomplete": "chip_watch: fleet bench produced no socket "
                          "record"}
        fleet_rec["socket_ok"] = False
        fleet_rec["chip_watch_stamp"] = stamp
        with open(fleet_path, "w") as f:
            json.dump(fleet_rec, f, indent=2, sort_keys=True)
            f.write("\n")
        _commit("socket fleet stamp", stamp)

    # 9c. numerics observability tier: the fused step timed with the
    # numwatch stats pack off vs armed (paired windows), the one-
    # dispatch/one-trace proof, and the per-tensor health table ->
    # NUMWATCH_health.json, which the gate checks against the 3%
    # overhead contract. Same INCOMPLETE contract: bench.py stamps its
    # own record when the child dies; a wedged orchestrator gets one
    # written here.
    out = _run([py, os.path.join(REPO, "bench.py"), "numwatch"], 1200)
    if out is None:
        with open(os.path.join(REPO, "NUMWATCH_health.json"), "w") as f:
            json.dump({"metric": "numwatch_overhead_pct", "value": 0,
                       "incomplete": "chip_watch numerics stage timed "
                                     "out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("numerics observability", stamp)

    # stage 10: the perf-regression gate over everything the window
    # just produced. Same INCOMPLETE contract: bench_gate itself treats
    # a missing/incomplete artifact as INCOMPLETE (exit 0), and if the
    # gate process dies the stamped verdict says so — the window
    # self-reports regressions either way, it never wedges on them.
    out = _run([py, os.path.join(REPO, "tools", "bench_gate.py"),
                "--json"], 600, keep_output=True)
    if out is None or not os.path.exists(
            os.path.join(REPO, "BENCH_GATE.json")):
        with open(os.path.join(REPO, "BENCH_GATE.json"), "w") as f:
            json.dump({"verdict": "incomplete",
                       "incomplete": "chip_watch bench_gate stage "
                                     "timed out or crashed",
                       "chip_watch_stamp": stamp}, f)
            f.write("\n")
    _commit("bench regression gate", stamp)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--once", action="store_true",
                    help="single probe; fire if live; exit")
    ap.add_argument("--interval", type=int, default=2700,
                    help="seconds between probes in loop mode")
    ap.add_argument("--probe-timeout", type=int, default=240)
    args = ap.parse_args(argv)

    while True:
        log("probing accelerator (timeout %ds)" % args.probe_timeout)
        if probe(args.probe_timeout):
            log("CHIP IS LIVE — firing armed suite")
            fire()
            if args.once:
                return 0
            # after a successful drop, keep watching but much less
            # often: the evidence is committed, re-runs only refresh it
            time.sleep(max(args.interval, 4 * 3600))
        else:
            log("tunnel dead")
            if args.once:
                return 3
            time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
