#!/usr/bin/env python
"""Launch a distributed job (reference tools/launch.py over dmlc_tracker).

TPU-native re-design: there is no parameter-server tier — every process is
a worker participating in jax.distributed collectives. The local launcher
forks N worker processes on this machine with the coordinator env set
(reference ``launch.py -n N --launcher local``); for real TPU pods, each
host runs the same command and jax.distributed picks up the topology from
the TPU runtime.
"""
import argparse
import os
import shlex
import signal
import subprocess
import sys
import time

# environment that must travel to remote workers for the job to behave
# like the local one (reference dmlc_tracker forwarded its env list the
# same way, tools/launch.py:32-79 -> dmlc_tracker/ssh.py)
FORWARD_ENV = ["PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS",
               "MXNET_ENGINE_TYPE", "MXNET_COMPUTE_DTYPE",
               "MXNET_BACKWARD_DO_MIRROR", "LD_LIBRARY_PATH",
               "MXTPU_PS_PORT", "MXTPU_PS_SECRET", "MXTPU_PS_INSECURE"]


def job_secret():
    """The PS frame secret for this job: the operator's MXTPU_PS_SECRET
    if set, otherwise a generated one — every launched job runs
    authenticated by default (the server refuses unauthenticated frames
    unless MXTPU_PS_INSECURE=1 is exported explicitly)."""
    if os.environ.get("MXTPU_PS_INSECURE") == "1":
        return os.environ.get("MXTPU_PS_SECRET") or None
    import secrets

    return os.environ.get("MXTPU_PS_SECRET") or secrets.token_hex(32)


def worker_env(args, rank):
    """Rendezvous env for one worker (both launchers use this)."""
    return {
        "MXTPU_COORDINATOR": args.coordinator,
        "MXTPU_NUM_WORKERS": str(args.num_workers),
        "MXTPU_WORKER_RANK": str(rank),
        # reference env names kept for script compat
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_WORKER_ID": str(rank),
    }


def monitor(procs):
    """Failure detection (reference dmlc_tracker behavior): if any
    worker dies abnormally, the survivors would hang in their next
    collective — kill the job and report the failure so a supervisor
    can restart from the last checkpoint."""
    import time

    def _kill(*_):
        for p in procs:
            p.terminate()
    signal.signal(signal.SIGINT, _kill)
    signal.signal(signal.SIGTERM, _kill)
    rc = 0
    pending = list(procs)
    while pending:
        time.sleep(0.2)
        for p in list(pending):
            prc = p.poll()
            if prc is None:
                continue
            pending.remove(p)
            if prc != 0:
                rc = prc
                sys.stderr.write(
                    "launch.py: worker pid %d exited with %d; "
                    "terminating %d remaining worker(s)\n"
                    % (p.pid, prc, len(pending)))
                for q in pending:
                    q.terminate()
                for q in pending:
                    try:
                        q.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        q.kill()
                pending = []
                break
    return rc


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed training job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference-CLI parity; the TPU "
                             "backend has no server tier (collectives "
                             "replace push/pull)")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh"],
                        help="local: fork processes on this machine; "
                             "ssh: one process per hostfile entry "
                             "(round-robin when workers > hosts)")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for ssh launcher (one host per "
                             "line; '#' comments allowed)")
    parser.add_argument("--coordinator", default="127.0.0.1:12421",
                        help="host:port every worker dials for "
                             "jax.distributed rendezvous; with the ssh "
                             "launcher this must be an address the "
                             "remote hosts can reach (i.e. not "
                             "127.0.0.1)")
    parser.add_argument("--ssh-cmd", default="ssh",
                        help="ssh binary (tests substitute a local shim)")
    parser.add_argument("--sync-dir", default=None,
                        help="remote working directory (default: this "
                             "job's cwd, assumed shared e.g. NFS — the "
                             "reference's ssh tracker made the same "
                             "assumption)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    if args.launcher == "local":
        secret = job_secret()
        procs = []
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env.update(worker_env(args, rank))
            if secret:
                # same host, env dict (not argv) — no /proc exposure
                env["MXTPU_PS_SECRET"] = secret
            procs.append(subprocess.Popen(args.command, env=env))
        sys.exit(monitor(procs))
    else:
        if not args.hostfile:
            parser.error("ssh launcher needs --hostfile")
        hosts = [h.split("#", 1)[0].strip() for h in open(args.hostfile)]
        hosts = [h for h in hosts if h]
        if not hosts:
            parser.error("hostfile %s lists no hosts" % args.hostfile)
        cwd = args.sync_dir or os.getcwd()
        # the PS shared secret must NOT ride the ssh command line (argv
        # is world-readable in /proc on every worker host): stage it as
        # a 0600 file in the job dir (shared, e.g. NFS — already this
        # launcher's assumption) and forward only the file's PATH;
        # parallel/ps.py reads MXTPU_PS_SECRET_FILE as a fallback
        secret_file = None
        secret = job_secret()
        if secret:
            # unique per-job filename: two jobs launched from the same
            # shared dir must not clobber each other's secret (a stale
            # read would make every HMAC check fail with no useful error)
            secret_file = os.path.join(
                cwd, ".mxtpu_ps_secret.%d.%d" % (os.getpid(),
                                                 int(time.time())))
            fd = os.open(secret_file,
                         os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(secret)
        procs = []
        for rank in range(args.num_workers):
            host = hosts[rank % len(hosts)]       # round-robin
            env = worker_env(args, rank)
            for k in FORWARD_ENV:                 # propagate local env
                if k == "MXTPU_PS_SECRET":
                    continue                      # staged as a file
                if os.environ.get(k) is not None:
                    env[k] = os.environ[k]
            if secret_file is not None:
                env["MXTPU_PS_SECRET_FILE"] = secret_file
            env_str = " ".join("%s=%s" % (k, shlex.quote(v))
                               for k, v in sorted(env.items()))
            remote = "cd %s && env %s %s" % (
                shlex.quote(cwd), env_str,
                " ".join(shlex.quote(c) for c in args.command))
            # -tt forces a remote pty so terminating the local ssh
            # client HUPs the remote worker too — without it the
            # launcher's kill-the-job-on-failure guarantee would stop
            # at the ssh client and orphan remote workers mid-collective
            procs.append(subprocess.Popen(
                [args.ssh_cmd, "-tt", "-o", "StrictHostKeyChecking=no",
                 host, remote]))
        try:
            rc = monitor(procs)
        finally:
            # the staged secret must not outlive the job: any reader on
            # the shared dir after this point gets the job's HMAC key
            if secret_file is not None:
                try:
                    os.unlink(secret_file)
                except OSError:
                    pass
        sys.exit(rc)


if __name__ == "__main__":
    main()
