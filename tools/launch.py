#!/usr/bin/env python
"""Launch a distributed job (reference tools/launch.py over dmlc_tracker).

TPU-native re-design: there is no parameter-server tier — every process is
a worker participating in jax.distributed collectives. The local launcher
forks N worker processes on this machine with the coordinator env set
(reference ``launch.py -n N --launcher local``); for real TPU pods, each
host runs the same command and jax.distributed picks up the topology from
the TPU runtime.
"""
import argparse
import os
import signal
import subprocess
import sys


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed training job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference-CLI parity; the TPU "
                             "backend has no server tier (collectives "
                             "replace push/pull)")
    parser.add_argument("--launcher", default="local",
                        choices=["local", "ssh"],
                        help="local: fork processes on this machine")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="hostfile for ssh launcher")
    parser.add_argument("--coordinator", default="127.0.0.1:12421")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    if args.launcher == "local":
        procs = []
        for rank in range(args.num_workers):
            env = dict(os.environ)
            env.update({
                "MXTPU_COORDINATOR": args.coordinator,
                "MXTPU_NUM_WORKERS": str(args.num_workers),
                "MXTPU_WORKER_RANK": str(rank),
                # reference env names kept for script compat
                "DMLC_ROLE": "worker",
                "DMLC_NUM_WORKER": str(args.num_workers),
                "DMLC_WORKER_ID": str(rank),
            })
            procs.append(subprocess.Popen(args.command, env=env))

        def _kill(*_):
            for p in procs:
                p.terminate()
        signal.signal(signal.SIGINT, _kill)
        signal.signal(signal.SIGTERM, _kill)
        # failure detection (reference dmlc_tracker behavior): if any
        # worker dies abnormally, the survivors would hang in their next
        # collective — kill the job and report the failure so a
        # supervisor can restart from the last checkpoint
        import time
        rc = 0
        pending = list(procs)
        while pending:
            time.sleep(0.2)
            for p in list(pending):
                prc = p.poll()
                if prc is None:
                    continue
                pending.remove(p)
                if prc != 0:
                    rc = prc
                    sys.stderr.write(
                        "launch.py: worker pid %d exited with %d; "
                        "terminating %d remaining worker(s)\n"
                        % (p.pid, prc, len(pending)))
                    for q in pending:
                        q.terminate()
                    for q in pending:
                        try:
                            q.wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            q.kill()
                    pending = []
                    break
        sys.exit(rc)
    else:
        if not args.hostfile:
            parser.error("ssh launcher needs --hostfile")
        hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
        procs = []
        for rank, host in enumerate(hosts[:args.num_workers]):
            remote_env = ("MXTPU_COORDINATOR=%s MXTPU_NUM_WORKERS=%d "
                          "MXTPU_WORKER_RANK=%d" %
                          (args.coordinator, args.num_workers, rank))
            cmd = ["ssh", host, remote_env + " " + " ".join(args.command)]
            procs.append(subprocess.Popen(cmd))
        rc = 0
        for p in procs:
            rc = p.wait() or rc
        sys.exit(rc)


if __name__ == "__main__":
    main()
