#!/usr/bin/env python
"""TPU-vs-CPU operator consistency sweep.

Reference analogue: ``tests/python/gpu/test_operator_gpu.py`` — the
reference validated its cuDNN/GPU kernels by binding every op on
``mx.gpu(0)`` and comparing against the CPU path via
``check_consistency``. This is the same tier against the real TPU
backend: for each representative op config, bind on ``mx.tpu(0)`` and
``mx.cpu(0)`` and require matching outputs and gradients.

Run directly on a TPU host (`python tools/tpu_consistency.py`); the
test-suite wrapper (`tests/test_tpu_consistency.py`) invokes it in a
subprocess with the accelerator platform enabled and skips when no
accelerator is reachable. Prints one PASS/FAIL line per case and a
final summary line `TPU_CONSISTENCY ok=N fail=M`.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def cases(mx):
    """(name, symbol, shapes, grad_req) — the cuDNN-class ops first."""
    sym = mx.sym
    data = sym.Variable("data")
    out = []
    out.append(("Convolution", sym.Convolution(
        data, kernel=(3, 3), num_filter=8, pad=(1, 1), name="c"),
        {"data": (2, 3, 10, 10)}, "write"))
    out.append(("Deconvolution", sym.Deconvolution(
        data, kernel=(4, 4), stride=(2, 2), pad=(1, 1), num_filter=4,
        name="dc"), {"data": (2, 3, 8, 8)}, "write"))
    out.append(("Pooling_max", sym.Pooling(
        data, kernel=(2, 2), stride=(2, 2), pool_type="max"),
        {"data": (2, 3, 8, 8)}, "write"))
    out.append(("Pooling_avg", sym.Pooling(
        data, kernel=(2, 2), stride=(2, 2), pool_type="avg"),
        {"data": (2, 3, 8, 8)}, "write"))
    out.append(("BatchNorm", sym.BatchNorm(data, name="bn"),
                {"data": (4, 3, 6, 6)}, "write"))
    out.append(("FullyConnected", sym.FullyConnected(
        data, num_hidden=8, name="fc"), {"data": (4, 12)}, "write"))
    out.append(("Activation_tanh", sym.Activation(data, act_type="tanh"),
                {"data": (4, 12)}, "write"))
    out.append(("LeakyReLU", sym.LeakyReLU(data, act_type="leaky"),
                {"data": (4, 12)}, "write"))
    out.append(("SoftmaxActivation", sym.SoftmaxActivation(data),
                {"data": (4, 12)}, "write"))
    out.append(("LRN", sym.LRN(data, nsize=3), {"data": (2, 6, 5, 5)},
                "write"))
    # inference-only: train-mode dropout draws per-executor PRNG keys,
    # so outputs would differ by construction
    out.append(("Dropout_inference", sym.Dropout(data, p=0.5),
                {"data": (4, 12)}, "null"))
    # fused RNN (the cudnn_rnn analogue): multi-arg bind
    from mxnet_tpu.ops.seq import rnn_param_size

    psize = rnn_param_size(1, 6, 5, False, "lstm")
    rnn = sym.RNN(data=data, parameters=sym.Variable("p"),
                  state=sym.Variable("s"), state_cell=sym.Variable("c"),
                  state_size=5, num_layers=1, mode="lstm", name="rnn")
    out.append(("RNN_lstm", rnn,
                {"data": (3, 2, 6), "p": (psize,), "s": (1, 2, 5),
                 "c": (1, 2, 5)}, "write"))
    return out


def run():
    import jax

    # the site hook overrides JAX_PLATFORMS at import; without
    # re-applying it, JAX_PLATFORMS=cpu still initializes the
    # accelerator backend and a dead tunnel hangs jax.devices() forever
    # (same guard as bench.py / pipeline_bench.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import mxnet_tpu as mx
    from mxnet_tpu.test_utils import check_consistency

    platform = jax.devices()[0].platform
    if platform == "cpu":
        print("TPU_CONSISTENCY skipped: no accelerator (platform=cpu)")
        return 2

    import signal

    # per-case watchdog (SIGALRM): catches cases that stall at the
    # Python level or run pathologically slowly. A hang INSIDE one C++
    # dispatch defers the signal until the call returns — that case is
    # covered by chip_watch's process-level timeout, which now salvages
    # the completed PASS/FAIL lines and marks the artifact INCOMPLETE.
    case_timeout = int(os.environ.get("MXTPU_CONSISTENCY_CASE_TIMEOUT",
                                      300))

    class _CaseTimeout(Exception):
        pass

    def _alarm(signum, frame):
        raise _CaseTimeout("case exceeded %ds" % case_timeout)

    has_alarm = hasattr(signal, "SIGALRM")
    if has_alarm:
        signal.signal(signal.SIGALRM, _alarm)

    ok = fail = 0
    for name, sym, shapes, grad_req in cases(mx):
        try:
            if has_alarm:
                signal.alarm(case_timeout)
            check_consistency(sym, [
                dict(ctx=mx.cpu(), **shapes),
                dict(ctx=mx.tpu(0), **shapes),
            ], grad_req=grad_req)
            print("PASS %s" % name)
            ok += 1
        except _CaseTimeout as e:
            print("FAIL %s: TIMEOUT %s" % (name, e))
            fail += 1
        except Exception as e:  # noqa: BLE001 - report and continue
            print("FAIL %s: %s" % (name, str(e)[:200]))
            fail += 1
        finally:
            if has_alarm:
                signal.alarm(0)
        sys.stdout.flush()
    print("TPU_CONSISTENCY ok=%d fail=%d" % (ok, fail))
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(run())
