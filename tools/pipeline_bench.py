"""Input-pipeline throughput benchmark: ImageRecordIter decode+augment
images/sec as a function of preprocess_threads.

The reference decodes recordio with an OMP pool sized by
preprocess_threads (src/io/iter_image_recordio.cc:188-196); this
measures our thread-pool equivalent so the "can the pipeline feed the
chip?" question has a number instead of a guess (round-2 verdict item:
compute side ran 2,504 img/s while decode was single-threaded).

Usage:
  python tools/pipeline_bench.py [--rec PATH] [--threads 1,4,8]
      [--procs 2,4] [--image 224] [--num 512] [--batch 64]
      [--seconds 6] [--augment]

Prints one JSON line per thread count:
  {"metric": "input_pipeline_imgs_per_sec", "value": N, "unit": "img/s",
   "threads": T, "image": S, "augment": bool}
and, with --procs, one per process-worker count (preprocess_mode=
"process": GIL-free decode into the shared-memory batch ring):
  {"metric": "input_pipeline_proc_imgs_per_sec", "value": N,
   "unit": "img/s", "procs": P, "image": S, "augment": bool}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_synthetic_rec(path: str, num: int, image: int, seed: int = 0):
    """Pack `num` photo-like JPEGs (smooth gradients + noise compress the
    way real photos do, unlike pure noise) into a recordio file."""
    from mxnet_tpu import recordio as rio

    rng = np.random.RandomState(seed)
    writer = rio.MXRecordIO(path, "w")
    base = np.linspace(0, 255, image)
    grad = np.add.outer(base, base)[:, :, None] / 2.0
    for i in range(num):
        img = (grad + rng.rand(image, image, 3) * 60.0 +
               rng.rand() * 40.0).clip(0, 255).astype(np.uint8)
        writer.write(rio.pack_img(rio.IRHeader(0, float(i % 10), i, 0),
                                  img, quality=90))
    writer.close()


def measure(rec_path: str, image: int, batch: int, threads: int,
            seconds: float, augment: bool, mode: str = None) -> float:
    from mxnet_tpu import io as mio

    kw = {}
    if augment:
        kw.update(rand_crop=True, rand_mirror=True, max_rotate_angle=10,
                  random_h=10, random_s=10, random_l=10)
    if mode is not None:
        kw["preprocess_mode"] = mode
    it = mio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, image, image),
        batch_size=batch, preprocess_threads=threads,
        scale=1.0 / 255.0, **kw)
    # warm the pool + caches with one batch
    next(iter(it))
    it.reset()
    n = 0
    tic = time.time()
    while time.time() - tic < seconds:
        try:
            b = next(it)
        except StopIteration:
            it.reset()
            continue
        # touch the data so lazy work can't be deferred out of the timing
        _ = b.data[0].asnumpy().ravel()[0]
        n += it.batch_size
    rate = n / (time.time() - tic)
    it.close()
    return rate


def measure_cached(rec_path: str, image: int, batch: int, seconds: float,
                   margin: int = 32, threads: int = 4) -> float:
    """Throughput of the pre-decoded cache path (decode once offline,
    then crop/mirror from a uint8 memmap + fused device normalize —
    round-4 verdict #2: the per-epoch JPEG decode can never feed the
    chip from a few cores)."""
    from mxnet_tpu import io_cache

    prefix = rec_path + ".cache"
    io_cache.build_decoded_cache(
        rec_path, prefix, (3, image + margin, image + margin),
        preprocess_threads=threads)
    it = io_cache.CachedImageRecordIter(
        prefix, (3, image, image), batch, shuffle=True, rand_crop=True,
        rand_mirror=True, scale=1.0 / 255.0)
    next(it)
    it.reset()
    n = 0
    tic = time.time()
    while time.time() - tic < seconds:
        try:
            b = next(it)
        except StopIteration:
            it.reset()
            continue
        _ = b.data[0].asnumpy().ravel()[0]
        n += it.batch_size
    return n / (time.time() - tic)


def main(argv=None):
    # the site hook overrides JAX_PLATFORMS at import; honoring the env
    # var needs an explicit config update AFTER importing jax (same
    # guard as bench.py / conftest.py) — without it a dead accelerator
    # tunnel hangs this host-side decode benchmark on backend init
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms",
                          os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser()
    p.add_argument("--rec", default=None, help="existing .rec (default: synthesize)")
    p.add_argument("--threads", default="1,%d" % max(2, os.cpu_count() or 1))
    p.add_argument("--procs", default="",
                   help="comma-separated process-worker counts to bench "
                        "(preprocess_mode='process')")
    p.add_argument("--image", type=int, default=224)
    p.add_argument("--num", type=int, default=256)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--seconds", type=float, default=6.0)
    p.add_argument("--augment", action="store_true")
    p.add_argument("--cached", action="store_true",
                   help="also measure the pre-decoded cache path")
    args = p.parse_args(argv)

    tmp = None
    rec = args.rec
    if rec is None:
        tmp = tempfile.mkdtemp(prefix="pipe_bench_")
        rec = os.path.join(tmp, "synth.rec")
        make_synthetic_rec(rec, args.num, args.image)
    results = []
    for t in [int(x) for x in str(args.threads).split(",") if x.strip()]:
        rate = measure(rec, args.image, args.batch, t, args.seconds,
                       args.augment)
        line = {"metric": "input_pipeline_imgs_per_sec",
                "value": round(rate, 1), "unit": "img/s", "threads": t,
                "image": args.image, "augment": bool(args.augment)}
        print(json.dumps(line))
        results.append(line)
    for np_ in [int(x) for x in str(args.procs).split(",") if x.strip()]:
        rate = measure(rec, args.image, args.batch, np_, args.seconds,
                       args.augment, mode="process")
        line = {"metric": "input_pipeline_proc_imgs_per_sec",
                "value": round(rate, 1), "unit": "img/s", "procs": np_,
                "image": args.image, "augment": bool(args.augment)}
        print(json.dumps(line))
        results.append(line)
    if args.cached:
        rate = measure_cached(rec, args.image, args.batch, args.seconds)
        line = {"metric": "input_pipeline_cached_imgs_per_sec",
                "value": round(rate, 1), "unit": "img/s",
                "image": args.image, "augment": True}
        print(json.dumps(line))
        results.append(line)
    return results


if __name__ == "__main__":
    main()
