#!/bin/sh
# Nightly gate runner (reference tests/nightly/test_all.sh): the
# convergence / distributed / recovery tiers, then the accelerator
# consistency sweep and the benchmark when a chip answers.
#
# Usage: sh tools/nightly.sh
set -e
cd "$(dirname "$0")/.."

echo "== nightly gates (MNIST convergence, dist_sync 4-proc, recovery) =="
python -m pytest tests/ -m nightly -q

echo "== feed-the-chip absolute gate (dedicated box: strict) =="
MXNET_TPU_STRICT_FEED_GATE=1 python -m pytest \
    tests/test_feed_the_chip.py -q

echo "== dist_sync 2-proc tier (kvstore arithmetic + training) =="
python -m pytest tests/test_dist_kvstore.py -q

echo "== frontend tier (R/Scala/Perl/Matlab must BUILD — skip = fail) =="
# the unit suite tolerates a missing toolchain with pytest.skip; the
# nightly gate does not: green here must mean the four non-Python
# frontends actually compiled and ran against the C ABI
for t in gcc perl; do
    command -v "$t" >/dev/null 2>&1 || {
        echo "nightly: required toolchain '$t' missing — frontend tier cannot certify"; exit 1; }
done
# no pipe: POSIX sh has no pipefail, and `pytest | tee` would let a
# FAILING tier exit 0 through tee's status
python -m pytest tests/test_r_package.py tests/test_scala_package.py \
    tests/test_perl_frontend.py tests/test_matlab_package.py -q -rs \
    > /tmp/nightly_frontend.log 2>&1 || {
    cat /tmp/nightly_frontend.log
    echo "nightly: frontend tests FAILED"
    exit 1
}
cat /tmp/nightly_frontend.log
if grep -E "[0-9]+ skipped" /tmp/nightly_frontend.log >/dev/null; then
    echo "nightly: frontend tests SKIPPED — treating as failure"
    exit 1
fi

echo "== accelerator tier (skips when no chip is reachable) =="
python -m pytest tests/test_tpu_consistency.py -q

echo "== benchmark (falls back to CPU when the chip is unreachable) =="
python bench.py

echo "nightly: all gates green"
