#!/bin/sh
# Nightly gate runner (reference tests/nightly/test_all.sh): the
# convergence / distributed / recovery tiers, then the accelerator
# consistency sweep and the benchmark when a chip answers.
#
# Usage: sh tools/nightly.sh
set -e
cd "$(dirname "$0")/.."

echo "== nightly gates (MNIST convergence, dist_sync 4-proc, recovery) =="
python -m pytest tests/ -m nightly -q

echo "== dist_sync 2-proc tier (kvstore arithmetic + training) =="
python -m pytest tests/test_dist_kvstore.py -q

echo "== accelerator tier (skips when no chip is reachable) =="
python -m pytest tests/test_tpu_consistency.py -q

echo "== benchmark (falls back to CPU when the chip is unreachable) =="
python bench.py

echo "nightly: all gates green"
