#!/usr/bin/env python
"""graftlint CLI: JAX-hazard static analysis over the package.

Usage::

    python tools/graftlint.py mxnet_tpu/                 # lint, exit 1 on findings
    python tools/graftlint.py mxnet_tpu tools bench.py \
        --baseline tools/graftlint_baseline.json          # gate on NEW findings
    python tools/graftlint.py --write-baseline --baseline B.json PATHS
    python tools/graftlint.py --write-env-docs            # regen docs/env_vars.md
    python tools/graftlint.py --check-env-docs            # verify docs in sync

Exit codes: 0 clean, 1 new findings (or docs drift), 2 usage error.
Rule catalog / annotation syntax: docs/static_analysis.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mxnet_tpu.analysis import graftlint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files/dirs to analyze")
    ap.add_argument("--baseline", help="accepted-findings JSON file")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline with the current findings")
    ap.add_argument("--rules", help="comma list of rule ids to run "
                    "(default: all of %s)" % ", ".join(graftlint.RULES))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--write-env-docs", action="store_true",
                    help="regenerate the MXNET_TPU block of "
                    "docs/env_vars.md from mxnet_tpu/env.py")
    ap.add_argument("--check-env-docs", action="store_true",
                    help="fail if docs/env_vars.md is out of sync with "
                    "the env registry")
    args = ap.parse_args(argv)

    if args.write_env_docs or args.check_env_docs:
        from mxnet_tpu import env

        doc_path = os.path.join(_ROOT, "docs", "env_vars.md")
        in_sync = env.sync_docs(doc_path, check=args.check_env_docs)
        if args.check_env_docs and not in_sync:
            print("graftlint: docs/env_vars.md is OUT OF SYNC with "
                  "mxnet_tpu/env.py — run "
                  "`python tools/graftlint.py --write-env-docs`")
            return 1
        if args.write_env_docs and not in_sync:
            print("graftlint: rewrote the generated block of %s"
                  % os.path.relpath(doc_path))
        if not args.paths:
            return 0

    if not args.paths:
        ap.print_usage()
        return 2

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        bad = set(rules) - set(graftlint.RULES)
        if bad:
            print("graftlint: unknown rule(s): %s" % ", ".join(sorted(bad)))
            return 2
    config = graftlint.Config(rules=rules)
    findings = graftlint.analyze_paths(args.paths, config, root=_ROOT)

    baseline = set()
    if args.baseline and os.path.exists(args.baseline) \
            and not args.write_baseline:
        baseline = graftlint.load_baseline(args.baseline)

    if args.write_baseline:
        if not args.baseline:
            print("graftlint: --write-baseline needs --baseline PATH")
            return 2
        graftlint.save_baseline(args.baseline, findings)
        print("graftlint: wrote %d accepted finding(s) to %s"
              % (len(findings), args.baseline))
        return 0

    new, accepted = graftlint.partition(findings, baseline)
    stale = baseline - {f.fingerprint for f in findings}

    if args.json:
        print(json.dumps({"new": [f.to_dict() for f in new],
                          "accepted": [f.to_dict() for f in accepted],
                          "stale_baseline": sorted(stale)}, indent=1))
    else:
        for f in new:
            print("%s:%d: [%s] %s\n    %s"
                  % (f.path, f.line, f.rule, f.message, f.snippet))
        if accepted:
            print("graftlint: %d baselined finding(s) suppressed"
                  % len(accepted))
        if stale:
            print("graftlint: %d stale baseline entr%s (fixed findings "
                  "still in the baseline — rewrite it with "
                  "--write-baseline)"
                  % (len(stale), "y" if len(stale) == 1 else "ies"))
        print("graftlint: %d new finding(s) in %d file(s)"
              % (len(new), len({f.path for f in new})))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
