#!/usr/bin/env python
"""Generate an MNIST-format dataset on disk (idx files) from rendered
digit glyphs with random shift/rotation/scale/noise.

Stands in for the real MNIST download of the reference's nightly gate
(/root/reference/tests/nightly/test_all.sh:56-62 trains LeNet to >=0.99)
in zero-egress environments: the files are byte-compatible idx
(train-images-idx3-ubyte etc.), so MNISTIter and train_mnist.py consume
them exactly like the real dataset.
"""
from __future__ import annotations

import argparse
import os
import struct

import numpy as np


def render_digit(digit: int, rng: np.random.RandomState) -> np.ndarray:
    """One 28x28 uint8 glyph: PIL text, random affine jitter + noise."""
    from PIL import Image, ImageDraw, ImageFont

    canvas = Image.new("L", (28, 28), 0)
    glyph = Image.new("L", (16, 16), 0)
    draw = ImageDraw.Draw(glyph)
    font = ImageFont.load_default()
    draw.text((4, 2), str(digit), fill=255, font=font)
    glyph = glyph.crop(glyph.getbbox())          # tight box around strokes
    size = rng.randint(14, 21)                   # target glyph height
    w = max(6, int(glyph.width * size / glyph.height))
    glyph = glyph.resize((w, size), Image.BILINEAR)
    glyph = glyph.rotate(rng.uniform(-20, 20), resample=Image.BILINEAR,
                         expand=True)
    ox = (28 - glyph.width) // 2 + rng.randint(-3, 4)
    oy = (28 - glyph.height) // 2 + rng.randint(-3, 4)
    canvas.paste(glyph, (max(0, min(ox, 27 - glyph.width)),
                         max(0, min(oy, 27 - glyph.height))))
    img = np.asarray(canvas, dtype=np.float32)
    img += rng.randn(28, 28) * 12.0
    return np.clip(img, 0, 255).astype(np.uint8)


def write_idx_images(path: str, images: np.ndarray) -> None:
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, h, w))
        f.write(images.tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x801, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


def generate(out_dir: str, n_train: int = 8000, n_test: int = 1000,
             seed: int = 0) -> None:
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.RandomState(seed)
    for split, n, img_name, lbl_name in [
            ("train", n_train, "train-images-idx3-ubyte",
             "train-labels-idx1-ubyte"),
            ("test", n_test, "t10k-images-idx3-ubyte",
             "t10k-labels-idx1-ubyte")]:
        labels = rng.randint(0, 10, n)
        images = np.stack([render_digit(int(d), rng) for d in labels])
        write_idx_images(os.path.join(out_dir, img_name), images)
        write_idx_labels(os.path.join(out_dir, lbl_name), labels)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out-dir", default="mnist/")
    p.add_argument("--n-train", type=int, default=8000)
    p.add_argument("--n-test", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    generate(args.out_dir, args.n_train, args.n_test, args.seed)
    print("wrote %d train / %d test to %s"
          % (args.n_train, args.n_test, args.out_dir))


if __name__ == "__main__":
    main()
