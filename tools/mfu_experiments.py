"""Measured MFU experiments for the ResNet-50 training step (round-3
perf item: experiments, not estimates).

Variants, each timed with the same protocol as bench.py (donated
buffers, two warmup steps, block_until_ready fence):

  baseline  NCHW tower (what bench.py measures)
  nhwc      channels-last tower (models.get_resnet50(layout="NHWC")):
            candidates channels onto the TPU lane axis
  s2d       space-to-depth stem: host-free 2x2 depth-to-space reshape of
            the input to (N, 12, H/2, W/2) + a 5x5/1 stem conv replacing
            7x7/2 — structurally the MLPerf trick (measures the
            throughput effect; not weight-exact with the 7x7 stem)
  nhwc_s2d  both together: channels-last tower + s2d stem
  flags:... any variant re-run under an XLA_FLAGS setting (process
            re-exec; flags only apply at backend init)

Usage:
  python tools/mfu_experiments.py                  # all variants
  python tools/mfu_experiments.py --variant nhwc
  python tools/mfu_experiments.py --sweep-flags \
      "--xla_tpu_enable_latency_hiding_scheduler=true" ...

Prints one JSON line per measurement:
  {"experiment": "nhwc", "imgs_per_sec": N, "step_time_ms": N,
   "mfu_pct": N, "chip": "...", "xla_flags": "..."}

Each line is self-contained evidence for docs/performance.md.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNET50_TRAIN_GFLOPS_PER_IMG = 4.089 * 3


def _chip_peak(kind):
    from bench import _chip_peak as peak

    return peak(kind)


def validate(result):
    """Physical-plausibility gate for a measurement row. Returns None
    when the row could be real, else a reason string.

    Two invariants no correct measurement can break: model FLOP
    utilization cannot exceed the chip's peak (mfu_pct <= 100), and a
    ResNet-50 train step cannot finish faster than the analytic floor
    ``batch * 12.267 GFLOP / peak`` — the time the chip would need at
    100% utilization. Rows that break either (the 2026-07-31 pre-fence
    lines: 1.46 ms "steps" for batch-256, mfu 1095%) measured dispatch
    latency, not training."""
    mfu = result.get("mfu_pct")
    if mfu is not None and mfu > 100.0:
        return "mfu_pct %.1f exceeds 100%% of chip peak" % mfu
    batch = result.get("batch")
    step_ms = result.get("step_time_ms")
    image = result.get("image", 0)
    # rows measured through the xprof registry carry the compiled
    # executable's true FLOP count: the tightest possible analytic
    # floor, valid for every variant/geometry (not just 224px ResNet)
    flops = result.get("flops_per_step")
    if step_ms and flops:
        try:
            peak = _chip_peak(result.get("chip", ""))
        except Exception:
            peak = None
        if peak:
            floor_ms = flops / (peak * 1e9)
            if step_ms < floor_ms:
                return ("step_time_ms %.2f below executable FLOP floor "
                        "%.2f ms (%.1f GFLOP/step at %.0f peak TFLOPS)"
                        % (step_ms, floor_ms, flops / 1e9, peak))
    if batch and step_ms and image >= 224:
        try:
            peak = _chip_peak(result.get("chip", ""))
        except Exception:
            peak = None
        if peak:
            floor_ms = batch * RESNET50_TRAIN_GFLOPS_PER_IMG / peak
            if step_ms < floor_ms:
                return ("step_time_ms %.2f below analytic floor %.2f ms "
                        "(batch %d ResNet-50 train at %.0f peak TFLOPS)"
                        % (step_ms, floor_ms, batch, peak))
    return None


def retag(path):
    """Rewrite a results .jsonl, tagging physically impossible rows that
    carry no ``valid`` field with ``"valid": false`` + the reason.
    Already-tagged rows and plausible rows pass through byte-identical.
    Returns the number of rows tagged."""
    out, tagged = [], 0
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except ValueError:
                out.append(line)
                continue
            if isinstance(row, dict) and "valid" not in row:
                reason = validate(row)
                if reason:
                    row["valid"] = False
                    row["invalid_reason"] = reason
                    line = json.dumps(row)
                    tagged += 1
            out.append(line)
    with open(path, "w") as f:
        for line in out:
            f.write(line + "\n")
    return tagged


def build_variant(variant, batch, image, num_classes, small):
    from mxnet_tpu import models

    layout = "NHWC" if variant in ("nhwc", "nhwc_s2d") else "NCHW"
    if variant in ("s2d", "nhwc_s2d"):
        net = models.get_resnet(
            [3, 4, 6, 3], [64, 256, 512, 1024, 2048],
            num_classes=num_classes, small_input=small, stem_s2d=True,
            layout=layout)
        if layout == "NHWC":
            data_shape = (batch, image // 2, image // 2, 12)
        else:
            data_shape = (batch, 12, image // 2, image // 2)
    else:
        net = models.get_resnet50(num_classes=num_classes,
                                  small_input=small, layout=layout)
        if layout == "NHWC":
            data_shape = (batch, image, image, 3)
        else:
            data_shape = (batch, 3, image, image)
    return net, data_shape


def measure(variant, batch, image, num_classes, steps, dtype_name):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import build_sgd_train_step

    small = image <= 64
    net, data_shape = build_variant(variant, batch, image, num_classes,
                                    small)
    arg_shapes, _, aux_shapes = net.infer_shape(data=data_shape)
    rng = np.random.RandomState(0)
    params, data = {}, {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name == "data":
            data[name] = jnp.asarray(rng.rand(*shape), jnp.float32)
        elif name == "softmax_label":
            data[name] = jnp.asarray(
                rng.randint(0, num_classes, shape), jnp.float32)
        elif name.endswith("gamma"):
            params[name] = jnp.ones(shape, jnp.float32)
        else:
            params[name] = jnp.asarray(rng.randn(*shape) * 0.05,
                                       jnp.float32)
    aux = [jnp.ones(s, jnp.float32) if "var" in n
           else jnp.zeros(s, jnp.float32)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]

    compute_dtype = None if dtype_name == "float32" \
        else getattr(jnp, dtype_name)
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.01, compute_dtype=compute_dtype)
    jit_step = jax.jit(step, donate_argnums=(0, 2))
    key = jax.random.PRNGKey(0)

    # AOT-compile through the xprof registry: the row carries the
    # executable's true FLOP count (validate() turns it into the
    # analytic floor) and the measured executable is what we dispatch,
    # so the instrumentation never pays the compile twice
    step_fn = jit_step
    compile_time_s = None
    flops_per_step = None
    try:
        from mxnet_tpu import xprof

        tic_c = time.time()
        compiled = jit_step.lower(params, data, aux, key).compile()
        compile_time_s = time.time() - tic_c
        rec = xprof.record_compile("mfu_experiments.%s" % variant,
                                   compiled, compile_time_s)
        flops_per_step = rec.flops
        step_fn = compiled
    except Exception:
        pass

    def _force(tree):
        # fetch a scalar: block_until_ready alone can under-synchronize
        # through remote-device transports, inflating throughput by
        # orders of magnitude (same fence as bench.py — the 2026-07-31
        # pre-fix numbers in MFU_EXPERIMENTS.jsonl show the failure mode:
        # 1.46 ms "steps" for batch-256 ResNet-50)
        leaf = next(iter(tree.values())) if isinstance(tree, dict) else tree
        return float(np.asarray(leaf.sum()))

    try:
        outputs, params, aux = step_fn(params, data, aux, key)
    except TypeError:
        # the AOT input check is stricter than jit dispatch; fall back
        step_fn = jit_step
        outputs, params, aux = step_fn(params, data, aux, key)
    outputs, params, aux = step_fn(params, data, aux,
                                   jax.random.fold_in(key, 999))
    _force(params)
    tic = time.time()
    for i in range(steps):
        outputs, params, aux = step_fn(params, data, aux,
                                       jax.random.fold_in(key, i))
    _force(params)
    elapsed = time.time() - tic

    dev = jax.devices()[0]
    imgs = batch * steps / elapsed
    result = {
        "experiment": variant,
        "imgs_per_sec": round(imgs, 1),
        "step_time_ms": round(elapsed / steps * 1000, 2),
        "batch": batch,
        "image": image,
        "compute_dtype": dtype_name,
        "chip": getattr(dev, "device_kind", dev.platform),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
        # marks results produced with the scalar-fetch fence; earlier
        # lines without this field under-synchronized and are invalid
        "fence": "scalar_fetch",
    }
    if compile_time_s is not None:
        result["compile_time_s"] = round(compile_time_s, 3)
    if flops_per_step:
        result["flops_per_step"] = flops_per_step
    peak = _chip_peak(getattr(dev, "device_kind", "")) \
        if dev.platform != "cpu" else None
    if peak and image >= 224:
        tflops = imgs * RESNET50_TRAIN_GFLOPS_PER_IMG / 1e3
        result["mfu_pct"] = round(100.0 * tflops / peak, 1)
    if peak and flops_per_step:
        # MFU from the executable's true FLOP count (the analytic
        # number the gap report compares the model-FLOP mfu_pct to)
        result["mfu_pct_xla"] = round(
            100.0 * flops_per_step * steps / elapsed / (peak * 1e12), 1)
    reason = validate(result)
    if reason:
        result["valid"] = False
        result["invalid_reason"] = reason
    return result


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--variant", default="all",
                   choices=["all", "baseline", "nhwc", "s2d",
                            "nhwc_s2d"])
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--image", type=int, default=None)
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--dtype", default=None)
    p.add_argument("--sweep-flags", nargs="*", default=None,
                   help="XLA_FLAGS sweep entries; each entry re-runs "
                        "the chosen variant in a fresh process. Values "
                        "start with '--', which argparse rejects as "
                        "positional — use the '=' form. Commas separate "
                        "INDEPENDENT entries "
                        "(--sweep-flags=--flag1,--flag2 sweeps each "
                        "alone); spaces inside one shell-quoted value "
                        "compose a combined set "
                        "(--sweep-flags='--flag1 --flag2')")
    p.add_argument("--retag", metavar="PATH",
                   help="rewrite an existing results .jsonl, tagging "
                        "physically impossible untagged rows with "
                        "\"valid\": false, then exit")
    p.add_argument("--_child", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.retag:
        n = retag(args.retag)
        sys.stderr.write("mfu_experiments: tagged %d row(s) invalid in %s\n"
                         % (n, args.retag))
        return n

    if args.sweep_flags is not None and not args._child:
        sweep_variants = [args.variant] if args.variant != "all" \
            else ["baseline", "nhwc", "s2d", "nhwc_s2d"]
        # commas separate independent sweep entries; split only on
        # commas that start the NEXT flag — a flag's own value may
        # contain commas (--xla_disable_hlo_passes=a,b)
        flag_sets = [x for f in args.sweep_flags
                     for x in re.split(r",(?=--)", f)]
        for flags in [""] + flag_sets:
            env = dict(os.environ)
            if flags:
                env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " "
                                    + flags).strip()
            for variant in sweep_variants:
                cmd = [sys.executable, os.path.abspath(__file__),
                       "--_child", "--variant", variant]
                for k in ("batch", "image", "steps", "dtype"):
                    v = getattr(args, k)
                    if v is not None:
                        cmd += ["--%s" % k, str(v)]
                r = subprocess.run(cmd, env=env)
                if r.returncode != 0:
                    print(json.dumps({"experiment": variant,
                                      "xla_flags": flags,
                                      "error": "child exited %d"
                                               % r.returncode}))
        return

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    on_accel = jax.devices()[0].platform != "cpu"
    batch = args.batch or (256 if on_accel else 4)
    image = args.image or (224 if on_accel else 32)
    steps = args.steps or (20 if on_accel else 2)
    dtype = args.dtype or ("bfloat16" if on_accel else "float32")
    num_classes = 1000 if on_accel else 8

    variants = [args.variant] if args.variant != "all" \
        else ["baseline", "nhwc", "s2d", "nhwc_s2d"]
    results = []
    for v in variants:
        r = measure(v, batch, image, num_classes, steps, dtype)
        if r.get("valid") is False:
            # stdout is what chip_watch appends to MFU_EXPERIMENTS.jsonl;
            # a physically impossible measurement is evidence of a broken
            # fence, not of performance — refuse to record it
            sys.stderr.write(
                "mfu_experiments: REFUSING to record physically "
                "impossible row (%s): %s\n"
                % (r["invalid_reason"], json.dumps(r)))
        else:
            print(json.dumps(r))
        results.append(r)
    return results


if __name__ == "__main__":
    main()
