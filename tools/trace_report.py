#!/usr/bin/env python
"""Render a step-trace JSONL (StepTrace.dump_jsonl / telemetry
dump_jsonl) or a flight-recorder crash-dump directory into a
human-readable table: the top-k slowest steps with their dominant
delta, plus any anomaly events and crash metadata.

Usage::

    python tools/trace_report.py RUN.jsonl [--top K]
    python tools/trace_report.py /tmp/mxnet_tpu_crash/flight-...-pid123-1
    python tools/trace_report.py --view waterfall <trace_id>

Stdlib only — runs on any box the crash dump was copied to.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

DELTA_COLS = ("io_stall_ms", "prefetch_stall_ms", "h2d_bytes",
              "kv_push_bytes", "kv_pull_bytes", "recompiles",
              "dispatches", "fused_recompiles", "fallbacks",
              "sanitizer_trips")


def load_records(path):
    """Step records from a JSONL file. Accepts both the StepTrace
    schema (latency_ms + deltas) and telemetry.dump_jsonl records
    (step_ms, no deltas); skips unparseable lines (a crash may truncate
    the final one)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "latency_ms" not in rec and "step_ms" in rec:
                rec = dict(rec, latency_ms=rec["step_ms"])
            if "latency_ms" in rec:
                records.append(rec)
    return records


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return "%.0f%s" % (n, unit) if unit == "B" \
                else "%.1f%s" % (n, unit)
        n /= 1024.0


def render(records, top=10):
    """Top-``top`` slowest steps as an aligned text table."""
    if not records:
        return "no step records\n"
    slowest = sorted(records, key=lambda r: -r.get("latency_ms", 0.0))[:top]
    lats = sorted(r["latency_ms"] for r in records)
    header = ("step", "latency_ms", "dominant", "io_stall_ms",
              "prefetch_ms", "h2d", "kv_push", "kv_pull", "recompiles",
              "dispatch", "fused_rc", "fallbacks", "san_trips")
    rows = [header]
    for r in slowest:
        d = r.get("deltas", {})
        rows.append((
            str(r.get("step", "?")),
            "%.2f" % r["latency_ms"],
            str(r.get("dominant", "-")),
            "%.2f" % d.get("io_stall_ms", 0.0),
            "%.2f" % d.get("prefetch_stall_ms", 0.0),
            _fmt_bytes(d.get("h2d_bytes", 0)),
            _fmt_bytes(d.get("kv_push_bytes", 0)),
            _fmt_bytes(d.get("kv_pull_bytes", 0)),
            str(d.get("recompiles", 0)),
            str(d.get("dispatches", 0)),
            str(d.get("fused_recompiles", 0)),
            str(d.get("fallbacks", 0)),
            str(d.get("sanitizer_trips", 0)),
        ))
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    out = ["%d steps, latency p50=%.2fms max=%.2fms; top %d slowest:"
           % (len(records), lats[len(lats) // 2], lats[-1], len(slowest)),
           ""]
    for j, row in enumerate(rows):
        out.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if j == 0:
            out.append("  ".join("-" * w for w in widths))
    return "\n".join(out) + "\n"


def render_events(events):
    if not events:
        return ""
    out = ["", "%d anomaly events:" % len(events)]
    for ev in events:
        detail = ", ".join("%s=%s" % (k, v) for k, v in sorted(ev.items())
                           if k not in ("type", "step", "ts"))
        out.append("  step %-6s %-12s %s"
                   % (ev.get("step", "?"), ev.get("type", "?"), detail))
    return "\n".join(out) + "\n"


def _hist_rows(node, prefix=""):
    """Flatten telemetry snapshot subtree into (name, summary) pairs.
    A name that is both leaf and prefix keeps its own summary under
    ``_value`` (see telemetry.snapshot)."""
    rows = []
    if not isinstance(node, dict):
        return rows
    if "count" in node and not isinstance(node.get("count"), dict):
        return [(prefix or "(all)", node)]
    for k, v in sorted(node.items()):
        name = prefix if k == "_value" else \
            ("%s.%s" % (prefix, k) if prefix else k)
        if k == "_value":
            rows.extend(_hist_rows(v, name or "(all)"))
        else:
            rows.extend(_hist_rows(v, name))
    return rows


def render_locks(telemetry):
    """Lock-contention (``lock.wait_ms`` histograms, fed by the
    `locks` sanitizer's instrumented locks) and ``sanitizer.trips``
    counters from a telemetry snapshot."""
    out = []
    wait = telemetry.get("lock", {}).get("wait_ms")
    rows = [(n, s) for n, s in _hist_rows(wait)
            if s.get("count", 0) > 0]
    if rows:
        out.append("lock contention (lock.wait_ms):")
        header = ("lock", "acquires", "mean_ms", "p50_ms", "p90_ms",
                  "max_ms")
        table = [header]
        for name, s in rows:
            table.append((name, str(s["count"]), "%.3f" % s["mean"],
                          "%.3f" % s["p50"], "%.3f" % s["p90"],
                          "%.3f" % s["max"]))
        widths = [max(len(r[i]) for r in table)
                  for i in range(len(header))]
        for j, r in enumerate(table):
            out.append("  " + "  ".join(c.rjust(w)
                                        for c, w in zip(r, widths)))
            if j == 0:
                out.append("  " + "  ".join("-" * w for w in widths))
    trips = telemetry.get("sanitizer", {}).get("trips")
    if trips is not None:
        if isinstance(trips, dict):
            total = trips.get("_value", 0)
            detail = ", ".join("%s=%s" % (k, v)
                               for k, v in sorted(trips.items())
                               if k != "_value")
            out.append("sanitizer trips: %s%s"
                       % (total, " (%s)" % detail if detail else ""))
        elif trips:
            out.append("sanitizer trips: %s" % trips)
    return "\n".join(out) + "\n" if out else ""


def render_ckpt(telemetry):
    """Preemption-safety counters (``ckpt.*``, fed by
    mxnet_tpu/checkpoint.py) from a telemetry snapshot: snapshot
    saves/bytes/latency, restores, SIGTERM grace saves, and torn files
    skipped at load."""
    ck = telemetry.get("ckpt")
    if not isinstance(ck, dict):
        return ""

    def _n(key):
        v = ck.get(key, 0)
        if isinstance(v, dict):
            v = v.get("_value", 0)
        return v

    counters = ("saves", "bytes", "restores", "preempt_saves",
                "preempt_abandoned", "torn_skipped")
    vals = {k: _n(k) for k in counters}
    if not any(vals.values()):
        return ""
    out = ["checkpoint (ckpt.*):",
           "  " + "  ".join("%s=%s" % (k, vals[k]) for k in counters)]
    rows = [(n, s) for n, s in _hist_rows(ck.get("save_ms"))
            if s.get("count", 0) > 0]
    for _, s in rows:
        out.append("  save_ms: mean=%.1f  p50=%.1f  p90=%.1f  max=%.1f"
                   % (s["mean"], s["p50"], s["p90"], s["max"]))
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# xprof views (compile / ops / memory) over BENCH records
# ---------------------------------------------------------------------------

def load_bench_records(path):
    """Dict records from a BENCH file (bench.py prints one JSON object
    per line; BENCH_watch.json interleaves stage markers — any dict
    line is kept, unparseable lines skipped). Pretty-printed artifacts
    holding one object (SERVE_bench.json) load as a single record."""
    recs = []
    with open(path) as f:
        body = f.read()
    try:
        whole = json.loads(body)
    except ValueError:
        pass
    else:
        if isinstance(whole, dict):
            return [whole]
        if isinstance(whole, list):
            return [r for r in whole if isinstance(r, dict)]
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if isinstance(r, dict):
                recs.append(r)
    return recs


def latest_xprof_record(recs):
    """The newest record carrying an xprof compile-registry summary."""
    for r in reversed(recs):
        if isinstance(r.get("xprof"), dict):
            return r
    return None


def _main_site(xp):
    """(site_name, site_summary) of the executable that owns the step:
    bench.train_step when present, else the site with the most FLOPs."""
    sites = xp.get("sites") or {}
    if "bench.train_step" in sites:
        return "bench.train_step", sites["bench.train_step"]
    best = None
    for name, s in sorted(sites.items()):
        fl = ((s.get("last") or {}).get("flops")) or 0
        if best is None or fl > best[2]:
            best = (name, s, fl)
    return (best[0], best[1]) if best else (None, {})


def _table(rows):
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = []
    for j, r in enumerate(rows):
        out.append("  " + "  ".join(c.rjust(w) for c, w in zip(r, widths)))
        if j == 0:
            out.append("  " + "  ".join("-" * w for w in widths))
    return out


def _strike(s):
    """Strike-through via the unicode combining long stroke: invalid
    rows stay visible in the table (the fence's whole point is that
    bad measurements are shown refuted, not silently dropped)."""
    return "".join(ch + "̶" for ch in s)


def load_tune_rows(path):
    """Autotuner rows from MFU_EXPERIMENTS.jsonl: the lines written by
    mxnet_tpu/autotune.py (``experiment: autotune:<site>:<cand>``).
    Unparseable lines are skipped, same contract as load_records."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("site") \
                        and str(rec.get("experiment",
                                        "")).startswith("autotune:"):
                    rows.append(rec)
    except OSError:
        pass
    return rows


def render_tune(rows):
    """Winners/losers table per autotune site: candidate, config,
    measured step time, analytic MFU, and the status column (BEST /
    prune reason). Rows the validate() gate rejects render
    struck-through with the reason — never dropped."""
    if not rows:
        return ("no autotune rows (run `python bench.py autotune "
                "[--smoke]` to populate MFU_EXPERIMENTS.jsonl)\n")
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from mfu_experiments import validate
    except Exception:   # numpy-less box: trust the stored tags
        def validate(row):
            return None
    out = []
    for site in sorted({r["site"] for r in rows}):
        srows = [r for r in rows if r["site"] == site]
        out.append("site %s (%d candidates)" % (site, len(srows)))
        table = [("candidate", "config", "step_ms", "mfu_pct", "status")]
        for r in srows:
            step = ("%.3f" % r["step_time_ms"]
                    if r.get("step_time_ms") is not None else "-")
            mfu = ("%.2f" % r["analytic_mfu_pct"]
                   if r.get("analytic_mfu_pct") is not None else "-")
            if r.get("pruned"):
                status = "pruned: %s" % r["pruned"]
            elif r.get("best"):
                status = "BEST"
            else:
                status = ""
            cells = (str(r.get("candidate", "?")),
                     json.dumps(r.get("config", {}), sort_keys=True),
                     step, mfu, status)
            reason = validate(r)
            if reason is None and r.get("valid") is False:
                reason = r.get("invalid_reason") or "tagged invalid"
            if reason:
                cells = tuple(_strike(c) for c in cells[:4]) \
                    + ("INVALID: %s" % reason,)
            table.append(cells)
        out.extend(_table(table))
        out.append("")
    return "\n".join(out) + "\n"


def render_bench_summary(rec):
    """The one-line "analytic vs measured MFU, gap attributed to
    <category>" headline for the top of the bench report."""
    xp = rec.get("xprof") or {}
    ana = xp.get("bench_analysis") or {}
    measured = rec.get("mfu_pct")
    analytic = rec.get("analytic_mfu", ana.get("analytic_mfu_pct"))
    _site, s = _main_site(xp)
    bd = ((s.get("last") or {}).get("op_breakdown")) or {}
    bound = ana.get("bound", "unknown")
    # blame the category that owns the executable: the biggest
    # byte-mover when bandwidth-bound, else the biggest FLOP owner
    key = "bytes" if bound == "bandwidth" else "flops"
    total_fl = sum(v.get("flops", 0) for v in bd.values()) or 1
    cat = max(bd, key=lambda c: bd[c].get(key, 0)) if bd else None
    blame = "unattributed (no op breakdown)"
    if cat:
        blame = "%s (%.0f%% of FLOPs, %s-bound)" % (
            cat, 100.0 * bd[cat].get("flops", 0) / total_fl,
            bound if bound != "unknown" else "unknown")
    fmt = lambda v: "%.1f%%" % v if v is not None else "n/a"  # noqa: E731
    gap = ("%.1fpt" % abs(analytic - measured)
           if analytic is not None and measured is not None else "n/a")
    out = ("analytic MFU %s vs measured %s — gap %s, attributed to %s\n"
           % (fmt(analytic), fmt(measured), gap, blame))
    coll = collective_fraction(rec)
    if coll is not None:
        out += ("collective (gradient exchange): %.1f%% of FLOPs, "
                "%.1f%% of bytes moved\n"
                % (100.0 * coll["flop_fraction"],
                   100.0 * coll["byte_fraction"]))
        out += _render_collective_axes(coll)
    return out


# which mesh axis each collective opcode serves: the param
# gather/scatter legs are the fsdp (ZeRO) exchange, the mean-psum
# all-reduce is the dp exchange. -start/-done variants fold onto their
# base opcode.
_AXIS_OPS = (("fsdp", ("all-gather", "reduce-scatter")),
             ("dp", ("all-reduce",)))


def _render_collective_axes(coll):
    """Per-axis breakdown of the collective bytes (``by_op`` sub-
    buckets from the HLO breakdown): 'fsdp: ... via all-gather+
    reduce-scatter / dp: ... via all-reduce'. Empty string when the
    record predates by_op."""
    by_op = coll.get("by_op") or {}
    if not by_op:
        return ""
    total = sum(v.get("bytes", 0) for v in by_op.values()) or 1

    def base(op):
        return op[:-6] if op.endswith("-start") else (
            op[:-5] if op.endswith("-done") else op)

    lines = []
    seen = set()
    for axis, ops in _AXIS_OPS:
        byts = ops_n = 0
        used = []
        for op, v in by_op.items():
            if base(op) in ops:
                seen.add(op)
                byts += v.get("bytes", 0)
                ops_n += v.get("count", 0)
                used.append(base(op))
        if ops_n:
            lines.append("  %s axis: %.1f%% of collective bytes "
                         "(%d op%s: %s)"
                         % (axis, 100.0 * byts / total, ops_n,
                            "s" if ops_n != 1 else "",
                            "+".join(sorted(set(used)))))
    other = {op: v for op, v in by_op.items() if op not in seen}
    if other:
        byts = sum(v.get("bytes", 0) for v in other.values())
        ops_n = sum(v.get("count", 0) for v in other.values())
        lines.append("  other: %.1f%% of collective bytes (%d ops: %s)"
                     % (100.0 * byts / total, ops_n,
                        "+".join(sorted(other))))
    return "\n".join(lines) + "\n" if lines else ""


def collective_fraction(rec):
    """Fraction of the main executable's FLOPs/bytes in the
    ``collective`` HLO category (all-reduce/all-gather/...): the cost of
    the sharded fused step's in-jit gradient exchange. None when no op
    breakdown (or no collective ops) was recorded."""
    xp = rec.get("xprof") or {}
    _site, s = _main_site(xp)
    bd = ((s.get("last") or {}).get("op_breakdown")) or {}
    if not bd or "collective" not in bd:
        # multichip records carry the precomputed fraction directly
        c = rec.get("collective")
        if isinstance(c, dict) and "byte_fraction" in c:
            return {"flop_fraction": c.get("flop_fraction", 0.0),
                    "byte_fraction": c.get("byte_fraction", 0.0),
                    "ops": c.get("ops", 0),
                    "by_op": c.get("by_op") or {}}
        return None
    total_fl = sum(v.get("flops", 0) for v in bd.values())
    total_by = sum(v.get("bytes", 0) for v in bd.values())
    c = bd["collective"]
    return {"flop_fraction": (c.get("flops", 0) / total_fl
                              if total_fl else 0.0),
            "byte_fraction": (c.get("bytes", 0) / total_by
                              if total_by else 0.0),
            "ops": c.get("count", 0),
            "by_op": c.get("by_op") or {}}


def latest_serve_record(recs):
    """The newest serving-bench record (SERVE_bench.json lines carry no
    xprof key, so they need their own selector)."""
    for r in reversed(recs):
        if (r.get("metric") == "serve_goodput_rps"
                or "latency_decomposition_ms" in r):
            return r
    return None


def render_serve(rec):
    """Serving view: the goodput/SLO headline, per-request latency
    decomposition (queue / sched-idle / h2d / dispatch / pad-waste /
    d2h), the adaptive-wait trajectory, the per-lane table, and the
    offered-load sweep table."""
    out = ["serving: %.1f req/s (goodput at %sms SLO: %.1f), "
           "p50 %.2fms  p99 %.2fms  p999 %.2fms"
           % (rec.get("requests_per_sec") or 0,
              ("%g" % rec["slo_ms"]) if rec.get("slo_ms") else "no",
              rec.get("goodput_rps_at_slo") or 0,
              rec.get("p50_ms") or 0, rec.get("p99_ms") or 0,
              rec.get("p999_ms") or 0),
           "buckets %s  dp=%s  mean occupancy %.1f%%  compiles %s  "
           "steady-state retraces %s  dispatches/batch %s"
           % (rec.get("buckets"), rec.get("dp"),
              100.0 * (rec.get("mean_batch_occupancy") or 0.0),
              rec.get("compiles"), rec.get("steady_state_retraces"),
              rec.get("dispatches_per_request_batch"))]
    if rec.get("adaptive") is not None:
        qd = rec.get("queue_depth") or {}
        out.append("adaptive %s  wait %.2fms  queue depth p50 %s  "
                   "p99 %s  max %s"
                   % ("on" if rec.get("adaptive") else "off",
                      rec.get("adaptive_wait_ms") or 0.0,
                      qd.get("p50", "-"), qd.get("p99", "-"),
                      qd.get("max", "-")))
    out.append("")
    dec = rec.get("latency_decomposition_ms") or {}
    if dec:
        order = ("queue_ms", "sched_idle_ms", "h2d_ms", "dispatch_ms",
                 "pad_waste_ms", "d2h_ms", "request_ms")
        rows = [("stage", "mean", "p50", "p99")]
        for k in order:
            h = dec.get(k)
            if not h:
                continue
            rows.append((k[:-3], "%.3f" % (h.get("mean") or 0),
                         "%.3f" % (h.get("p50") or 0),
                         "%.3f" % (h.get("p99") or 0)))
        out.append("per-request latency decomposition (ms):")
        out += _table(rows)
        out.append("")
    tiers = rec.get("tiers") or []
    if tiers:
        rows = [("offered", "achieved", "goodput", "p50_ms", "p99_ms",
                 "p999_ms", "slo")]
        for t in tiers:
            rows.append(("%g" % t.get("offered_rps", 0),
                         "%.1f" % t.get("achieved_rps", 0),
                         "%.1f" % t.get("goodput_rps", 0),
                         "%.2f" % t.get("p50_ms", 0),
                         "%.2f" % t.get("p99_ms", 0),
                         "%.2f" % t.get("p999_ms", 0),
                         "ok" if t.get("slo_ok") else "BREACH"))
        out.append("offered-load sweep (req/s):")
        out += _table(rows)
        out.append("")
    lanes = rec.get("lanes") or {}
    if lanes:
        rows = [("lane", "offered", "goodput", "deadline_ms", "served",
                 "shed", "p50_ms", "p99_ms")]
        for name, ln in sorted(lanes.items()):
            rows.append((name, "%.1f" % (ln.get("offered_rps") or 0),
                         "%.1f" % (ln.get("goodput_rps") or 0),
                         "%g" % (ln.get("deadline_ms") or 0),
                         str(ln.get("served", "-")),
                         str(ln.get("shed", "-")),
                         "%.2f" % (ln.get("p50_ms") or 0),
                         "%.2f" % (ln.get("p99_ms") or 0)))
        out.append("per-lane goodput (mixed workload):")
        out += _table(rows)
        out.append("")
    traj = rec.get("adaptive_wait_trajectory") or []
    if traj:
        # downsample to ~16 rows: enough to see the controller ramp,
        # collapse and recovery without drowning the report
        step = max(1, len(traj) // 16)
        rows = [("t_s", "wait_ms", "depth", "rows", "bucket", "occ",
                 "reason")]
        for p in traj[::step]:
            rows.append(("%.2f" % (p.get("t_s") or 0),
                         "%.2f" % (p.get("wait_ms") or 0),
                         str(p.get("queue_depth", "-")),
                         str(p.get("rows", "-")),
                         str(p.get("bucket", "-")),
                         "%.2f" % (p.get("occupancy") or 0),
                         str(p.get("reason", "-"))))
        out.append("adaptive-wait trajectory (sampled):")
        out += _table(rows)
        out.append("")
    tp = rec.get("tp") or {}
    if tp:
        if tp.get("incomplete"):
            out.append("tensor-parallel serving: INCOMPLETE: %s"
                       % tp["incomplete"])
            out.append("")
        else:
            out.append(
                "tensor-parallel serving (tp=%s dp=%s): %.1f req/s  "
                "p50 %.2fms  p99 %.2fms  param bytes/device %.2fx  "
                "dispatches/batch %s"
                % (tp.get("tp"), tp.get("dp"),
                   tp.get("goodput_rps") or 0, tp.get("p50_ms") or 0,
                   tp.get("p99_ms") or 0,
                   tp.get("param_bytes_ratio") or 0,
                   tp.get("dispatches_per_request_batch")))
            coll = tp.get("collective") or {}
            by_op = coll.get("by_op") or {}
            out.append(
                "in-graph collectives: %s ops, %s bytes (%.1f%% of "
                "HLO bytes)%s"
                % (coll.get("count", 0), coll.get("bytes", 0),
                   100.0 * (tp.get("collective_bytes_fraction") or 0),
                   "  [%s]" % ", ".join(
                       "%s x%d" % (op, v.get("count", 0))
                       for op, v in sorted(by_op.items()))
                   if by_op else ""))
            pf = tp.get("preflight") or {}
            if pf:
                out.append(
                    "preflight vs simulated %s-byte chip: replicated "
                    "pack %s, tp pack fits (headroom %s bytes)"
                    % (pf.get("simulated_limit_bytes"),
                       "REFUSED" if pf.get("replicated_refused")
                       else "fit (?)", pf.get("tp_headroom_bytes")))
            rf = tp.get("refresh") or {}
            if rf:
                out.append(
                    "delta weight stream: full re-pack %s bytes -> "
                    "delta %s bytes (%.1f%% moved; %s changed / %s "
                    "skipped params)"
                    % (rf.get("full_bytes"), rf.get("delta_bytes"),
                       100.0 * (rf.get("delta_bytes_ratio") or 0),
                       rf.get("changed_params"),
                       rf.get("skipped_params")))
            out.append("")
    if rec.get("incomplete"):
        out.append("INCOMPLETE: %s" % rec["incomplete"])
    return "\n".join(out) + "\n"


def latest_fleet_record(recs):
    """The newest fleet-bench record (FLEET_bench.json)."""
    for r in reversed(recs):
        if r.get("metric") == "fleet_goodput_rps" or "chaos" in r:
            return r
    return None


def render_fleet(rec):
    """Fleet view: goodput vs replica count, the killed-replica
    recovery window, and the rolling-swap purity proof."""
    out = ["fleet: %.1f req/s best (%s replicas)  chaos %s  swap %s"
           % (rec.get("value") or 0, rec.get("replicas_best"),
              "OK" if rec.get("chaos_ok") else "FAILED",
              "OK" if rec.get("swap_ok") else "FAILED"), ""]
    scaling = rec.get("scaling") or []
    if scaling:
        rows = [("replicas", "offered", "achieved", "p50_ms", "p99_ms",
                 "errors")]
        for t in scaling:
            rows.append((str(t.get("replicas")),
                         "%g" % t.get("offered_rps", 0),
                         "%.1f" % t.get("achieved_rps", 0),
                         "%.2f" % (t.get("p50_ms") or 0),
                         "%.2f" % (t.get("p99_ms") or 0),
                         str(t.get("errors", 0))))
        out.append("goodput vs replica count:")
        out += _table(rows)
        out.append("")
    c = rec.get("chaos") or {}
    if c:
        out.append("killed-replica window:")
        out.append("  pre-kill %.1f req/s -> min %.1f req/s in window, "
                   "recovered to 90%% in %sms"
                   % (c.get("pre_kill_goodput_rps") or 0,
                      c.get("kill_window_min_goodput_rps") or 0,
                      c.get("recovery_ms")))
        out.append("  client errors %s  crashes %s  respawns %s  "
                   "retries %s  recovered requests %s"
                   % (c.get("client_errors"), c.get("replica_crashes"),
                      c.get("respawns"), c.get("retries"),
                      c.get("recovered_requests")))
        out.append("")
    s = rec.get("swap") or {}
    if s:
        out.append("rolling param swap under load (torn_swap armed):")
        out.append("  %s responses: %s old / %s new / %s MIXED, "
                   "%s failed; %s swaps, torn window injected %sx"
                   % (s.get("responses"), s.get("old_version"),
                      s.get("new_version"), s.get("mixed_version"),
                      s.get("failed"), s.get("swaps"),
                      s.get("torn_injected")))
        out.append("")
    if rec.get("incomplete"):
        out.append("INCOMPLETE: %s" % rec["incomplete"])
    return "\n".join(out) + "\n"


def render_wire(rec):
    """Wire view over a FLEET_bench.json socket record: the
    serialization-vs-pickle headline, the socket-vs-pipe overhead
    claim, a per-peer transport table (frames, bytes, rtt, reconnects,
    backpressure stalls), and the netfeed epoch. INCOMPLETE-safe: a
    record whose socket phase never ran renders its marker instead of
    crashing the report."""
    if rec.get("incomplete"):
        return "wire: INCOMPLETE: %s\n" % rec["incomplete"]
    sock = rec.get("socket")
    if not sock:
        return ("wire: no socket record in this FLEET bench "
                "(run `make net-bench`)\n")
    if sock.get("incomplete"):
        return "wire: INCOMPLETE: %s\n" % sock["incomplete"]
    out = ["wire: %.1f req/s over TCP  p99 %.2fx of pipe  chaos "
           "goodput %s%%  [%s]"
           % (sock.get("goodput_rps") or 0,
              sock.get("overhead_p99_x") or 0,
              round(100 * (sock.get("chaos_goodput_ratio") or 0), 1),
              "OK" if rec.get("socket_ok") else "FAILED"), ""]
    ser = sock.get("serialization") or {}
    if ser:
        out.append("serialization (%.2f MB payload, ms/MB):"
                   % (ser.get("payload_mb") or 0))
        rows = [("codec", "encode", "decode"),
                ("wire frames", "%.4f" % (ser.get("wire_encode_ms_per_mb")
                                          or 0),
                 "%.4f" % (ser.get("wire_decode_ms_per_mb") or 0)),
                ("pickle", "%.4f" % (ser.get("pickle_ms_per_mb") or 0),
                 "%.4f" % (ser.get("unpickle_ms_per_mb") or 0))]
        out += _table(rows)
        out.append("")
    rows = [("peer", "pool", "frames", "MB", "rtt_mean", "rtt_p99",
             "reconnects", "bp_stalls")]
    for phase in ("clean", "chaos"):
        w = (sock.get(phase) or {}).get("wire")
        if not w:
            continue
        rtt = w.get("rtt_ms") or {}
        rows.append(("%s/%s" % (phase, w.get("peer", "?")),
                     str(w.get("pool")),
                     "%d/%d" % (w.get("frames_tx", 0),
                                w.get("frames_rx", 0)),
                     "%.1f" % ((w.get("bytes_tx", 0)
                                + w.get("bytes_rx", 0)) / 1048576.0),
                     "-" if rtt.get("mean") is None
                     else "%.2f" % rtt["mean"],
                     "-" if rtt.get("p99") is None
                     else "%.2f" % rtt["p99"],
                     str(w.get("reconnects", 0)),
                     str(w.get("backpressure_stalls", 0))))
    if len(rows) > 1:
        out.append("per-peer transport (frames tx/rx, rtt in ms):")
        out += _table(rows)
        out.append("")
    for phase in ("pipe", "clean", "chaos"):
        t = sock.get(phase) or {}
        if t:
            out.append("  %-5s %6.1f req/s  p50 %sms  p99 %sms  "
                       "errors %s"
                       % (phase, t.get("achieved_rps") or 0,
                          t.get("p50_ms"), t.get("p99_ms"),
                          t.get("errors")))
    inj = (sock.get("chaos") or {}).get("injected") or {}
    if inj:
        out.append("  chaos injected: %s" % ", ".join(
            "%s x%d" % (k, v) for k, v in sorted(inj.items())))
    out.append("")
    nf = sock.get("netfeed") or {}
    if nf.get("incomplete"):
        out.append("netfeed: INCOMPLETE: %s" % nf["incomplete"])
    elif nf:
        out.append("netfeed epoch (2-process, loopback):")
        out.append("  %s batches, %.1f MB in %.2fs (%.1f MB/s); "
                   "feed stall p50 %sms p99 %sms"
                   % (nf.get("batches"), nf.get("payload_mb") or 0,
                      nf.get("epoch_s") or 0,
                      nf.get("goodput_mb_s") or 0,
                      nf.get("feed_stall_p50_ms"),
                      nf.get("feed_stall_p99_ms")))
    return "\n".join(out) + "\n"


def render_fleet_health(rec):
    """Fleet-health view over an obswatch artifact (OBS_fleet.json):
    the federated rollup table — one row per replica plus the fleet
    row — the federation-agreement numbers, and the SLO burn-rate
    verdict. INCOMPLETE-safe: a stamped-incomplete record renders its
    marker instead of crashing the report."""
    if rec.get("incomplete"):
        return ("fleet-health: INCOMPLETE: %s\n" % rec["incomplete"])
    fed = rec.get("federation") or {}
    rollup = rec.get("final_rollup") or {}
    fleet = rollup.get("fleet") or {}
    burn = rec.get("burn") or {}
    out = ["fleet-health: %s replicas up / %s, %.1f req/s federated "
           "goodput" % (fleet.get("up", "?"),
                        fleet.get("replicas", "?"),
                        fed.get("fed_goodput_rps") or 0), ""]
    rows = [("replica", "status", "state", "breaker", "served",
             "breaches", "in_flight", "p50_ms", "p99_ms")]

    def _ms(v):
        return "-" if v is None else "%.2f" % v

    for rid, r in sorted((rollup.get("replica_rows") or {}).items()):
        rows.append((rid, str(r.get("status")), str(r.get("state")),
                     str(r.get("breaker")), str(r.get("served")),
                     str(r.get("slo_breaches")),
                     "%g" % (r.get("in_flight") or 0),
                     _ms(r.get("p50_ms")), _ms(r.get("p99_ms"))))
    rows.append(("FLEET", "-", "-",
                 "%s open" % fleet.get("breakers_open", 0),
                 str(fleet.get("served")),
                 str(fleet.get("slo_breaches")),
                 "%g" % (fleet.get("in_flight") or 0),
                 _ms(fleet.get("p50_ms")), _ms(fleet.get("p99_ms"))))
    out.append("federated rollup (per-replica scheduler view; FLEET "
               "row = router-view merge):")
    out += _table(rows)
    out.append("")
    if fed:
        out.append("federation agreement vs client-measured:")
        out.append("  goodput %.1f vs %.1f req/s (%.2f%% off)   "
                   "p99 %.2f vs %.2f ms (%.2f%% off)"
                   % (fed.get("fed_goodput_rps") or 0,
                      fed.get("client_goodput_rps") or 0,
                      100 * (fed.get("goodput_rel_err") or 0),
                      fed.get("fed_p99_ms") or 0,
                      fed.get("client_p99_ms") or 0,
                      100 * (fed.get("p99_rel_err") or 0)))
        out.append("")
    if burn:
        if burn.get("alert_fired"):
            out.append("SLO burn: ALERT at +%ss (fast %.2fx / slow "
                       "%.2fx over budget rate), %.0f%% of error "
                       "budget spent at alert"
                       % (burn.get("alert_at_s"),
                          burn.get("fast_burn") or 0,
                          burn.get("slow_burn") or 0,
                          100 * (burn.get("budget_spent_at_alert")
                                 or 0)))
        else:
            out.append("SLO burn: no alert")
        out.append("")
    series = rec.get("series") or {}
    pts = series.get("burn.budget_spent") or []
    if pts:
        out.append("budget burn-down (%d rollups in store):" % len(pts))
        t0 = pts[0][0]
        for ts, v in pts[-8:]:
            out.append("  +%6.2fs  spent %5.1f%%"
                       % (ts - t0, 100 * float(v or 0)))
    return "\n".join(out) + "\n"


def render_health_rows(rows, top=10):
    """The last-K numwatch health rows (a crash dump's numwatch.jsonl):
    the model's numeric trajectory into the failure."""
    if not rows:
        return ""
    out = ["last-%d model-health rows (numwatch fetches):" % min(
        len(rows), top)]
    t = [("step", "loss", "grad_norm", "uw_max", "nonfinite",
          "bad_tensor", "skips", "rollbacks")]

    def _f(v, fmt="%.4g"):
        if v is None:
            return "-"
        try:
            return fmt % v
        except TypeError:
            return str(v)

    for r in rows[-top:]:
        t.append((str(r.get("step", "?")), _f(r.get("loss")),
                  _f(r.get("grad_norm")), _f(r.get("uw_max")),
                  str(r.get("nonfinite", 0)),
                  str(r.get("bad_tensor") or "-"),
                  str(r.get("skips", 0)), str(r.get("rollbacks", 0))))
    out += _table(t)
    return "\n".join(out) + "\n"


def render_numerics(rec):
    """Numerics view over a NUMWATCH_health.json artifact: the per-
    tensor health table (norm / max-abs / nonfinite / zero-frac /
    update-to-weight ratio), the measured stats-on overhead and the
    one-dispatch proof, the guard counters, and the provenance verdict
    when something went nonfinite. INCOMPLETE-safe: a stamped-
    incomplete record renders its marker instead of crashing."""
    if rec.get("incomplete"):
        return "numerics: INCOMPLETE: %s\n" % rec["incomplete"]
    out = ["numerics: stats-on overhead %.2f%% (baseline %.3f ms -> "
           "armed %.3f ms per fused step)"
           % (rec.get("overhead_pct") or 0,
              rec.get("baseline_step_ms") or 0,
              rec.get("armed_step_ms") or 0)]
    out.append("  dispatches/step %.3f   fused_recompiles %s   "
               "overhead gate (<=3%%): %s"
               % (rec.get("dispatches_per_step") or 0,
                  rec.get("fused_recompiles", "?"),
                  "PASS" if rec.get("overhead_ok") else "FAIL"))
    out.append("")
    tensors = rec.get("tensors") or []
    if tensors:
        rows = [("tensor", "grad_l2", "grad_maxabs", "nonfinite",
                 "zero_frac", "uw_ratio")]
        for t in tensors:
            rows.append((str(t.get("name")),
                         "%.4g" % (t.get("grad_l2") or 0),
                         "%.4g" % (t.get("grad_maxabs") or 0),
                         str(t.get("nonfinite", 0)),
                         "%.3f" % (t.get("zero_frac") or 0),
                         "%.3g" % (t.get("uw_ratio") or 0)))
        out.append("per-tensor health (forward order):")
        out += _table(rows)
        out.append("")
    guard = rec.get("guard") or {}
    out.append("guard: %s skipped steps, %s rollbacks"
               % (guard.get("skipped", 0), guard.get("rollbacks", 0)))
    prov = rec.get("provenance")
    if prov:
        out.append("provenance: first bad tensor %s (%s, step %s)"
                   % (prov.get("name"), prov.get("kind"),
                      prov.get("step")))
    health = rec.get("health_rows") or []
    if health:
        out.append("")
        out.append(render_health_rows(health).rstrip())
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# distributed-trace views (dtrace span trees in a merged chrome trace)
# ---------------------------------------------------------------------------

#: the serving tier's exact latency decomposition, in wall order —
#: these five child spans partition their serve.request parent
FIVE_COMPONENTS = ("serve.queue", "serve.sched_idle", "serve.h2d",
                   "serve.dispatch", "serve.d2h")


def load_chrome_trace(path):
    """Event list from a chrome-trace json ({"traceEvents": [...]} or
    a bare list)."""
    with open(path) as f:
        doc = json.load(f)
    evs = doc.get("traceEvents") if isinstance(doc, dict) else doc
    return [e for e in (evs or []) if isinstance(e, dict)]


def dtrace_trees(events):
    """``{trace_id: [span, ...]}`` from the dtrace ``X`` events of a
    merged chrome trace (mxnet_tpu.dtrace.write_chrome_trace output);
    ts/dur stay in the file's microseconds."""
    trees = {}
    for e in events:
        if e.get("ph") != "X" or e.get("cat") != "dtrace":
            continue
        args = e.get("args") or {}
        tid = args.get("trace")
        if not tid:
            continue
        trees.setdefault(tid, []).append({
            "span": args.get("span"),
            "parent": args.get("parent") or "",
            "name": e.get("name"), "pid": e.get("pid"),
            "ts": float(e.get("ts", 0.0)),
            "dur": float(e.get("dur", 0.0)),
            "kept": args.get("kept"),
            "tags": {k: v for k, v in args.items()
                     if k not in ("trace", "span", "parent", "kept")}})
    return trees


def _span_label(s):
    tags = s["tags"]
    bits = []
    for k in ("request_id", "attempt", "replica", "hedge", "won",
              "abandoned", "breaker", "bucket", "occupancy", "compile",
              "slo_breach", "shed", "pad_rows", "error"):
        if k in tags and tags[k] is not None:
            v = tags[k]
            bits.append(k if v is True else "%s=%s" % (k, v))
    return "  [%s]" % ", ".join(bits) if bits else ""


def render_waterfall(trace_id, spans):
    """One kept trace as an indented tree: per-span wall offset from
    the root (ms), duration, owning pid, and the load-bearing tags;
    under each traced serve.request, the five-component decomposition
    line whose parts sum to the request span by construction."""
    by_id = {s["span"]: s for s in spans}
    by_parent = {}
    for s in spans:
        by_parent.setdefault(s["parent"], []).append(s)
    roots = sorted((s for s in spans if s["parent"] not in by_id),
                   key=lambda s: s["ts"])
    if not roots:
        return "trace %s: no spans\n" % trace_id
    t0 = roots[0]["ts"]
    pids = sorted({s["pid"] for s in spans})
    out = ["trace %s  kept=%s  %d spans across %d processes %s"
           % (trace_id, roots[0].get("kept"), len(spans), len(pids),
              pids)]

    def walk(s, depth):
        out.append("  %+9.2fms %s%-22s %9.2fms  pid %-8s%s"
                   % ((s["ts"] - t0) / 1e3, "  " * depth,
                      s["name"], s["dur"] / 1e3, s["pid"],
                      _span_label(s)))
        for c in sorted(by_parent.get(s["span"], ()),
                        key=lambda c: (c["ts"], c["name"])):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    for s in spans:
        if s["name"] != "serve.request":
            continue
        comp = {c["name"]: c["dur"] for c in by_parent.get(s["span"], ())
                if c["name"] in FIVE_COMPONENTS}
        if len(comp) == len(FIVE_COMPONENTS):
            total = sum(comp.values())
            out.append("")
            out.append("  decomposition of serve.request %s (pid %s):"
                       % (s["tags"].get("request_id", "?"), s["pid"]))
            out.append("    " + " + ".join(
                "%s %.2fms" % (n.split(".", 1)[1], comp[n] / 1e3)
                for n in FIVE_COMPONENTS)
                + " = %.2fms (request span %.2fms)"
                % (total / 1e3, s["dur"] / 1e3))
    return "\n".join(out) + "\n"


def _dominant_span(spans):
    """The longest non-root span of a tree — where the time actually
    went (leaf spans preferred: a parent always outlasts its pieces)."""
    parents = {s["parent"] for s in spans}
    leaves = [s for s in spans
              if s["parent"] and s["span"] not in parents]
    pool = leaves or [s for s in spans if s["parent"]] or spans
    return max(pool, key=lambda s: s["dur"])


def render_trace_summary(trees, top=3):
    """Top-``top`` slowest kept traces with their dominant span — the
    profile-report teaser pointing at the full waterfall view."""
    ranked = []
    for tid, spans in trees.items():
        by_id = {s["span"]: s for s in spans}
        roots = [s for s in spans if s["parent"] not in by_id]
        if not roots:
            continue
        root = max(roots, key=lambda s: s["dur"])
        ranked.append((root["dur"], tid, root, spans))
    ranked.sort(key=lambda t: -t[0])
    out = ["%d kept trace(s); top %d slowest:"
           % (len(ranked), min(top, len(ranked)))]
    rows = [("trace", "root_ms", "kept", "spans", "dominant")]
    for dur, tid, root, spans in ranked[:top]:
        dom = _dominant_span(spans)
        rows.append((tid[:16], "%.2f" % (dur / 1e3),
                     str(root.get("kept")), str(len(spans)),
                     "%s (%.2fms, pid %s)"
                     % (dom["name"], dom["dur"] / 1e3, dom["pid"])))
    out += _table(rows)
    out.append("(full tree: python tools/trace_report.py --view "
               "waterfall <trace>)")
    return "\n".join(out) + "\n"


def render_compile(rec):
    """Per-site compile registry table."""
    xp = rec.get("xprof") or {}
    sites = xp.get("sites") or {}
    if not sites:
        return "no xprof compile records\n"
    rows = [("site", "compiles", "total_s", "last_s", "flops",
             "peak_bytes")]
    for name, s in sorted(sites.items()):
        last = s.get("last") or {}
        rows.append((name, str(s.get("compiles", 0)),
                     "%.3f" % s.get("compile_time_s", 0.0),
                     "%.3f" % (last.get("compile_time_s") or 0.0),
                     "%.3g" % (last.get("flops") or 0),
                     _fmt_bytes(last.get("peak_bytes") or 0)))
    out = ["compile registry (%d sites, %d compiles, %.3fs total):"
           % (len(sites), (xp.get("totals") or {}).get("compiles", 0),
              (xp.get("totals") or {}).get("compile_time_s", 0.0)), ""]
    out += _table(rows)
    causes = [(n, (s.get("last") or {}).get("retrace_cause"))
              for n, s in sorted(sites.items())]
    causes = [(n, c) for n, c in causes if c]
    if causes:
        out.append("")
        out.append("retrace causes:")
        out += ["  %s: %s" % (n, c) for n, c in causes]
    return "\n".join(out) + "\n"


def render_ops(rec):
    """Per-category FLOP+bytes breakdown of the main executable; the
    TOTAL row equals the sum of the category rows by construction."""
    xp = rec.get("xprof") or {}
    site, s = _main_site(xp)
    bd = ((s.get("last") or {}).get("op_breakdown")) or {}
    if not bd:
        return "no op-category breakdown recorded\n"
    total_fl = sum(v.get("flops", 0) for v in bd.values())
    total_by = sum(v.get("bytes", 0) for v in bd.values())
    total_n = sum(v.get("count", 0) for v in bd.values())
    rows = [("category", "flops", "share", "bytes", "ops")]
    for cat, v in sorted(bd.items(), key=lambda kv: -kv[1].get("flops", 0)):
        rows.append((cat, str(v.get("flops", 0)),
                     "%.1f%%" % (100.0 * v.get("flops", 0)
                                 / total_fl if total_fl else 0.0),
                     _fmt_bytes(v.get("bytes", 0)),
                     str(v.get("count", 0))))
    rows.append(("TOTAL", str(total_fl), "100.0%",
                 _fmt_bytes(total_by), str(total_n)))
    out = ["op categories for %s:" % site, ""] + _table(rows)
    ana = xp.get("bench_analysis") or {}
    if ana.get("arithmetic_intensity") is not None:
        out.append("")
        out.append("arithmetic intensity %.2f FLOP/B (ridge %s) -> %s"
                   % (ana["arithmetic_intensity"],
                      "%.2f" % ana["ridge_intensity"]
                      if ana.get("ridge_intensity") else "unknown",
                      "%s-bound" % ana.get("bound", "unknown")))
    return "\n".join(out) + "\n"


def render_memory(rec):
    """Per-site memory_analysis table + the HBM watermark/headroom."""
    xp = rec.get("xprof") or {}
    sites = xp.get("sites") or {}
    out = []
    if sites:
        rows = [("site", "arg", "out", "temp", "peak")]
        for name, s in sorted(sites.items()):
            last = s.get("last") or {}
            rows.append((name,
                         _fmt_bytes(last.get("argument_bytes") or 0),
                         _fmt_bytes(last.get("output_bytes") or 0),
                         _fmt_bytes(last.get("temp_bytes") or 0),
                         _fmt_bytes(last.get("peak_bytes") or 0)))
        out += ["memory analysis per executable:", ""] + _table(rows)
    hbm = xp.get("hbm") or {}
    peak = rec.get("peak_hbm_bytes")
    if hbm or peak is not None:
        out.append("")
        out.append("hbm: live %s  run-peak %s  limit %s  headroom %s "
                   "(source: %s)"
                   % (_fmt_bytes(hbm.get("live_bytes") or 0),
                      _fmt_bytes(peak or 0),
                      _fmt_bytes(hbm["limit_bytes"])
                      if hbm.get("limit_bytes") else "n/a",
                      _fmt_bytes(hbm["limit_bytes"]
                                 - (hbm.get("live_bytes") or 0))
                      if hbm.get("limit_bytes") else "n/a",
                      hbm.get("source", "?")))
    return ("\n".join(out) + "\n") if out else "no xprof memory data\n"


def render_bench_report(rec, top=10):
    """Full bench view: the MFU-gap headline first, then compile, ops
    and memory."""
    return "\n".join([render_bench_summary(rec), render_compile(rec),
                      render_ops(rec), render_memory(rec)])


def categorize_op(name):
    """Map a profiler-trace op name (trace_top rows) onto the same
    categories the HLO breakdown uses, so device time and analytic
    FLOPs line up in one table."""
    n = name.lower()
    if "conv" in n:
        return "conv"
    if "dot" in n or "einsum" in n or "matmul" in n:
        return "dot"
    if any(k in n for k in ("all-reduce", "all-gather", "all-to-all",
                            "reduce-scatter", "collective", "permute",
                            "allreduce", "allgather")):
        return "collective"
    if "fusion" in n:
        return "fusion"
    if any(k in n for k in ("transpose", "copy", "reshape", "broadcast",
                            "slice", "concatenate", "pad", "gather",
                            "scatter", "bitcast", "iota")):
        return "transpose"
    if any(k in n for k in ("add", "sub", "mul", "div", "max", "min",
                            "exp", "log", "tanh", "sqrt", "rsqrt",
                            "compare", "select", "convert", "reduce",
                            "rng", "neg", "abs")):
        return "elementwise"
    return "other"


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def profile_report(top=10):
    """`make profile-report`: run the xprof views against the newest
    BENCH / chip_watch artifacts in the repo root."""
    root = _repo_root()
    candidates = [os.path.join(root, "BENCH_watch.json"),
                  os.path.join(root, ".bench_cache.json")]
    import glob

    candidates += sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                         reverse=True)
    out = []
    rec = None
    for path in candidates:
        if not os.path.exists(path):
            continue
        rec = latest_xprof_record(load_bench_records(path))
        if rec is not None:
            out.append("bench artifact: %s\n" % os.path.basename(path))
            break
    if rec is None:
        out.append("no BENCH artifact with an xprof summary found "
                   "(run bench.py, or bench.py --smoke)\n")
    else:
        out.append(render_bench_report(rec, top=top))
    tr_path = os.path.join(root, "FLEET_trace.json")
    if os.path.exists(tr_path):
        try:
            trees = dtrace_trees(load_chrome_trace(tr_path))
        except (OSError, ValueError):
            trees = {}
        if trees:
            out.append("distributed traces (FLEET_trace.json):\n")
            out.append(render_trace_summary(trees, top=3))
    dev = os.path.join(root, "XPROF_DEVICE_TIME.json")
    if os.path.exists(dev):
        rows = load_bench_records(dev)
        if rows:
            last = rows[-1]
            out.append("chip_watch device-time artifact "
                       "(XPROF_DEVICE_TIME.json):\n")
            cats = last.get("device_time_by_category") or {}
            if cats:
                t = [("category", "ms/step", "share")]
                tot = sum(cats.values()) or 1.0
                for c, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
                    t.append((c, "%.2f" % ms, "%.1f%%" % (100 * ms / tot)))
                out.append("\n".join(_table(t)) + "\n")
            if last.get("incomplete"):
                out.append("  INCOMPLETE: %s\n" % last["incomplete"])
    return "\n".join(out)


def report_crash_dump(dump_dir, top=10):
    """Full report for one flight-recorder dump directory."""
    out = []
    meta_path = os.path.join(dump_dir, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        out.append("flight recorder dump: %s" % dump_dir)
        out.append("  reason: %s  pid: %s  rank: %s  steps: %s"
                   % (meta.get("reason"), meta.get("pid"),
                      meta.get("rank"), meta.get("steps_recorded")))
        if meta.get("exception"):
            out.append("  exception:")
            out.extend("    " + l for l in
                       meta["exception"].rstrip().splitlines())
        out.append("")
        events = meta.get("events", [])
    else:
        events = []
    steps_path = os.path.join(dump_dir, "steps.jsonl")
    if os.path.exists(steps_path):
        out.append(render(load_records(steps_path), top=top))
    tel_path = os.path.join(dump_dir, "telemetry.json")
    if os.path.exists(tel_path):
        with open(tel_path) as f:
            tel = json.load(f)
        locks = render_locks(tel)
        if locks:
            out.append(locks)
        ckpt = render_ckpt(tel)
        if ckpt:
            out.append(ckpt)
    nw_path = os.path.join(dump_dir, "numwatch.jsonl")
    if os.path.exists(nw_path):
        health = render_health_rows(load_records(nw_path), top=top)
        if health:
            out.append(health)
    out.append(render_events(events))
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", nargs="?",
                   help="step-trace .jsonl, BENCH .json, crash-dump "
                        "dir, or (--view waterfall) a trace id or "
                        "chrome-trace path (optional with "
                        "--profile-report)")
    p.add_argument("--top", type=int, default=10,
                   help="slowest steps to show (default 10)")
    p.add_argument("--view", default="steps",
                   choices=("steps", "compile", "ops", "memory", "bench",
                            "serve", "fleet", "fleet-health", "wire",
                            "tune", "waterfall", "numerics"),
                   help="steps (default): slowest-step trace table; "
                        "compile/ops/memory/bench: xprof views over a "
                        "BENCH record file; serve: latency decomposition "
                        "+ load sweep over a SERVE_bench.json record; "
                        "fleet: recovery window + swap purity over a "
                        "FLEET_bench.json record; wire: socket-"
                        "transport per-peer table + netfeed epoch over "
                        "a FLEET_bench.json record (path optional); "
                        "fleet-health: "
                        "federated rollup table + burn-rate verdict "
                        "over an obswatch artifact (path optional, "
                        "defaults to OBS_fleet.json); tune: autotuner "
                        "winners/losers per site from "
                        "MFU_EXPERIMENTS.jsonl; waterfall: one kept "
                        "distributed trace as an indented span tree "
                        "(path = trace id, resolved against "
                        "FLEET_trace.json in the repo root, or a "
                        "chrome-trace file); numerics: per-tensor "
                        "model-health table + overhead verdict over a "
                        "NUMWATCH_health.json artifact (path optional)")
    p.add_argument("--profile-report", action="store_true",
                   help="auto-discover the newest BENCH / chip_watch "
                        "artifacts in the repo root and render the "
                        "bench view (used by `make profile-report`)")
    a = p.parse_args(argv)
    if a.profile_report:
        sys.stdout.write(profile_report(top=a.top))
        return 0
    if a.view == "waterfall":
        # positional: a trace id (or unique prefix) resolved against
        # FLEET_trace.json in the repo root, or a chrome-trace path
        # (then the slowest kept tree renders)
        tid, path = a.path, None
        if tid and os.path.exists(tid):
            path, tid = tid, None
        if path is None:
            path = os.path.join(_repo_root(), "FLEET_trace.json")
        if not os.path.exists(path):
            sys.stdout.write("no chrome trace at %s (run `make "
                             "trace-smoke`)\n" % path)
            return 1
        trees = dtrace_trees(load_chrome_trace(path))
        if not trees:
            sys.stdout.write("no dtrace span trees in %s\n" % path)
            return 1
        if tid is not None:
            hits = [t for t in trees if t.startswith(tid)]
            if len(hits) != 1:
                sys.stdout.write(
                    "trace id %r matches %d of %d kept traces in %s\n"
                    % (tid, len(hits), len(trees), path))
                return 1
            tid = hits[0]
        else:
            tid = max(trees, key=lambda t: max(
                s["dur"] for s in trees[t]))
        sys.stdout.write(render_waterfall(tid, trees[tid]))
        return 0
    if a.view == "wire":
        # path optional: defaults to the repo-root fleet bench record
        path = a.path or os.path.join(_repo_root(), "FLEET_bench.json")
        if not os.path.exists(path):
            sys.stdout.write("no fleet bench record at %s (run `make "
                             "net-bench`)\n" % path)
            return 1
        rec = latest_fleet_record(load_bench_records(path))
        if rec is None:
            sys.stdout.write("no fleet record in %s\n" % path)
            return 1
        sys.stdout.write(render_wire(rec))
        return 0
    if a.view == "fleet-health":
        # path optional: defaults to the repo-root obswatch artifact
        path = a.path or os.path.join(_repo_root(), "OBS_fleet.json")
        if not os.path.exists(path):
            sys.stdout.write("no obswatch artifact at %s (run `python "
                             "bench.py fleet --smoke`)\n" % path)
            return 1
        try:
            with open(path) as f:
                rec = json.load(f)
        except ValueError:
            sys.stdout.write("fleet-health: INCOMPLETE: unreadable "
                             "artifact %s\n" % path)
            return 0
        sys.stdout.write(render_fleet_health(rec))
        return 0
    if a.view == "numerics":
        # path optional: defaults to the repo-root numwatch artifact
        path = a.path or os.path.join(_repo_root(), "NUMWATCH_health.json")
        if not os.path.exists(path):
            sys.stdout.write("no numwatch artifact at %s (run `python "
                             "bench.py numwatch`)\n" % path)
            return 1
        try:
            with open(path) as f:
                rec = json.load(f)
        except ValueError:
            sys.stdout.write("numerics: INCOMPLETE: unreadable "
                             "artifact %s\n" % path)
            return 0
        sys.stdout.write(render_numerics(rec))
        return 0
    if a.path is None:
        p.error("path is required unless --profile-report is given")
    if a.view == "tune":
        rows = load_tune_rows(a.path)
        sys.stdout.write(render_tune(rows))
        return 0 if rows else 1
    if a.view == "serve":
        rec = latest_serve_record(load_bench_records(a.path))
        if rec is None:
            sys.stdout.write("no serving record in %s\n" % a.path)
            return 1
        sys.stdout.write(render_serve(rec))
        return 0
    if a.view == "fleet":
        rec = latest_fleet_record(load_bench_records(a.path))
        if rec is None:
            sys.stdout.write("no fleet record in %s\n" % a.path)
            return 1
        sys.stdout.write(render_fleet(rec))
        return 0
    if a.view != "steps":
        rec = latest_xprof_record(load_bench_records(a.path))
        if rec is None:
            sys.stdout.write("no record with an xprof summary in %s\n"
                             % a.path)
            return 1
        fn = {"compile": render_compile, "ops": render_ops,
              "memory": render_memory, "bench": render_bench_report}
        sys.stdout.write(fn[a.view](rec))
        return 0
    if os.path.isdir(a.path):
        sys.stdout.write(report_crash_dump(a.path, top=a.top))
    else:
        sys.stdout.write(render(load_records(a.path), top=a.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
