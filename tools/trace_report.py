#!/usr/bin/env python
"""Render a step-trace JSONL (StepTrace.dump_jsonl / telemetry
dump_jsonl) or a flight-recorder crash-dump directory into a
human-readable table: the top-k slowest steps with their dominant
delta, plus any anomaly events and crash metadata.

Usage::

    python tools/trace_report.py RUN.jsonl [--top K]
    python tools/trace_report.py /tmp/mxnet_tpu_crash/flight-...-pid123-1

Stdlib only — runs on any box the crash dump was copied to.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

DELTA_COLS = ("io_stall_ms", "prefetch_stall_ms", "h2d_bytes",
              "kv_push_bytes", "kv_pull_bytes", "recompiles",
              "dispatches", "fused_recompiles", "sanitizer_trips")


def load_records(path):
    """Step records from a JSONL file. Accepts both the StepTrace
    schema (latency_ms + deltas) and telemetry.dump_jsonl records
    (step_ms, no deltas); skips unparseable lines (a crash may truncate
    the final one)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "latency_ms" not in rec and "step_ms" in rec:
                rec = dict(rec, latency_ms=rec["step_ms"])
            if "latency_ms" in rec:
                records.append(rec)
    return records


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return "%.0f%s" % (n, unit) if unit == "B" \
                else "%.1f%s" % (n, unit)
        n /= 1024.0


def render(records, top=10):
    """Top-``top`` slowest steps as an aligned text table."""
    if not records:
        return "no step records\n"
    slowest = sorted(records, key=lambda r: -r.get("latency_ms", 0.0))[:top]
    lats = sorted(r["latency_ms"] for r in records)
    header = ("step", "latency_ms", "dominant", "io_stall_ms",
              "prefetch_ms", "h2d", "kv_push", "kv_pull", "recompiles",
              "dispatch", "fused_rc", "san_trips")
    rows = [header]
    for r in slowest:
        d = r.get("deltas", {})
        rows.append((
            str(r.get("step", "?")),
            "%.2f" % r["latency_ms"],
            str(r.get("dominant", "-")),
            "%.2f" % d.get("io_stall_ms", 0.0),
            "%.2f" % d.get("prefetch_stall_ms", 0.0),
            _fmt_bytes(d.get("h2d_bytes", 0)),
            _fmt_bytes(d.get("kv_push_bytes", 0)),
            _fmt_bytes(d.get("kv_pull_bytes", 0)),
            str(d.get("recompiles", 0)),
            str(d.get("dispatches", 0)),
            str(d.get("fused_recompiles", 0)),
            str(d.get("sanitizer_trips", 0)),
        ))
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    out = ["%d steps, latency p50=%.2fms max=%.2fms; top %d slowest:"
           % (len(records), lats[len(lats) // 2], lats[-1], len(slowest)),
           ""]
    for j, row in enumerate(rows):
        out.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if j == 0:
            out.append("  ".join("-" * w for w in widths))
    return "\n".join(out) + "\n"


def render_events(events):
    if not events:
        return ""
    out = ["", "%d anomaly events:" % len(events)]
    for ev in events:
        detail = ", ".join("%s=%s" % (k, v) for k, v in sorted(ev.items())
                           if k not in ("type", "step", "ts"))
        out.append("  step %-6s %-12s %s"
                   % (ev.get("step", "?"), ev.get("type", "?"), detail))
    return "\n".join(out) + "\n"


def _hist_rows(node, prefix=""):
    """Flatten telemetry snapshot subtree into (name, summary) pairs.
    A name that is both leaf and prefix keeps its own summary under
    ``_value`` (see telemetry.snapshot)."""
    rows = []
    if not isinstance(node, dict):
        return rows
    if "count" in node and not isinstance(node.get("count"), dict):
        return [(prefix or "(all)", node)]
    for k, v in sorted(node.items()):
        name = prefix if k == "_value" else \
            ("%s.%s" % (prefix, k) if prefix else k)
        if k == "_value":
            rows.extend(_hist_rows(v, name or "(all)"))
        else:
            rows.extend(_hist_rows(v, name))
    return rows


def render_locks(telemetry):
    """Lock-contention (``lock.wait_ms`` histograms, fed by the
    `locks` sanitizer's instrumented locks) and ``sanitizer.trips``
    counters from a telemetry snapshot."""
    out = []
    wait = telemetry.get("lock", {}).get("wait_ms")
    rows = [(n, s) for n, s in _hist_rows(wait)
            if s.get("count", 0) > 0]
    if rows:
        out.append("lock contention (lock.wait_ms):")
        header = ("lock", "acquires", "mean_ms", "p50_ms", "p90_ms",
                  "max_ms")
        table = [header]
        for name, s in rows:
            table.append((name, str(s["count"]), "%.3f" % s["mean"],
                          "%.3f" % s["p50"], "%.3f" % s["p90"],
                          "%.3f" % s["max"]))
        widths = [max(len(r[i]) for r in table)
                  for i in range(len(header))]
        for j, r in enumerate(table):
            out.append("  " + "  ".join(c.rjust(w)
                                        for c, w in zip(r, widths)))
            if j == 0:
                out.append("  " + "  ".join("-" * w for w in widths))
    trips = telemetry.get("sanitizer", {}).get("trips")
    if trips is not None:
        if isinstance(trips, dict):
            total = trips.get("_value", 0)
            detail = ", ".join("%s=%s" % (k, v)
                               for k, v in sorted(trips.items())
                               if k != "_value")
            out.append("sanitizer trips: %s%s"
                       % (total, " (%s)" % detail if detail else ""))
        elif trips:
            out.append("sanitizer trips: %s" % trips)
    return "\n".join(out) + "\n" if out else ""


def report_crash_dump(dump_dir, top=10):
    """Full report for one flight-recorder dump directory."""
    out = []
    meta_path = os.path.join(dump_dir, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        out.append("flight recorder dump: %s" % dump_dir)
        out.append("  reason: %s  pid: %s  rank: %s  steps: %s"
                   % (meta.get("reason"), meta.get("pid"),
                      meta.get("rank"), meta.get("steps_recorded")))
        if meta.get("exception"):
            out.append("  exception:")
            out.extend("    " + l for l in
                       meta["exception"].rstrip().splitlines())
        out.append("")
        events = meta.get("events", [])
    else:
        events = []
    steps_path = os.path.join(dump_dir, "steps.jsonl")
    if os.path.exists(steps_path):
        out.append(render(load_records(steps_path), top=top))
    tel_path = os.path.join(dump_dir, "telemetry.json")
    if os.path.exists(tel_path):
        with open(tel_path) as f:
            locks = render_locks(json.load(f))
        if locks:
            out.append(locks)
    out.append(render_events(events))
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="step-trace .jsonl or crash-dump dir")
    p.add_argument("--top", type=int, default=10,
                   help="slowest steps to show (default 10)")
    a = p.parse_args(argv)
    if os.path.isdir(a.path):
        sys.stdout.write(report_crash_dump(a.path, top=a.top))
    else:
        sys.stdout.write(render(load_records(a.path), top=a.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
