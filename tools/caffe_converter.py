#!/usr/bin/env python
"""Caffe prototxt → mxnet_tpu Symbol converter.

Equivalent of the reference's ``tools/caffe_converter/convert_symbol.py``,
which parsed a text-format ``NetParameter`` via a bundled ``caffe_pb2``
and emitted mx.symbol calls. This version needs no caffe/protobuf at
all: text-format prototxt is a simple recursive ``key { ... }`` /
``key: value`` grammar, parsed here directly.

Supported layers (new-style ``layer {}`` with string types, plus the
old V1 ``layers {}`` enum spellings): Data/Input, Convolution,
Deconvolution, Pooling (MAX/AVE), InnerProduct, ReLU, Sigmoid, TanH,
LRN, Dropout, Concat, Eltwise (SUM/PROD/MAX), Flatten, BatchNorm
(+following Scale folded in), Softmax / SoftmaxWithLoss / Accuracy.

Weight conversion from binary ``.caffemodel`` requires the caffe
protobuf schema and is out of scope (the reference needed caffe_pb2 for
that too); use ``convert_symbol`` + your own weight loading, or retrain.

Usage:
    python tools/caffe_converter.py net.prototxt out_prefix
    # writes out_prefix-symbol.json
"""
from __future__ import annotations

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_TOKEN = re.compile(r"""
    (?P<comment>\#[^\n]*)
  | (?P<brace>[{}])
  | (?P<colon>:)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<atom>[^\s{}:"#]+)
""", re.VERBOSE)


def _tokenize(text):
    for m in _TOKEN.finditer(text):
        kind = m.lastgroup
        if kind == "comment":
            continue
        yield kind, m.group()


def _coerce(tok_kind, tok):
    if tok_kind == "string":
        return tok[1:-1]
    low = tok.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok


def parse_prototxt(text):
    """Parse protobuf text format into a dict; repeated keys become lists."""
    tokens = list(_tokenize(text))
    pos = 0

    def parse_block(pos, end_at_brace):
        msg = {}

        def put(key, value):
            if key in msg:
                cur = msg[key]
                if not isinstance(cur, list):
                    msg[key] = cur = [cur]
                cur.append(value)
            else:
                msg[key] = value

        while pos < len(tokens):
            kind, tok = tokens[pos]
            if kind == "brace" and tok == "}":
                if not end_at_brace:
                    raise ValueError("unexpected '}'")
                return msg, pos + 1
            if kind != "atom":
                raise ValueError("expected field name, got %r" % tok)
            key = tok
            pos += 1
            if pos >= len(tokens):
                raise ValueError("truncated input after field %r" % key)
            kind, tok = tokens[pos]
            if kind == "brace" and tok == "{":
                sub, pos = parse_block(pos + 1, True)
                put(key, sub)
            elif kind == "colon":
                pos += 1
                if pos >= len(tokens):
                    raise ValueError("truncated input after '%s:'" % key)
                kind, tok = tokens[pos]
                if kind == "brace" and tok == "{":  # "key: { ... }" form
                    sub, pos = parse_block(pos + 1, True)
                    put(key, sub)
                else:
                    put(key, _coerce(kind, tok))
                    pos += 1
            else:
                raise ValueError("expected ':' or '{' after %s" % key)
        if end_at_brace:
            raise ValueError("missing '}'")
        return msg, pos

    msg, _ = parse_block(0, False)
    return msg


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def _first(v, default=None):
    vals = _as_list(v)
    return vals[0] if vals else default


def _pair(param, key, default):
    """Caffe geometry fields: scalar, repeated (one per spatial axis), or
    explicit ``kernel_h``/``kernel_w`` (note: *not* ``kernel_size_h``) —
    normalize all three to an (h, w) tuple."""
    v = param.get(key)
    if v is None:
        base = key[:-5] if key.endswith("_size") else key  # kernel_size→kernel
        h = param.get(base + "_h")
        w = param.get(base + "_w")
        if h is not None or w is not None:
            return (int(h if h is not None else default),
                    int(w if w is not None else default))
        return (default, default)
    if isinstance(v, list):
        if len(v) >= 2:
            return (int(v[0]), int(v[1]))
        v = v[0]
    return (int(v), int(v))


_V1_TYPES = {  # old enum spellings → new string types
    "CONVOLUTION": "Convolution", "DECONVOLUTION": "Deconvolution",
    "POOLING": "Pooling", "INNER_PRODUCT": "InnerProduct",
    "RELU": "ReLU", "SIGMOID": "Sigmoid", "TANH": "TanH", "LRN": "LRN",
    "DROPOUT": "Dropout", "CONCAT": "Concat", "ELTWISE": "Eltwise",
    "FLATTEN": "Flatten", "SOFTMAX": "Softmax",
    "SOFTMAX_LOSS": "SoftmaxWithLoss", "DATA": "Data", "ACCURACY": "Accuracy",
    "BATCHNORM": "BatchNorm", "SCALE": "Scale",
}


def convert_symbol(prototxt, input_name="data"):
    """Convert prototxt text/path → (Symbol, input_shape or None).

    Mirrors reference ``convert_symbol.py:proto2script`` semantics: walks
    layers in order, keeps a bottom-name → symbol mapping, returns the
    last top. A trailing SoftmaxWithLoss/Softmax becomes SoftmaxOutput
    (reference emitted ``mx.symbol.SoftmaxOutput``).
    """
    import mxnet_tpu as mx

    if os.path.exists(prototxt):
        with open(prototxt) as f:
            text = f.read()
    else:
        text = prototxt
    net = parse_prototxt(text)
    layers = _as_list(net.get("layer")) + _as_list(net.get("layers"))

    mapping = {}
    input_shape = None
    if "input" in net:
        name = _first(net["input"], input_name)
        mapping[name] = mx.sym.Variable(name)
        dims = net.get("input_dim")
        if dims is None and isinstance(net.get("input_shape"), dict):
            dims = net["input_shape"].get("dim")
        if dims is not None:
            input_shape = tuple(int(d) for d in _as_list(dims))
    last = None

    for layer in layers:
        ltype = str(layer.get("type", ""))
        ltype = _V1_TYPES.get(ltype, ltype)
        name = str(layer.get("name", ltype))
        bottoms = [str(b) for b in _as_list(layer.get("bottom"))]
        tops = [str(t) for t in _as_list(layer.get("top"))] or [name]
        # skip test-phase-only layers
        include = layer.get("include")
        if isinstance(include, dict) and include.get("phase") == "TEST":
            continue
        ins = [mapping[b] for b in bottoms if b in mapping]

        if ltype in ("Data", "Input", "ImageData", "HDF5Data", "MemoryData"):
            var = mx.sym.Variable(input_name)
            for t in tops:
                mapping[t] = var
            if ip := layer.get("input_param"):
                shape = ip.get("shape")
                if isinstance(shape, dict):
                    input_shape = tuple(
                        int(d) for d in _as_list(shape.get("dim")))
            continue
        if not ins and ltype not in ("Accuracy",):
            # bottom not produced (e.g. label-only path): make a variable
            ins = [mx.sym.Variable(b) for b in bottoms]
        x = ins[0] if ins else None

        if ltype == "Convolution" or ltype == "Deconvolution":
            p = layer.get("convolution_param", {})
            kernel = _pair(p, "kernel_size", 1)
            op = mx.sym.Convolution if ltype == "Convolution" \
                else mx.sym.Deconvolution
            kw = dict(num_filter=int(_first(p.get("num_output"), 1)),
                      kernel=kernel, stride=_pair(p, "stride", 1),
                      pad=_pair(p, "pad", 0),
                      no_bias=not p.get("bias_term", True), name=name)
            group = int(_first(p.get("group"), 1))
            if group != 1 and ltype == "Convolution":
                kw["num_group"] = group
            dil = p.get("dilation")
            if dil is not None and ltype == "Convolution":
                d = int(_first(dil))
                if d > 1:
                    kw["dilate"] = (d, d)
            out = op(data=x, **kw)
        elif ltype == "Pooling":
            p = layer.get("pooling_param", {})
            raw_pool = p.get("pool", "MAX")
            pool = {0: "max", 1: "avg", "MAX": "max",
                    "AVE": "avg"}.get(raw_pool)
            if pool is None:  # 2/STOCHASTIC has no equivalent here
                raise ValueError("unsupported pool type %r (layer %s)"
                                 % (raw_pool, name))
            if p.get("global_pooling"):
                out = mx.sym.Pooling(data=x, kernel=(1, 1), pool_type=pool,
                                     global_pool=True, name=name)
            else:
                # caffe pools with ceil ("full") convention; pad covers the
                # common nets since kernel/stride normally divide evenly
                out = mx.sym.Pooling(
                    data=x, kernel=_pair(p, "kernel_size", 2),
                    stride=_pair(p, "stride", 1), pad=_pair(p, "pad", 0),
                    pool_type=pool, name=name)
        elif ltype == "InnerProduct":
            p = layer.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                data=mx.sym.Flatten(data=x),
                num_hidden=int(_first(p.get("num_output"), 1)),
                no_bias=not p.get("bias_term", True), name=name)
        elif ltype == "ReLU":
            out = mx.sym.Activation(data=x, act_type="relu", name=name)
        elif ltype == "Sigmoid":
            out = mx.sym.Activation(data=x, act_type="sigmoid", name=name)
        elif ltype == "TanH":
            out = mx.sym.Activation(data=x, act_type="tanh", name=name)
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = mx.sym.LRN(data=x, alpha=float(p.get("alpha", 1e-4)),
                             beta=float(p.get("beta", 0.75)),
                             knorm=float(p.get("k", 1.0)),
                             nsize=int(p.get("local_size", 5)), name=name)
        elif ltype == "Dropout":
            p = layer.get("dropout_param", {})
            out = mx.sym.Dropout(data=x,
                                 p=float(p.get("dropout_ratio", 0.5)),
                                 name=name)
        elif ltype == "Concat":
            p = layer.get("concat_param", {})
            out = mx.sym.Concat(*ins, dim=int(p.get("axis", 1)), name=name)
        elif ltype == "Eltwise":
            p = layer.get("eltwise_param", {})
            op_name = p.get("operation", "SUM")
            if op_name in ("SUM", 1):
                coeff = [float(c) for c in _as_list(p.get("coeff"))]
                if coeff and len(coeff) != len(ins):
                    raise ValueError(
                        "Eltwise %s: %d coeffs for %d bottoms"
                        % (name, len(coeff), len(ins)))
                terms = [s if not coeff or coeff[i] == 1.0 else s * coeff[i]
                         for i, s in enumerate(ins)]
                out = terms[0]
                for other in terms[1:]:
                    out = out + other
            elif op_name in ("PROD", 0):
                out = ins[0]
                for other in ins[1:]:
                    out = out * other
            else:  # MAX
                out = ins[0]
                for other in ins[1:]:
                    out = mx.sym._Maximum(out, other)
        elif ltype == "Flatten":
            out = mx.sym.Flatten(data=x, name=name)
        elif ltype == "BatchNorm":
            p = layer.get("batch_norm_param", {})
            out = mx.sym.BatchNorm(
                data=x, eps=float(p.get("eps", 1e-5)),
                momentum=float(p.get("moving_average_fraction", 0.9)),
                fix_gamma=False, name=name)
        elif ltype == "Scale":
            # caffe BatchNorm has no affine params; the following Scale
            # layer supplies them — our BatchNorm already has gamma/beta,
            # so Scale folds away (reference converter did the same).
            out = x
        elif ltype in ("Softmax", "SoftmaxWithLoss"):
            label = None
            if len(bottoms) > 1:
                label = mapping.get(bottoms[1],
                                    mx.sym.Variable(bottoms[1]))
            kw = {"name": name if name else "softmax"}
            if label is not None:
                kw["label"] = label
            out = mx.sym.SoftmaxOutput(data=x, **kw)
        elif ltype == "Accuracy":
            continue
        else:
            raise ValueError("unsupported caffe layer type %r (layer %s)"
                             % (ltype, name))
        for t in tops:
            mapping[t] = out
        last = out

    if last is None:
        raise ValueError("no layers converted")
    return last, input_shape


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("prototxt", help="path to .prototxt")
    p.add_argument("out_prefix", help="writes <out_prefix>-symbol.json")
    args = p.parse_args(argv)
    sym, input_shape = convert_symbol(args.prototxt)
    out = args.out_prefix + "-symbol.json"
    sym.save(out)
    print("saved %s" % out)
    if input_shape:
        print("input shape: %s" % (input_shape,))
    return out


if __name__ == "__main__":
    main()
