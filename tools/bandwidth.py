#!/usr/bin/env python
"""Communication bandwidth benchmark.

TPU-native equivalent of the reference's ``tools/bandwidth/measure.py``,
which timed KVStore push+pull of model-sized gradient arrays across
devices and reported per-GPU "bus bandwidth". Here the measured
primitive is what actually moves bytes on TPU:

* ``--test allreduce`` — a fused ``jax.lax.psum`` over every device on
  the mesh (what data-parallel training lowers to on ICI).
* ``--test kvstore``  — KVStore push (reduce) + pull (broadcast) through
  the explicit API, matching the reference's measurement shape.

Bus bandwidth follows the reference's convention: each all-reduce of
``S`` bytes over ``n`` devices moves ``2 * S * (n - 1) / n`` bytes per
device (reduce-scatter + all-gather), so

    bus_bw = 2 * S * (n - 1) / n / time / device.

Run on one chip it degrades to a copy benchmark; run under a virtual CPU
mesh (``XLA_FLAGS=--xla_force_host_platform_device_count=8``) it
validates the collective path end to end.

Usage:
    python tools/bandwidth.py --num-mb 64 --iters 10 --test allreduce
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _sync(x):
    for leaf in x if isinstance(x, (list, tuple)) else [x]:
        leaf.block_until_ready()


def bench_allreduce(num_mb: float, iters: int, dtype: str) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from mxnet_tpu.parallel._compat import shard_map

    devices = np.asarray(jax.devices())
    n = devices.size
    mesh = Mesh(devices, ("dp",))
    itemsize = jnp.dtype(dtype).itemsize
    nelem = int(num_mb * 1e6 / itemsize)
    # Per-device shard; total array is n shards reduced together.
    x = jnp.ones((n, nelem), dtype=dtype)

    @jax.jit
    def step(x):
        def allreduce(shard):
            return jax.lax.psum(shard, axis_name="dp")

        return shard_map(allreduce, mesh=mesh, in_specs=P("dp", None),
                         out_specs=P("dp", None))(x)

    _sync(step(x))  # compile + warm up
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    _sync(out)
    dt = (time.perf_counter() - t0) / iters
    size = nelem * itemsize  # bytes reduced per device shard
    bus = 2.0 * size * (n - 1) / max(n, 1) / dt if n > 1 else size / dt
    return {"test": "allreduce", "devices": n, "size_mb": size / 1e6,
            "avg_time_s": dt, "bus_gb_s": bus / 1e9}


def bench_kvstore(num_mb: float, iters: int, dtype: str, kv_type: str) -> dict:
    import jax

    import mxnet_tpu as mx

    n = len(jax.devices())
    kv = mx.kv.create(kv_type)
    itemsize = np.dtype(dtype).itemsize
    nelem = int(num_mb * 1e6 / itemsize)
    vals = [mx.nd.ones((nelem,), dtype=dtype) for _ in range(max(n, 2))]
    outs = [mx.nd.zeros((nelem,), dtype=dtype) for _ in range(max(n, 2))]
    kv.init(0, vals[0])
    kv.push(0, vals)
    kv.pull(0, out=outs)
    for o in outs:
        o.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(iters):
        kv.push(0, vals)
        kv.pull(0, out=outs)
    for o in outs:
        o.wait_to_read()
    dt = (time.perf_counter() - t0) / iters
    size = nelem * itemsize
    nd = len(vals)
    bus = 2.0 * size * (nd - 1) / nd / dt
    return {"test": "kvstore(%s)" % kv_type, "devices": nd,
            "size_mb": size / 1e6, "avg_time_s": dt, "bus_gb_s": bus / 1e9}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--num-mb", type=float, default=16.0,
                   help="payload size in MB")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--dtype", default="float32")
    p.add_argument("--test", default="allreduce",
                   choices=["allreduce", "kvstore", "both"])
    p.add_argument("--kv-type", default="device")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. cpu; combine with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                        "for a virtual mesh)")
    args = p.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    results = []
    if args.test in ("allreduce", "both"):
        results.append(bench_allreduce(args.num_mb, args.iters, args.dtype))
    if args.test in ("kvstore", "both"):
        results.append(bench_kvstore(args.num_mb, args.iters, args.dtype,
                                     args.kv_type))
    for r in results:
        print("%-22s devices=%d size=%.1fMB time=%.4fs bus=%.2f GB/s"
              % (r["test"], r["devices"], r["size_mb"], r["avg_time_s"],
                 r["bus_gb_s"]))
    return results


if __name__ == "__main__":
    main()
