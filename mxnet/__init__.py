"""``import mxnet`` compatibility alias.

Scripts written against the reference frontend (``import mxnet as mx``)
run against this framework unchanged: every reference module name
(``mx.symbol``/``mx.sym``, ``mx.ndarray``/``mx.nd``, ``mx.io``,
``mx.model``, ``mx.module``/``mx.mod``, ``mx.kvstore``/``mx.kv``, and
the rest of the frontend) resolves to the mxnet_tpu implementation,
including ``from mxnet.foo import bar`` imports (sys.modules entries
are registered for every module below).

The reference's GPU contexts map to TPU devices: ``mx.gpu(i)`` is the
accelerator context (mxnet_tpu.context.gpu is an alias of tpu).
"""
import importlib
import sys

import mxnet_tpu as _m

# everything mxnet_tpu exports at top level (FeedForward, NDArray,
# Symbol, Monitor, cpu/gpu/tpu, Context, MXNetError, the nd/sym/init/
# kv/mod/viz short aliases, ...)
from mxnet_tpu import *  # noqa: F401,F403

__version__ = _m.__version__

# one list drives both the attribute aliases and the sys.modules
# registration, so `import mxnet.X` and `from mxnet.X import y` work for
# every reference frontend module (python/mxnet/*.py) — long name first,
# then the short aliases the reference __init__ exposed
_MODULES = [
    "attribute", "base", "callback", "context", "engine", "executor",
    "executor_manager", "filesystem", "initializer", "io", "kvstore",
    "kvstore_server", "libinfo", "lr_scheduler", "metric", "model",
    "module", "monitor", "name", "ndarray", "operator", "optimizer",
    "random", "recordio", "rtc", "symbol", "symbol_doc", "test_utils",
    "visualization", "profiler", "export",
]
_SHORT = {"nd": "ndarray", "sym": "symbol", "init": "initializer",
          "kv": "kvstore", "mod": "module", "viz": "visualization"}
# reference module names whose implementation lives under a different
# name here (python/mxnet/misc.py was the pre-lr_scheduler home of the
# schedulers; the _internal namespaces held the generated operators;
# torch.py was the torch-op bridge)
_COMPAT = {"misc": "lr_scheduler",
           "_ndarray_internal": "ndarray_ops",
           "_symbol_internal": "symbol",
           "torch": "plugins.torch_bridge"}

for _name in _MODULES:
    _mod_obj = importlib.import_module("mxnet_tpu." + _name)
    globals()[_name] = _mod_obj
    sys.modules["mxnet." + _name] = _mod_obj
for _alias, _target in _COMPAT.items():
    _mod_obj = importlib.import_module("mxnet_tpu." + _target)
    globals()[_alias] = _mod_obj
    sys.modules["mxnet." + _alias] = _mod_obj
for _alias, _target in _SHORT.items():
    _mod_obj = sys.modules["mxnet." + _target]
    globals()[_alias] = _mod_obj
    sys.modules["mxnet." + _alias] = _mod_obj
