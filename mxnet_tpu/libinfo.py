"""Library/version info (reference ``python/mxnet/libinfo.py``: its
``find_lib_path`` located libmxnet.so for ctypes). Here the runtime is
the package itself; the discoverable native artifacts are the host
engine and the C predict ABI built under ``mxnet_tpu/_native``."""
from __future__ import annotations

import os

from . import __version__  # noqa: F401  (reference exposed it here too)


def find_lib_path():
    """Paths of the built native libraries, most specific first.

    Returns the existing candidates among the host-engine library
    (``libmxtpu.so``) and the embedded-runtime C ABI
    (``libmxtpu_predict.so``). Empty list if neither is built —
    unlike the reference this is not fatal, because the Python
    frontend does not need a native library to run.
    """
    here = os.path.dirname(os.path.abspath(os.path.expanduser(__file__)))
    candidates = [
        os.path.join(here, "_native", "libmxtpu_predict.so"),
        os.path.join(here, "_native", "libmxtpu.so"),
    ]
    return [p for p in candidates if os.path.exists(p)]
