"""Numeric verification harness
(reference ``python/mxnet/test_utils.py``): finite-difference gradient
checking, symbolic forward/backward checks against closed forms, and
cross-backend consistency checks (the reference's gpu-vs-cpu
``check_consistency`` becomes accelerator-vs-CPU-jax here).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context, num_devices
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["default_context", "reldiff", "same", "assert_almost_equal",
           "numeric_grad", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "check_speed", "rand_ndarray", "random_arrays"]


def default_context() -> Context:
    return current_context()


def random_arrays(*shapes) -> List[np.ndarray]:
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def rand_ndarray(shape, ctx=None) -> NDArray:
    return nd.array(np.random.randn(*shape).astype(np.float32), ctx=ctx)


def reldiff(a, b) -> float:
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a)) + np.sum(np.abs(b))
    if diff == 0:
        return 0.0
    return diff / norm


def same(a, b) -> bool:
    return np.array_equal(a, b)


def assert_almost_equal(a, b, threshold: float = 1e-5, name=""):
    rel = reldiff(np.asarray(a), np.asarray(b))
    if not rel <= threshold:
        raise AssertionError("%s reldiff %g > %g\n%s\nvs\n%s"
                             % (name, rel, threshold, a, b))
    return rel


def _parse_location(sym, location, ctx) -> Dict[str, NDArray]:
    if isinstance(location, dict):
        return {k: (v if isinstance(v, NDArray) else nd.array(v, ctx=ctx))
                for k, v in location.items()}
    return {k: (v if isinstance(v, NDArray) else nd.array(v, ctx=ctx))
            for k, v in zip(sym.list_arguments(), location)}


def numeric_grad(executor, location: Dict[str, np.ndarray],
                 aux_states=None, eps: float = 1e-4) -> Dict[str, np.ndarray]:
    """Central finite differences of sum(outputs) wrt each argument
    (reference test_utils.py:193)."""
    grads = {}
    for name in location:
        arr = location[name].astype(np.float64)
        grad = np.zeros_like(arr)
        flat = arr.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps
            executor.arg_dict[name][:] = arr.astype(np.float32)
            executor.forward(is_train=True)
            f_pos = sum(float(o.asnumpy().astype(np.float64).sum())
                        for o in executor.outputs)
            flat[i] = orig - eps
            executor.arg_dict[name][:] = arr.astype(np.float32)
            executor.forward(is_train=True)
            f_neg = sum(float(o.asnumpy().astype(np.float64).sum())
                        for o in executor.outputs)
            gflat[i] = (f_pos - f_neg) / (2 * eps)
            flat[i] = orig
        executor.arg_dict[name][:] = arr.astype(np.float32)
        grads[name] = grad
    return grads


def check_numeric_gradient(sym, location, aux_states=None,
                           numeric_eps: float = 1e-3, check_eps: float = 2e-2,
                           grad_nodes=None, ctx=None):
    """Compare autodiff grads against finite differences with random
    projection (reference test_utils.py:242-279)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    loc_np = {k: v.asnumpy() for k, v in location.items()}
    grad_nodes = grad_nodes or list(location.keys())

    executor = sym.simple_bind(ctx=ctx, grad_req={
        k: ("write" if k in grad_nodes else "null") for k in location},
        **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            executor.aux_dict[k][:] = v

    executor.forward(is_train=True)
    executor.backward()
    sym_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    num_grads = numeric_grad(executor, {k: loc_np[k] for k in grad_nodes},
                             eps=numeric_eps)
    for name in grad_nodes:
        rel = reldiff(num_grads[name], sym_grads[name])
        if not rel <= check_eps:
            raise AssertionError(
                "numeric gradient check failed for '%s': reldiff %g > %g"
                % (name, rel, check_eps))


def check_symbolic_forward(sym, location, expected, check_eps: float = 1e-5,
                           aux_states=None, ctx=None):
    """Forward against closed-form expectation (reference
    test_utils.py:364)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    executor = sym.simple_bind(ctx=ctx, grad_req="null",
                               **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            executor.aux_dict[k][:] = v
    outputs = executor.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, check_eps)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym, location, out_grads, expected,
                            check_eps: float = 1e-5, aux_states=None,
                            grad_req="write", ctx=None):
    """Backward against closed-form expectation (reference
    test_utils.py:425)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    executor = sym.simple_bind(ctx=ctx, grad_req=grad_req,
                               **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            executor.aux_dict[k][:] = v
    executor.forward(is_train=True)
    out_grads = [g if isinstance(g, NDArray) else nd.array(g, ctx=ctx)
                 for g in out_grads]
    executor.backward(out_grads)
    if isinstance(expected, dict):
        for name, exp in expected.items():
            assert_almost_equal(executor.grad_dict[name].asnumpy(), exp,
                                check_eps, name=name)
    else:
        for name, exp in zip(sym.list_arguments(), expected):
            if exp is None:
                continue
            assert_almost_equal(executor.grad_dict[name].asnumpy(), exp,
                                check_eps, name=name)
    return {k: v.asnumpy() for k, v in executor.grad_dict.items()}


def check_consistency(sym, ctx_list, scale: float = 1.0,
                      tol: Optional[Dict] = None, grad_req: str = "write"):
    """Bind the same symbol under multiple {ctx, shapes, type_dict} configs
    and require matching outputs/grads under per-dtype tolerance (reference
    test_utils.py:588-640 — the cuDNN-vs-CPU validation mechanism; here it
    validates accelerator vs CPU-jax backends and dtype variants)."""
    tol = tol or {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
                  np.dtype(np.float64): 1e-5, np.dtype(np.uint8): 0,
                  np.dtype(np.int32): 0}
    assert len(ctx_list) > 1
    configs = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx")
        type_dict = spec.pop("type_dict", {})
        shapes = spec
        configs.append((ctx, shapes, type_dict))

    arg_names = sym.list_arguments()
    # common random inputs, cast per config
    base_shapes = configs[0][1]
    arg_shapes, _, aux_shapes = sym.infer_shape(**base_shapes)
    rng = np.random.RandomState(0)
    base_args = [rng.normal(0, scale, size=s).astype(np.float64)
                 for s in arg_shapes]

    results = []
    for ctx, shapes, type_dict in configs:
        executor = sym.simple_bind(ctx=ctx, grad_req=grad_req,
                                   type_dict=type_dict, **shapes)
        dtypes = [executor.arg_dict[n].dtype for n in arg_names]
        for n, v, dt in zip(arg_names, base_args, dtypes):
            executor.arg_dict[n][:] = v.astype(dt)
        executor.forward(is_train=(grad_req != "null"))
        outs = [o.asnumpy().astype(np.float64) for o in executor.outputs]
        grads = None
        if grad_req != "null":
            # random (seeded) head grads shared across configs: the
            # reference uses the output as head grad
            # (test_utils.py:651 ``exe.backward(exe.outputs[0])``), but
            # that is degenerate for BatchNorm (grads cancel to ~0);
            # a random cotangent exercises every grad path non-trivially
            grng = np.random.RandomState(17)
            heads = [nd.array(grng.normal(0, 1, size=o.shape)
                              .astype(executor.outputs[i].dtype), ctx=ctx)
                     for i, o in enumerate(outs)]
            executor.backward(heads)
            grads = {n: executor.grad_dict[n].asnumpy().astype(np.float64)
                     for n in executor.grad_dict}
        results.append((outs, grads, max(tol.get(np.dtype(d), 1e-3)
                                         for d in dtypes)))

    ref_outs, ref_grads, _ = results[0]
    for outs, grads, eps in results[1:]:
        for a, b in zip(ref_outs, outs):
            assert_almost_equal(a, b, max(eps, results[0][2]), "output")
        if grads is not None and ref_grads is not None:
            for name in ref_grads:
                assert_almost_equal(ref_grads[name], grads[name],
                                    max(eps, results[0][2]), name)
    return results


def check_speed(sym, location=None, ctx=None, N: int = 20,
                grad_req: str = "write", typ: str = "whole") -> float:
    """Micro-benchmark a symbol (reference test_utils.py:510)."""
    ctx = ctx or default_context()
    if location is None:
        raise MXNetError("location required")
    location = _parse_location(sym, location, ctx)
    executor = sym.simple_bind(ctx=ctx, grad_req=grad_req,
                               **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        executor.arg_dict[k][:] = v

    if typ == "whole":
        # warmup
        executor.forward(is_train=True)
        executor.backward()
        for o in executor.outputs:
            o.wait_to_read()
        tic = time.time()
        for _ in range(N):
            executor.forward(is_train=True)
            executor.backward()
        for g in executor.grad_dict.values():
            g.wait_to_read()
        return (time.time() - tic) / N
    elif typ == "forward":
        executor.forward(is_train=False)
        for o in executor.outputs:
            o.wait_to_read()
        tic = time.time()
        for _ in range(N):
            executor.forward(is_train=False)
        for o in executor.outputs:
            o.wait_to_read()
        return (time.time() - tic) / N
    raise MXNetError("typ must be 'whole' or 'forward'")
