"""Device observability plane: compile analytics, per-op FLOP/memory
attribution, and HBM accounting.

Host-side observability (telemetry counters, StepTrace, lock
contention) tells you *when* a step was slow; this module tells you
what the *device* was doing. The reference framework's ``profiler.h``
layer attributed time to individual engine ops; the XLA-native
equivalent is compile-time analysis of the executables the step path
actually runs:

* **CompileRegistry** — every step-path jit (fused_step, the
  executor's fused fwd+bwd, metric folds, kvstore reduce) is routed
  through :func:`jit`, an AOT ``lower()``/``compile()`` wrapper that
  records compile wall-time, the argument-aval signature,
  ``cost_analysis()`` FLOPs / bytes-accessed and ``memory_analysis()``
  argument/output/temp/peak bytes into the ``compile.*`` telemetry
  namespace. A recompile carries a *retrace-cause diff* naming exactly
  which avals changed vs the previous signature — "(64,3,224,224)f32
  -> (32,3,224,224)f32 on batch.data" instead of "something retraced".
* **Op-category attribution** — :func:`hlo_op_breakdown` parses the
  compiled executable's optimized HLO into a conv / dot / fusion /
  collective / transpose / elementwise FLOP+bytes table whose category
  sums ARE the reported totals (exact by construction), so the
  measured-vs-analytic MFU gap is attributable to a specific category.
  :func:`analyze` adds analytic MFU, arithmetic intensity and a
  compute- vs bandwidth-bound classification from the chip's peak
  FLOPs and HBM bandwidth.
* **HBM accounting** — :class:`HbmWatermark` samples the live-buffer
  watermark per step (``device.memory_stats()`` on TPU,
  ``jax.live_arrays()`` fallback on CPU), feeds the
  ``hbm.headroom_bytes`` gauge the MetricsServer exports, and
  :func:`preflight_check` refuses a config whose ``memory_analysis``
  peak cannot fit before a single step runs.

Everything except profiler trace capture works on CPU, so tier-1
exercises the whole plane (``tests/test_xprof.py``).

Design note: jax's AOT path does NOT populate the jit dispatch cache,
so a naive "lower+compile to measure, then call the jit" pays every
compile twice. The wrapper therefore *keeps* the AOT executable it
measured and dispatches through it — instrumentation adds zero extra
compiles and zero extra dispatches (regression-tested against
``dispatches_per_step``).
"""
from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import env as _env
from . import telemetry as _tel
from .base import MXNetError

__all__ = [
    "enabled", "enable", "disable", "reset", "jit", "record_compile",
    "records", "summary", "last_retrace_cause", "hlo_op_breakdown",
    "analyze", "chip_peak_tflops", "chip_hbm_gbps", "hbm_stats",
    "HbmWatermark", "preflight_check", "device_memory_limit",
    "CompileRecord", "CATEGORIES",
]

# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------

_override: Optional[bool] = None


def enabled() -> bool:
    """Master switch: ``MXNET_TPU_XPROF`` or a runtime enable()."""
    if _override is not None:
        return _override
    return bool(_env.get("MXNET_TPU_XPROF"))


def enable():
    global _override
    _override = True


def disable():
    global _override
    _override = False


# ---------------------------------------------------------------------------
# compile registry
# ---------------------------------------------------------------------------

class CompileRecord:
    """One measured ``lower()``/``compile()`` of a step-path site."""

    __slots__ = ("site", "seq", "compile_time_s", "signature", "flops",
                 "bytes_accessed", "argument_bytes", "output_bytes",
                 "temp_bytes", "peak_bytes", "generated_code_bytes",
                 "op_breakdown", "retrace_cause", "num_devices", "ts")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in self.__slots__}
        d["signature"] = [[n, list(a[0]), a[1]]
                          + ([a[3]] if len(a) > 3 and a[3] else [])
                          for n, a in (self.signature or ())]
        return d


_lock = threading.RLock()
_records: List[CompileRecord] = []
_sites: Dict[str, dict] = {}
_last_cause: Optional[str] = None
_seq = 0


def reset():
    """Clear recorded compiles and per-site state (not the enable
    override — tests pair enable()/disable() explicitly)."""
    global _last_cause, _seq
    with _lock:
        del _records[:]
        _sites.clear()
        _last_cause = None
        _seq = 0


def records() -> List[CompileRecord]:
    with _lock:
        return list(_records)


def last_retrace_cause() -> Optional[str]:
    """The most recent recompile's aval diff (None before any retrace);
    the RecompileDetector attaches this to its anomaly events."""
    return _last_cause


# -- argument signatures ----------------------------------------------------

def _sharding_fp(x) -> Optional[str]:
    """Stable placement fingerprint for a device array, or None for
    host arrays. Part of the AOT-cache key: two calls with identical
    shapes but different shardings (a server re-bound across mesh
    factorings) must NOT share an executable — dispatching one
    compiled for the old placement silently computes on wrong layouts.
    """
    sh = getattr(x, "sharding", None)
    if sh is None:
        return None
    spec = getattr(sh, "spec", None)
    mesh = getattr(sh, "mesh", None)
    if spec is not None and mesh is not None:
        axes = ",".join("%s=%d" % (a, int(mesh.shape[a]))
                        for a in mesh.axis_names)
        return "mesh(%s)%s" % (axes, spec)
    dev = getattr(sh, "_device", None)
    if dev is not None:
        return "dev(%s)" % (dev,)
    return type(sh).__name__


def _aval(x) -> tuple:
    shape = tuple(int(d) for d in getattr(x, "shape", ()) or ())
    dtype = str(getattr(x, "dtype", type(x).__name__))
    return (shape, dtype, bool(getattr(x, "weak_type", False)),
            _sharding_fp(x))


def _fmt_aval(a) -> str:
    shape, dtype = a[0], a[1]
    placed = a[3] if len(a) > 3 and a[3] else ""
    return "(%s)%s%s" % (",".join(str(d) for d in shape), dtype,
                         "@" + placed if placed else "")


def leaf_signature(args, arg_names=None) -> tuple:
    """((name, (shape, dtype, weak_type)), ...) over the flattened
    positional args. ``arg_names[i]`` labels arg i; a list/tuple entry
    names that argument's leaves individually (the fused step passes
    the executor's own arg names, so a diff says ``batch.data`` rather
    than ``arg1[0]``)."""
    import jax

    specs = []
    for i, a in enumerate(args):
        name = arg_names[i] if arg_names and i < len(arg_names) else None
        flat = jax.tree_util.tree_flatten_with_path(a)[0]
        for j, (kp, leaf) in enumerate(flat):
            if isinstance(name, (list, tuple)):
                label = (name[j] if j < len(name)
                         else "arg%d%s" % (i, jax.tree_util.keystr(kp)))
            elif name:
                label = name + jax.tree_util.keystr(kp)
            else:
                label = "arg%d%s" % (i, jax.tree_util.keystr(kp))
            specs.append((label, _aval(leaf)))
    return tuple(specs)


def diff_signatures(prev, cur) -> Optional[str]:
    """Human-readable retrace cause: which leaves' avals changed."""
    if prev is None or prev == cur:
        return None
    if len(prev) != len(cur):
        return ("argument tree changed: %d -> %d leaves"
                % (len(prev), len(cur)))
    changes = ["%s -> %s on %s" % (_fmt_aval(pa), _fmt_aval(ca), cn)
               for (_pn, pa), (cn, ca) in zip(prev, cur) if pa != ca]
    if not changes:
        return "argument names changed (same avals)"
    head = "; ".join(changes[:3])
    if len(changes) > 3:
        head += " (+%d more)" % (len(changes) - 3)
    return head


# -- executable analysis ----------------------------------------------------

def _cost_dict(compiled) -> dict:
    try:
        c = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(c, (list, tuple)):
        c = c[0] if c else {}
    return dict(c) if c else {}


def _device_count(compiled) -> Optional[int]:
    """Devices the executable was SPMD-partitioned over (1 for an
    unsharded step; the dp mesh size for the sharded fused step) — the
    compile-registry witness that GSPMD actually partitioned a site."""
    try:
        return len(compiled.runtime_executable().local_devices())
    except Exception:
        return None


def _memory_dict(compiled) -> Optional[dict]:
    try:
        m = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(m, (list, tuple)):
        m = m[0] if m else None
    if m is None:
        return None
    out = {}
    for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                      ("output_bytes", "output_size_in_bytes"),
                      ("temp_bytes", "temp_size_in_bytes"),
                      ("alias_bytes", "alias_size_in_bytes"),
                      ("generated_code_bytes",
                       "generated_code_size_in_bytes")):
        out[key] = int(getattr(m, attr, 0) or 0)
    # aliased (donated) buffers are counted once: they are argument
    # bytes XLA reuses for outputs, not extra live memory at peak
    out["peak_bytes"] = max(0, out["argument_bytes"] + out["output_bytes"]
                            + out["temp_bytes"]
                            + out["generated_code_bytes"]
                            - out["alias_bytes"])
    return out


def record_compile(site: str, compiled, compile_time_s: float,
                   signature: Optional[tuple] = None) -> CompileRecord:
    """Record one measured compile into the registry + ``compile.*``
    telemetry; computes the retrace-cause diff against the site's
    previous signature."""
    global _last_cause, _seq
    cost = _cost_dict(compiled)
    mem = _memory_dict(compiled) or {}
    breakdown = None
    if _env.get("MXNET_TPU_XPROF_OPS"):
        try:
            breakdown = hlo_op_breakdown(compiled.as_text())
        except Exception:
            breakdown = None
    flops = cost.get("flops")
    flops = float(flops) if flops else None
    if flops is None and breakdown:
        flops = float(sum(v["flops"] for v in breakdown.values()))
    ba = cost.get("bytes accessed")
    with _lock:
        st = _sites.setdefault(site, {"compiles": 0, "time_s": 0.0,
                                      "sig": None, "last": None})
        cause = diff_signatures(st["sig"], signature) \
            if signature is not None else None
        _seq += 1
        rec = CompileRecord(
            site=site, seq=_seq,
            compile_time_s=round(float(compile_time_s), 6),
            signature=signature, flops=flops,
            bytes_accessed=float(ba) if ba else None,
            argument_bytes=mem.get("argument_bytes"),
            output_bytes=mem.get("output_bytes"),
            temp_bytes=mem.get("temp_bytes"),
            peak_bytes=mem.get("peak_bytes"),
            generated_code_bytes=mem.get("generated_code_bytes"),
            op_breakdown=breakdown, retrace_cause=cause,
            num_devices=_device_count(compiled),
            ts=round(time.time(), 6))
        st["compiles"] += 1
        st["time_s"] += float(compile_time_s)
        st["sig"] = signature
        st["last"] = rec
        _records.append(rec)
        cap = int(_env.get("MXNET_TPU_XPROF_RECORDS"))
        if len(_records) > cap:
            del _records[:len(_records) - cap]
        if cause:
            _last_cause = "%s: %s" % (site, cause)
    if _tel.enabled():
        _tel.inc("compile.count")
        _tel.observe("compile.time_ms", compile_time_s * 1e3)
        if flops:
            _tel.inc("compile.flops", int(flops))
        if rec.peak_bytes:
            _tel.set_gauge("compile.peak_bytes", rec.peak_bytes)
    return rec


def summary() -> dict:
    """JSON-able registry summary for BENCH records / trace_report."""
    with _lock:
        sites = {}
        for site, st in _sites.items():
            sites[site] = {"compiles": st["compiles"],
                           "compile_time_s": round(st["time_s"], 4),
                           "last": (st["last"].to_dict()
                                    if st["last"] else None)}
        total_t = sum(st["time_s"] for st in _sites.values())
        total_n = sum(st["compiles"] for st in _sites.values())
        peaks = [r.peak_bytes for r in _records if r.peak_bytes]
    out = {"sites": sites,
           "totals": {"compiles": total_n,
                      "compile_time_s": round(total_t, 4),
                      "peak_bytes_max": max(peaks) if peaks else 0}}
    try:
        out["hbm"] = hbm_stats()
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# the instrumented jit wrapper
# ---------------------------------------------------------------------------

_FALLBACK = object()


def jit(fn, site: str, arg_names=None, **jit_kw):
    """``jax.jit`` with the compile registry on the compile path.

    Disabled (the default): returns the plain ``jax.jit`` — zero added
    work per dispatch. Enabled: returns a wrapper that, per new
    argument-aval signature, times ``lower().compile()`` into a
    :class:`CompileRecord` and then dispatches through the measured AOT
    executable itself (same donation, same executable — no second
    compile, no extra dispatch). Positional calling only, which is all
    the step-path sites use."""
    import jax

    jfn = jax.jit(fn, **jit_kw)
    if not enabled():
        return jfn
    return _InstrumentedJit(jfn, site, arg_names)


class _InstrumentedJit:
    def __init__(self, jfn, site, arg_names):
        self._jit = jfn
        self._site = site
        self._arg_names = arg_names
        self._cache: Dict[tuple, Any] = {}
        self._lock = threading.Lock()

    def lower(self, *args, **kw):
        # HLO regression gates lower() the raw jit; keep that working
        return self._jit.lower(*args, **kw)

    def __call__(self, *args):
        sig = leaf_signature(args, self._arg_names)
        with self._lock:
            compiled = self._cache.get(sig)
            if compiled is None:
                # compiling under the lock is the point: a second
                # thread hitting the same signature must wait for the
                # one measured compile, not race a duplicate
                compiled = self._compile(args, sig)  # graft: blocking-ok
        if compiled is _FALLBACK:
            return self._jit(*args)
        try:
            return compiled(*args)
        except TypeError:
            # the AOT input check is stricter than jit dispatch (e.g. a
            # committed-device mismatch); fall back rather than fail
            with self._lock:
                self._cache[sig] = _FALLBACK
            return self._jit(*args)

    def _compile(self, args, sig):
        t0 = time.perf_counter()
        try:
            compiled = self._jit.lower(*args).compile()
        except NotImplementedError:
            self._cache[sig] = _FALLBACK
            return _FALLBACK
        rec = record_compile(self._site, compiled,
                             time.perf_counter() - t0, signature=sig)
        if _env.get("MXNET_TPU_XPROF_PREFLIGHT") and rec.peak_bytes:
            try:
                devs = compiled.runtime_executable().local_devices()
            except Exception:
                devs = None
            preflight_check(rec.peak_bytes, devices=devs,
                            what=self._site)
        self._cache[sig] = compiled
        return compiled


# ---------------------------------------------------------------------------
# HLO op-category attribution
# ---------------------------------------------------------------------------

CATEGORIES = ("conv", "dot", "fusion", "collective", "transpose",
              "elementwise", "other")

_DTYPE_BYTES = {"pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
                "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
                "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
                "token": 0, "opaque": 0}

_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\]")
_INSTR_RE = re.compile(r"^(?:ROOT\s+)?%?([\w.\-]+)\s=\s(.*)$")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(")

_COLLECTIVE = frozenset((
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-reduce-done", "all-gather-start", "all-gather-done",
    "collective-permute-start", "collective-permute-done",
    "partition-id", "replica-id", "send", "recv", "send-done",
    "recv-done"))
_DATA_MOVE = frozenset((
    "transpose", "copy", "reshape", "bitcast", "bitcast-convert",
    "broadcast", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "gather", "scatter", "pad", "reverse", "copy-start",
    "copy-done", "iota"))
_SKIP = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "after-all",
    "domain", "opt-barrier", "add-dependency", "partition-id"))
_REDUCES = frozenset(("reduce", "reduce-window", "select-and-scatter",
                      "sort"))
# elementwise ops that actually do arithmetic (1 FLOP/elem model;
# comparisons/selects/converts are categorized elementwise at 0 FLOPs)
_ARITH = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "expm1", "log", "log1p", "logistic", "power",
    "sqrt", "rsqrt", "cbrt", "tanh", "tan", "sine", "cosine", "atan2",
    "remainder", "negate", "abs", "erf", "sign", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "clamp", "map"))


def _dtype_bytes(dt: str) -> int:
    return _DTYPE_BYTES.get(dt, 4)


def _shape_list(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """Every ``dtype[dims]`` token in ``text`` (operand lists carry the
    operands' shapes inline in optimized-HLO text)."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        if dt in _DTYPE_BYTES or dt[0] in "sufc" or dt == "pred":
            out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


def _elems(dims) -> int:
    n = 1
    for d in dims:
        n *= d
    return n


def _split_instr(rhs: str):
    """(out_shapes, opcode, operand_text, attr_text) from an
    instruction's right-hand side, or None."""
    rhs = rhs.strip()
    if rhs.startswith("("):            # tuple-shaped output
        depth, i = 0, 0
        for i, ch in enumerate(rhs):
            depth += (ch == "(") - (ch == ")")
            if depth == 0:
                break
        out_txt, rest = rhs[:i + 1], rhs[i + 1:]
    else:
        m = _SHAPE_RE.match(rhs)
        if not m:
            return None
        rest = rhs[m.end():]
        if rest.startswith("{"):       # layout
            rest = rest[rest.index("}") + 1:]
        out_txt = rhs[:m.end()]
    rest = rest.strip()
    m = re.match(r"([\w\-]+)\(", rest)
    if not m:
        return None
    opcode = m.group(1)
    depth, j = 0, m.end() - 1
    for j in range(m.end() - 1, len(rest)):
        depth += (rest[j] == "(") - (rest[j] == ")")
        if depth == 0:
            break
    return (_shape_list(out_txt), opcode,
            rest[m.end():j], rest[j + 1:])


def _conv_flops(out_elems: int, op_shapes, attrs: str) -> int:
    ksize = 1
    m = re.search(r"size=([\dx]+)", attrs)
    if m:
        for d in m.group(1).split("x"):
            ksize *= int(d)
    cin = 1
    m = re.search(r"dim_labels=[\w?]+_([\w?]+)->", attrs)
    if m and len(op_shapes) >= 2:
        rhs_labels, rhs_shape = m.group(1), op_shapes[1][1]
        if "i" in rhs_labels and rhs_labels.index("i") < len(rhs_shape):
            cin = rhs_shape[rhs_labels.index("i")]
    m = re.search(r"feature_group_count=(\d+)", attrs)
    groups = int(m.group(1)) if m else 1
    return 2 * out_elems * ksize * cin // max(groups, 1)


def _dot_flops(out_elems: int, op_shapes, attrs: str) -> int:
    k = 1
    m = re.search(r"lhs_contracting_dims=\{([\d,]*)\}", attrs)
    if m and op_shapes:
        lhs_shape = op_shapes[0][1]
        for d in m.group(1).split(","):
            if d and int(d) < len(lhs_shape):
                k *= lhs_shape[int(d)]
    return 2 * out_elems * k


def hlo_op_breakdown(hlo_text: str) -> Dict[str, dict]:
    """Parse optimized HLO text into ``{category: {"flops", "bytes",
    "count"}}`` over the entry computation. FLOPs follow the standard
    analytic model (2·N·K per dot/conv MAC, 1/elem for arithmetic,
    in-elems per reduce); fused computations contribute their body's
    conv/dot FLOPs to those categories and everything else to
    ``fusion``, whose bytes are the fusion's interface traffic. The
    per-category FLOPs sum to the reported total by construction —
    cross-check against ``cost_analysis()['flops']`` lives in the
    CompileRecord beside it."""
    comps: Dict[str, list] = {}
    entry = None
    cur: Optional[list] = None
    for line in hlo_text.splitlines():
        s = line.strip()
        if s.endswith("{") and "=" not in s.split("(")[0]:
            m = _COMP_RE.match(s)
            if m:
                cur = comps.setdefault(m.group(2), [])
                if m.group(1):
                    entry = m.group(2)
            continue
        if s.startswith("}"):
            cur = None
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(s)
        if m is None:
            continue
        parsed = _split_instr(m.group(2))
        if parsed is not None:
            cur.append(parsed)
    if entry is None:          # single-computation module w/o ENTRY tag
        entry = next(iter(comps), None)
    if entry is None:
        return {}

    def classify(parsed):
        out_shapes, opcode, operands, attrs = parsed
        op_shapes = _shape_list(operands)
        out_elems = sum(_elems(d) for _dt, d in out_shapes)
        out_bytes = sum(_elems(d) * _dtype_bytes(dt)
                        for dt, d in out_shapes)
        byts = out_bytes + sum(_elems(d) * _dtype_bytes(dt)
                               for dt, d in op_shapes)
        if opcode in _SKIP:
            return None
        if opcode == "convolution":
            return "conv", _conv_flops(out_elems, op_shapes, attrs), byts
        if opcode in ("dot", "ragged-dot"):
            return "dot", _dot_flops(out_elems, op_shapes, attrs), byts
        if opcode in _COLLECTIVE:
            return "collective", 0, byts
        if opcode in _DATA_MOVE:
            return "transpose", 0, byts
        if opcode in _REDUCES:
            in_elems = sum(_elems(d) for _dt, d in op_shapes) or out_elems
            return "elementwise", in_elems, byts
        if opcode == "fusion":
            return "fusion", 0, byts       # body folded in below
        return ("elementwise", out_elems if opcode in _ARITH else 0,
                byts) if opcode in _ARITH or opcode in (
                    "compare", "select", "convert", "and", "or", "xor",
                    "not", "is-finite", "shift-left",
                    "shift-right-logical", "shift-right-arithmetic",
                    "exponential-minus-one", "rng", "rng-bit-generator",
                    "reduce-precision", "real", "imag", "complex",
        ) else ("other", 0, byts)

    memo: Dict[str, Dict[str, int]] = {}

    def body_flops(name, stack=()):
        """Per-category FLOPs of a computation body (bytes inside a
        fusion are not real memory traffic and are not counted)."""
        if name in memo:
            return memo[name]
        if name in stack or name not in comps:
            return {}
        totals: Dict[str, int] = {}
        for parsed in comps[name]:
            cl = classify(parsed)
            if cl is None:
                continue
            cat, fl, _by = cl
            if cat == "fusion":
                m = re.search(r"calls=%?([\w.\-]+)", parsed[3])
                if m:
                    for c, f in body_flops(m.group(1),
                                           stack + (name,)).items():
                        c = c if c in ("conv", "dot") else "fusion"
                        totals[c] = totals.get(c, 0) + f
                continue
            totals[cat] = totals.get(cat, 0) + fl
        memo[name] = totals
        return totals

    agg = {c: {"flops": 0, "bytes": 0, "count": 0} for c in CATEGORIES}
    coll_ops: Dict[str, Dict[str, int]] = {}
    for parsed in comps[entry]:
        cl = classify(parsed)
        if cl is None:
            continue
        cat, fl, by = cl
        agg[cat]["bytes"] += by
        agg[cat]["count"] += 1
        if cat == "collective":
            # per-opcode sub-buckets: an fsdp step's all-gather
            # (param gather before forward) and reduce-scatter (grad
            # shard-reduce) are distinguishable from the dp all-reduce
            op = parsed[1]
            sub = coll_ops.setdefault(op, {"bytes": 0, "count": 0})
            sub["bytes"] += by
            sub["count"] += 1
        if cat == "fusion":
            m = re.search(r"calls=%?([\w.\-]+)", parsed[3])
            sub = body_flops(m.group(1), (entry,)) if m else {}
            for c, f in sub.items():
                c = c if c in ("conv", "dot") else "fusion"
                agg[c]["flops"] += f
        else:
            agg[cat]["flops"] += fl
    if coll_ops:
        agg["collective"]["by_op"] = coll_ops
    return {c: v for c, v in agg.items()
            if v.get("count") or v.get("flops")}


# ---------------------------------------------------------------------------
# analytic MFU / roofline classification
# ---------------------------------------------------------------------------

# bf16 peak TFLOP/s per chip (kept in sync with bench.CHIP_PEAK_TFLOPS)
CHIP_PEAK_TFLOPS = {"v5 lite": 197, "v5litepod": 197, "v5e": 197,
                    "v5p": 459, "v4": 275, "v6 lite": 918, "v6e": 918,
                    "v3": 123, "v2": 45}
# HBM bandwidth GB/s per chip (public TPU system specs)
CHIP_HBM_GBPS = {"v5 lite": 819, "v5litepod": 819, "v5e": 819,
                 "v5p": 2765, "v4": 1228, "v6 lite": 1640, "v6e": 1640,
                 "v3": 900, "v2": 700}


def _table_lookup(table, device_kind: Optional[str]):
    if not device_kind:
        return None
    kind = device_kind.lower()
    for frag, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if frag in kind:
            return val
    return None


def chip_peak_tflops(device_kind: Optional[str]):
    return _table_lookup(CHIP_PEAK_TFLOPS, device_kind)


def chip_hbm_gbps(device_kind: Optional[str]):
    return _table_lookup(CHIP_HBM_GBPS, device_kind)


def analyze(flops, bytes_accessed, step_time_s=None,
            device_kind: Optional[str] = None) -> dict:
    """Roofline analytics for one executable: arithmetic intensity,
    the chip's ridge point, compute- vs bandwidth-bound, and (given a
    measured step time) achieved TFLOP/s + analytic MFU. Unknown chips
    (CPU) report ``analytic_mfu_pct: 0.0`` and ``bound: "unknown"``
    with the FLOP counts still attached."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
    peak = chip_peak_tflops(device_kind)
    bw = chip_hbm_gbps(device_kind)
    out = {"flops": flops, "bytes_accessed": bytes_accessed,
           "device_kind": device_kind,
           "peak_tflops": peak, "hbm_gbps": bw}
    ai = (float(flops) / float(bytes_accessed)
          if flops and bytes_accessed else None)
    ridge = (peak * 1e12) / (bw * 1e9) if peak and bw else None
    out["arithmetic_intensity"] = round(ai, 2) if ai else None
    out["ridge_intensity"] = round(ridge, 2) if ridge else None
    out["bound"] = (("compute" if ai >= ridge else "bandwidth")
                    if ai is not None and ridge is not None else "unknown")
    if step_time_s and flops:
        achieved = float(flops) / float(step_time_s)
        out["achieved_tflops"] = round(achieved / 1e12, 3)
        out["analytic_mfu_pct"] = (
            round(100.0 * achieved / (peak * 1e12), 2) if peak else 0.0)
    return out


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

def hbm_stats(device=None) -> dict:
    """Live-buffer accounting FOR ONE DEVICE: ``device.memory_stats()``
    where the backend provides it (TPU), else ``jax.live_arrays()``
    (CPU — no allocator limit, so ``limit_bytes`` is None). The
    live_arrays walk is per-device exact: a sharded array contributes
    only the bytes of its shards resident on ``device`` (an
    fsdp-sharded pack bills 1/fsdp per chip), never its GLOBAL
    ``nbytes`` — billing the whole pack to device 0 is precisely the
    accounting bug a sharded mesh exposes."""
    import jax

    try:
        dev = device if device is not None else jax.devices()[0]
    except Exception:
        return {"live_bytes": 0, "limit_bytes": None,
                "peak_bytes": None, "source": "none"}
    ms = None
    try:
        ms = dev.memory_stats()
    except Exception:
        ms = None
    if ms and ms.get("bytes_in_use") is not None:
        return {"live_bytes": int(ms.get("bytes_in_use", 0)),
                "limit_bytes": (int(ms["bytes_limit"])
                                if ms.get("bytes_limit") else None),
                "peak_bytes": (int(ms["peak_bytes_in_use"])
                               if ms.get("peak_bytes_in_use") else None),
                "source": "memory_stats"}
    live = 0
    for arr in jax.live_arrays():
        try:
            shards = getattr(arr, "addressable_shards", None)
            if shards:
                for s in shards:
                    if s.device == dev:
                        live += int(s.data.nbytes)
            else:
                live += int(arr.nbytes)
        except Exception:
            pass
    return {"live_bytes": live, "limit_bytes": None,
            "peak_bytes": None, "source": "live_arrays"}


class HbmWatermark:
    """Per-step live-buffer watermark. ``sample()`` after each step;
    ``peak`` is monotone over the run and the ``hbm.*`` gauges
    (including ``hbm.headroom_bytes``, exported by the MetricsServer)
    track the latest sample. ``limit_bytes`` overrides the device
    limit where the backend reports none (CPU tests)."""

    def __init__(self, device=None, limit_bytes: Optional[int] = None):
        self.device = device
        self.limit = limit_bytes
        self.peak = 0
        self.last = 0

    def sample(self) -> int:
        s = hbm_stats(self.device)
        self.last = s["live_bytes"]
        if self.limit is None:
            self.limit = s["limit_bytes"]
        self.peak = max(self.peak, self.last, s["peak_bytes"] or 0)
        if _tel.enabled():
            _tel.set_gauge("hbm.live_bytes", self.last)
            _tel.set_gauge("hbm.peak_bytes", self.peak)
            if self.limit:
                _tel.set_gauge("hbm.headroom_bytes",
                               self.limit - self.last)
        return self.last

    @property
    def headroom_bytes(self) -> Optional[int]:
        return self.limit - self.last if self.limit else None


def device_memory_limit(device=None) -> Optional[int]:
    try:
        import jax
        dev = device if device is not None else jax.devices()[0]
        ms = dev.memory_stats()
        if ms and ms.get("bytes_limit"):
            return int(ms["bytes_limit"])
    except Exception:
        pass
    return None


def _fmt_bytes(n) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return ("%d%s" if unit == "B" else "%.1f%s") % (n, unit)
        n /= 1024.0


def preflight_check(peak_bytes, limit_bytes: Optional[int] = None,
                    device=None, devices=None, what: str = "computation"):
    """Refuse a config before it runs: raise :class:`MXNetError` when
    the executable's ``memory_analysis`` peak exceeds the device HBM
    limit. Returns the headroom in bytes, or None when no limit is
    known (CPU) — the check is advisory there by design.

    ``memory_analysis`` reports PER-PARTITION bytes for an SPMD
    executable (each device holds only its shard of arguments, temps
    and outputs), so the comparison is per-device by construction:
    pass ``devices`` (the executable's local devices) and the peak is
    checked against the SMALLEST per-device limit among them — NOT
    against device 0's limit with the whole pack billed to it."""
    if limit_bytes is None and devices:
        limits = [device_memory_limit(d) for d in devices]
        limits = [l for l in limits if l]
        limit_bytes = min(limits) if limits else None
    if limit_bytes is None:
        limit_bytes = device_memory_limit(device)
    if not limit_bytes or not peak_bytes:
        return None
    headroom = int(limit_bytes) - int(peak_bytes)
    if headroom < 0:
        raise MXNetError(
            "pre-flight OOM: %s needs %s at peak but the device limit "
            "is %s (short %s) — shrink the batch or shard the model"
            % (what, _fmt_bytes(int(peak_bytes)),
               _fmt_bytes(int(limit_bytes)), _fmt_bytes(-headroom)))
    return headroom
