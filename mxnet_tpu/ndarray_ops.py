"""Imperative invocation of registered operators.

The reference's SimpleOp registry (``include/mxnet/operator_util.h:243-481``)
registers an op once and exposes it BOTH as an NDArray function and a
symbolic op. Here the same unification: every operator in the registry is
materialized as ``mx.nd.<OpName>(*ndarrays, **params)``: one dependency-
engine op that reads the input vars and writes fresh output vars, applying
the op's jnp/XLA kernel. Mirrors the auto-generation in
``python/mxnet/ndarray.py:1127-1306``.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray, _new_from_multi
from .ops import OP_REGISTRY
from .ops.registry import OpContext

__all__ = ["init_ndarray_ops"]


def _make_imperative(op_name: str):
    cls = OP_REGISTRY.get(op_name)

    def fn(*args, **params):
        is_train = params.pop("is_train", False)
        op = cls(**params)
        arg_names = op.list_arguments()
        if len(args) != len(arg_names):
            raise MXNetError("%s expects inputs %s, got %d arrays"
                             % (op_name, arg_names, len(args)))
        if op.list_auxiliary_states():
            raise MXNetError(
                "%s has auxiliary states; use the symbolic API" % op_name)
        arrays = [a if isinstance(a, NDArray) else None for a in args]
        if any(a is None for a in arrays):
            raise MXNetError("%s: inputs must be NDArrays" % op_name)

        rng = None
        if is_train or not arg_names:  # sampling ops need a key
            from . import random as _random

            rng = _random.next_key()

        if not arrays:
            # zero-input ops (samplers): run directly
            outs, _ = op.apply(OpContext(is_train, rng), [], [])
            res = [NDArray(o) for o in outs]
            return res[0] if len(res) == 1 else res

        # ONE engine op reading the input vars and writing fresh output
        # vars — imperative ops are ordered by the dependency engine
        # exactly like NDArray arithmetic, so async-pending inputs are safe
        def compute(*datas):
            outs, _ = op.apply(OpContext(is_train, rng), list(datas), [])
            return outs

        res_nd = _new_from_multi(arrays[0]._ctx, compute, arrays,
                                 len(op.list_outputs()))
        return res_nd[0] if len(res_nd) == 1 else res_nd

    fn.__name__ = op_name
    fn.__doc__ = cls.__doc__ or "Imperative %s." % op_name
    return fn


def init_ndarray_ops(nd_module):
    """Populate the nd namespace with imperative op functions (skipping
    names already hand-defined there, e.g. the reduce/unary zoo)."""
    done = set()
    for _, cls in list(OP_REGISTRY.items()):
        for name in (cls.op_name,) + getattr(cls, "op_aliases", ()):
            if name in done or hasattr(nd_module, name):
                continue
            done.add(name)
            setattr(nd_module, name, _make_imperative(name))
