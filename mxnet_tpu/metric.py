"""Evaluation metrics (reference ``python/mxnet/metric.py``)."""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "CompositeEvalMetric", "CustomMetric",
           "np_metric", "create"]

_REG: Registry = Registry.get_registry("metric")


def check_label_shapes(labels, preds):
    if len(labels) != len(preds):
        raise MXNetError("labels/preds count mismatch: %d vs %d"
                         % (len(labels), len(preds)))


class EvalMetric:
    def __init__(self, name: str, num: Optional[int] = None):
        self.name = name
        self.num = num
        self.reset()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num

    def update(self, labels: Sequence[NDArray], preds: Sequence[NDArray]):
        raise NotImplementedError

    def get(self):
        if self.num is None:
            value = self.sum_metric / self.num_inst if self.num_inst else float("nan")
            return self.name, value
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [s / n if n else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return names, values

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))


@_REG.register("acc")
@_REG.register("accuracy")
class Accuracy(EvalMetric):
    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = pred.asnumpy()
            pred_label = np.argmax(p, axis=1) if p.ndim > 1 else p
            lab = label.asnumpy().astype(np.int32).ravel()
            self.sum_metric += int((pred_label.astype(np.int32).ravel() == lab).sum())
            self.num_inst += len(lab)


@_REG.register("top_k_accuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k: int = 1, **kwargs):
        self.top_k = kwargs.get("top_k", top_k)
        super().__init__("top_k_accuracy_%d" % self.top_k)
        if self.top_k <= 1:
            raise MXNetError("top_k should be >1; use Accuracy otherwise")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = pred.asnumpy().astype(np.float32)
            lab = label.asnumpy().astype(np.int32)
            topk = np.argsort(p, axis=1)[:, -self.top_k:]
            for i in range(len(lab)):
                self.sum_metric += int(lab[i] in topk[i])
            self.num_inst += len(lab)


@_REG.register("f1")
class F1(EvalMetric):
    """Binary F1 (reference metric.py F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = np.argmax(pred.asnumpy(), axis=1)
            lab = label.asnumpy().astype(np.int32).ravel()
            if len(np.unique(lab)) > 2:
                raise MXNetError("F1 supports binary classification only")
            tp = int(((p == 1) & (lab == 1)).sum())
            fp = int(((p == 1) & (lab == 0)).sum())
            fn = int(((p == 0) & (lab == 1)).sum())
            precision = tp / (tp + fp) if tp + fp else 0.0
            recall = tp / (tp + fn) if tp + fn else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@_REG.register("mae")
class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()
            p_np = pred.asnumpy().reshape(l_np.shape)
            self.sum_metric += float(np.abs(l_np - p_np).mean())
            self.num_inst += 1


@_REG.register("mse")
class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()
            p_np = pred.asnumpy().reshape(l_np.shape)
            self.sum_metric += float(((l_np - p_np) ** 2).mean())
            self.num_inst += 1


@_REG.register("rmse")
class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()
            p_np = pred.asnumpy().reshape(l_np.shape)
            self.sum_metric += float(np.sqrt(((l_np - p_np) ** 2).mean()))
            self.num_inst += 1


@_REG.register("ce")
@_REG.register("cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps: float = 1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            lab = label.asnumpy().astype(np.int32).ravel()
            p = pred.asnumpy()
            prob = p[np.arange(lab.shape[0]), lab]
            self.sum_metric += float((-np.log(prob + self.eps)).sum())
            self.num_inst += len(lab)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics: Optional[List[EvalMetric]] = None, **kwargs):
        super().__init__("composite")
        self.metrics = metrics or []

    def add(self, metric: "EvalMetric"):
        self.metrics.append(metric)

    def get_metric(self, index: int) -> EvalMetric:
        return self.metrics[index]

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


class CustomMetric(EvalMetric):
    """Wrap ``feval(label, pred) -> float`` (reference CustomMetric)."""

    def __init__(self, feval: Callable, name: Optional[str] = None,
                 allow_extra_outputs: bool = False):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__("custom(%s)" % name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(label.asnumpy(), pred.asnumpy())
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np_metric(numpy_feval: Callable, name: Optional[str] = None,
              allow_extra_outputs: bool = False):
    """Decorator creating a CustomMetric from a numpy function."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name or numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric: Union[str, Callable, EvalMetric], **kwargs) -> EvalMetric:
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, **kwargs))
        return composite
    cls = _REG.get(metric)
    return cls(**kwargs)
