"""Evaluation metrics (reference ``python/mxnet/metric.py``).

Device-side accumulation: metrics whose math is expressible as a pure
per-batch fold (``has_device_fold``) keep a running ``(sum, count)``
pair ON DEVICE and only fetch it to the host in :meth:`EvalMetric.get`
(Speedometer / epoch-report cadence). The reference synced every batch:
each ``update`` called ``asnumpy``, serializing the dispatch queue. Here
``update`` dispatches one small async fold instead, and the fused train
step (:mod:`mxnet_tpu.fused_step`) folds the same math INTO the training
computation so a batch costs zero extra dispatches.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from . import telemetry as _tel
from .base import MXNetError, Registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "CompositeEvalMetric", "CustomMetric",
           "np_metric", "create"]

_REG: Registry = Registry.get_registry("metric")

# jitted device folds shared across metric instances, keyed by
# (_fold_cache_key(), n_pairs): metrics are constructed per fit()/score()
# call, and a per-instance jit would recompile the same tiny fold for
# every one of them
_FOLD_FNS: dict = {}


def _replicated_zero(like):
    """A zero f32 scalar placed compatibly with ``like``: replicated over
    ``like``'s device set so a jit mixing the accumulator with sharded
    batch outputs (multi-device executor) sees one consistent mesh."""
    import jax
    import jax.numpy as jnp

    z = jnp.zeros((), jnp.float32)
    sharding = getattr(like, "sharding", None)
    if sharding is None:
        return z
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        if isinstance(sharding, NamedSharding):
            return jax.device_put(
                z, NamedSharding(sharding.mesh, PartitionSpec()))
        devs = list(sharding.device_set)
        if len(devs) == 1:
            return jax.device_put(z, devs[0])
    except Exception:
        pass
    return z


def _device_ids(x):
    """frozenset of device ids ``x`` is committed to, or None when it
    carries no sharding (uncommitted / not a jax array)."""
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return None
    try:
        return frozenset(d.id for d in sharding.device_set)
    except Exception:
        return None


def check_label_shapes(labels, preds):
    if len(labels) != len(preds):
        raise MXNetError("labels/preds count mismatch: %d vs %d"
                         % (len(labels), len(preds)))


class EvalMetric:
    # True on subclasses that implement device_fold; such metrics keep a
    # cumulative (sum, count) pair on device (self._device_acc) and read
    # it back only in get()
    has_device_fold = False

    def __init__(self, name: str, num: Optional[int] = None):
        self.name = name
        self.num = num
        self.reset()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
        self._device_acc = None
        self._fold_fn = None

    def device_fold(self, label, pred):
        """Pure jnp fold of ONE (label, pred) pair into ``(sum_delta,
        count_delta)`` f32 scalars — the jit-friendly form of this
        metric's update math. Traceable inside the fused train step."""
        raise NotImplementedError

    def _fold_cache_key(self):
        """Key under which this metric's jitted fold may be shared with
        other instances; subclasses whose device_fold reads instance
        config (top_k, eps, ...) must extend it."""
        return (type(self),)

    def _lazy_update(self, labels, preds) -> bool:
        """Accumulate this batch on device without any host sync; True
        when handled (the numpy path must then be skipped). Only for
        scalar (num is None) metrics with a device fold over NDArray
        inputs — anything else falls through to the eager path."""
        if not self.has_device_fold or self.num is not None:
            return False
        labels, preds = list(labels), list(preds)
        if not labels or len(labels) != len(preds):
            return False
        if not all(isinstance(a, NDArray) for a in labels + preds):
            return False
        # one jit needs one consistent device set: a multi-device
        # executor shards preds over the mesh while labels sit on one
        # device — that batch takes the eager numpy path instead
        # (get() still folds in whatever the accumulator already holds)
        sets = {_device_ids(a._data) for a in labels + preds}
        sets.discard(None)
        if len(sets) > 1:
            return False
        if self._device_acc is not None and sets \
                and _device_ids(self._device_acc[0]) not in (
                    None, next(iter(sets))):
            return False
        import jax

        if self._fold_fn is None:
            key = self._fold_cache_key()
            fn = _FOLD_FNS.get(key)
            if fn is None:
                fold = self.device_fold

                def accum(acc, labs, ps):
                    s, c = acc
                    for lab, p in zip(labs, ps):
                        ds, dc = fold(lab, p)
                        s = s + ds
                        c = c + dc
                    return s, c

                from . import xprof as _xprof

                _FOLD_FNS[key] = fn = _xprof.jit(
                    accum, site="metric.fold",
                    arg_names=("acc", "labels", "preds"))
            self._fold_fn = fn
        acc = self._device_acc
        if acc is None:
            from .analysis import sanitizers as _san

            with _san.intentional_transfer():
                z = _replicated_zero(preds[0]._data)
            acc = (z, z)
        _tel.inc("step.dispatches")
        self._device_acc = self._fold_fn(
            acc, [a._data for a in labels], [p._data for p in preds])
        return True

    def _host_totals(self):
        """(sum, count) with the device accumulator folded in — the ONLY
        place the accumulator syncs to the host."""
        from .analysis import sanitizers as _san

        s, n = self.sum_metric, self.num_inst
        if self._device_acc is not None:
            acc_s, acc_c = self._device_acc
            with _san.intentional_transfer():
                s = s + float(acc_s)  # graft: host-sync
                n = n + float(acc_c)  # graft: host-sync
        return s, n

    def update(self, labels: Sequence[NDArray], preds: Sequence[NDArray]):
        raise NotImplementedError

    def get(self):
        if self.num is None:
            s, n = self._host_totals()
            value = s / n if n else float("nan")
            return self.name, value
        names = ["%s_%d" % (self.name, i) for i in range(self.num)]
        values = [s / n if n else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return names, values

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))


@_REG.register("acc")
@_REG.register("accuracy")
class Accuracy(EvalMetric):
    has_device_fold = True

    def __init__(self):
        super().__init__("accuracy")

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        lab = label.astype(jnp.int32).ravel()
        pl = jnp.argmax(pred, axis=1) if pred.ndim > 1 else pred
        hits = (pl.astype(jnp.int32).ravel() == lab).sum()
        return hits.astype(jnp.float32), jnp.float32(lab.size)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            p = pred.asnumpy()  # graft: host-sync
            pred_label = np.argmax(p, axis=1) if p.ndim > 1 else p
            lab = label.asnumpy().astype(np.int32).ravel()  # graft: host-sync
            self.sum_metric += int((pred_label.astype(np.int32).ravel() == lab).sum())
            self.num_inst += len(lab)


@_REG.register("top_k_accuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k: int = 1, **kwargs):
        self.top_k = kwargs.get("top_k", top_k)
        super().__init__("top_k_accuracy_%d" % self.top_k)
        if self.top_k <= 1:
            raise MXNetError("top_k should be >1; use Accuracy otherwise")

    has_device_fold = True

    def _fold_cache_key(self):
        return (type(self), self.top_k)

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        lab = label.astype(jnp.int32).ravel()
        topk = jnp.argsort(pred, axis=1)[:, -self.top_k:]
        hits = (topk == lab[:, None]).any(axis=1).sum()
        return hits.astype(jnp.float32), jnp.float32(lab.size)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            p = pred.asnumpy().astype(np.float32)  # graft: host-sync
            lab = label.asnumpy().astype(np.int32)  # graft: host-sync
            topk = np.argsort(p, axis=1)[:, -self.top_k:]
            for i in range(len(lab)):
                self.sum_metric += int(lab[i] in topk[i])
            self.num_inst += len(lab)


@_REG.register("f1")
class F1(EvalMetric):
    """Binary F1 (reference metric.py F1)."""

    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = np.argmax(pred.asnumpy(), axis=1)  # graft: host-sync
            lab = label.asnumpy().astype(np.int32).ravel()  # graft: host-sync
            if len(np.unique(lab)) > 2:
                raise MXNetError("F1 supports binary classification only")
            tp = int(((p == 1) & (lab == 1)).sum())
            fp = int(((p == 1) & (lab == 0)).sum())
            fn = int(((p == 0) & (lab == 1)).sum())
            precision = tp / (tp + fp) if tp + fp else 0.0
            recall = tp / (tp + fn) if tp + fn else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@_REG.register("mae")
class MAE(EvalMetric):
    has_device_fold = True

    def __init__(self):
        super().__init__("mae")

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        err = jnp.abs(label - pred.reshape(label.shape)).mean()
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()  # graft: host-sync
            p_np = pred.asnumpy().reshape(l_np.shape)  # graft: host-sync
            self.sum_metric += float(np.abs(l_np - p_np).mean())
            self.num_inst += 1


@_REG.register("mse")
class MSE(EvalMetric):
    has_device_fold = True

    def __init__(self):
        super().__init__("mse")

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        err = ((label - pred.reshape(label.shape)) ** 2).mean()
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()  # graft: host-sync
            p_np = pred.asnumpy().reshape(l_np.shape)  # graft: host-sync
            self.sum_metric += float(((l_np - p_np) ** 2).mean())
            self.num_inst += 1


@_REG.register("rmse")
class RMSE(EvalMetric):
    has_device_fold = True

    def __init__(self):
        super().__init__("rmse")

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        err = jnp.sqrt(((label - pred.reshape(label.shape)) ** 2).mean())
        return err.astype(jnp.float32), jnp.float32(1.0)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            l_np = label.asnumpy()  # graft: host-sync
            p_np = pred.asnumpy().reshape(l_np.shape)  # graft: host-sync
            self.sum_metric += float(np.sqrt(((l_np - p_np) ** 2).mean()))
            self.num_inst += 1


@_REG.register("ce")
@_REG.register("cross-entropy")
class CrossEntropy(EvalMetric):
    has_device_fold = True

    def __init__(self, eps: float = 1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def _fold_cache_key(self):
        return (type(self), self.eps)

    def device_fold(self, label, pred):
        import jax.numpy as jnp

        lab = label.astype(jnp.int32).ravel()
        prob = jnp.take_along_axis(pred, lab[:, None], axis=1)[:, 0]
        loss = (-jnp.log(prob + self.eps)).sum()
        return loss.astype(jnp.float32), jnp.float32(lab.size)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        if self._lazy_update(labels, preds):
            return
        for label, pred in zip(labels, preds):
            lab = label.asnumpy().astype(np.int32).ravel()  # graft: host-sync
            p = pred.asnumpy()  # graft: host-sync
            prob = p[np.arange(lab.shape[0]), lab]
            self.sum_metric += float((-np.log(prob + self.eps)).sum())
            self.num_inst += len(lab)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics: Optional[List[EvalMetric]] = None, **kwargs):
        super().__init__("composite")
        self.metrics = metrics or []

    def add(self, metric: "EvalMetric"):
        self.metrics.append(metric)

    def get_metric(self, index: int) -> EvalMetric:
        return self.metrics[index]

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


class CustomMetric(EvalMetric):
    """Wrap ``feval(label, pred) -> float`` (reference CustomMetric)."""

    def __init__(self, feval: Callable, name: Optional[str] = None,
                 allow_extra_outputs: bool = False):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__("custom(%s)" % name)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            # graft: host-sync
            reval = self._feval(label.asnumpy(), pred.asnumpy())
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np_metric(numpy_feval: Callable, name: Optional[str] = None,
              allow_extra_outputs: bool = False):
    """Decorator creating a CustomMetric from a numpy function."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = name or numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric: Union[str, Callable, EvalMetric], **kwargs) -> EvalMetric:
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, **kwargs))
        return composite
    cls = _REG.get(metric)
    return cls(**kwargs)
