"""Step traces, anomaly detection, flight recording, live metrics.

``telemetry.py`` gives the framework raw counters/gauges/histograms;
this module is the layer that *interprets* them. The reference had
nothing comparable — a stalled input ring or a mid-run recompile
surfaced as "training got slower" with no artifact saying why. Four
pieces close that gap:

* :class:`StepTrace` — once per training step, snapshots every tracked
  telemetry counter and stores the per-step DELTAS (io stall ms, h2d
  bytes, kvstore traffic, decode-cache hits, executor recompiles)
  alongside the step latency in a bounded ring. Each slow step carries
  the evidence of what it spent its time on.
* Anomaly detectors over that ring — :class:`SlowStepDetector`
  (latency > k x rolling median), :class:`RecompileDetector`
  (``executor.jit_build`` past warmup) and :class:`InputStallDetector`
  (stall-dominated step). A trigger emits a structured event, and with
  ``MXNET_TPU_TRACE_ON_ANOMALY=1`` auto-starts a short, rate-limited
  XLA trace window (:class:`AnomalyProfiler`).
* :class:`FlightRecorder` — ``sys.excepthook`` / ``SIGTERM`` /
  ``SIGUSR1`` handlers that dump the last-N step records, all-thread
  stacks and a full telemetry snapshot into a crash directory for
  post-mortem (``MXNET_TPU_FLIGHT_RECORDER=1``; ``kill -USR1 <pid>``
  dumps without stopping the run).
* :class:`MetricsServer` — a stdlib ``http.server`` thread serving
  Prometheus text format at ``/metrics`` plus ``/healthz`` on
  ``MXNET_TPU_METRICS_PORT``, so an operator (or the bench harness)
  can scrape a live run without attaching to the process. Samples are
  labeled with the worker rank so ``dist_async`` workers are
  distinguishable on one dashboard.

Overhead contract (inherited from telemetry): everything here is off
unless telemetry is enabled; :func:`record_step` and
:func:`maybe_init` start with one flag check and return immediately,
taking no locks and allocating nothing. See docs/performance.md
("Interpreting step traces").
"""
from __future__ import annotations

import http.server
import json
import logging
import math
import os
import signal
import sys
import tempfile
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional

from . import telemetry as _tel
from . import env as _env

__all__ = ["StepTrace", "SlowStepDetector", "RecompileDetector",
           "InputStallDetector", "SlowRequestDetector",
           "FleetHealthDetector", "LossSpikeDetector",
           "GradExplosionDetector", "DeadUpdateDetector",
           "NonfiniteDetector", "AnomalyProfiler",
           "FlightRecorder", "MetricsServer", "step_trace", "record_step",
           "maybe_init", "set_worker_rank", "worker_rank", "shutdown",
           "register_health_probe", "unregister_health_probe",
           "register_health_info", "unregister_health_info",
           "register_preempt_hook", "unregister_preempt_hook",
           "ensure_flight_recorder"]

_log = logging.getLogger(__name__)

# Per-step delta sources: (record field, telemetry metric, kind).
# "counter" reads the running int; "hist_sum" reads a histogram's
# running sum (the stall histograms observe milliseconds, so their sum
# delta IS the ms this step spent stalled).
DELTA_SOURCES = (
    ("io_stall_ms", "io.pipeline.stall_ms", "hist_sum"),
    ("prefetch_stall_ms", "io.prefetch_stall_ms", "hist_sum"),
    ("feed_stall_ms", "io.feed_stall_ms", "hist_sum"),
    ("h2d_bytes", "ndarray.h2d_bytes", "counter"),
    ("kv_push_bytes", "kvstore.push_bytes", "counter"),
    ("kv_pull_bytes", "kvstore.pull_bytes", "counter"),
    ("decode_cache_hits", "io.decode_cache_hit", "counter"),
    ("recompiles", "executor.jit_build", "counter"),
    ("dispatches", "step.dispatches", "counter"),
    ("fused_recompiles", "step.fused_recompiles", "counter"),
    ("fallbacks", "step.fused_fallback", "counter"),
    ("sanitizer_trips", "sanitizer.trips", "counter"),
    # xprof compile registry: measured XLA compiles this step and the
    # wall time they took (the time_ms histogram's sum delta IS the ms
    # this step spent compiling)
    ("compiles", "compile.count", "counter"),
    ("compile_ms", "compile.time_ms", "hist_sum"),
    # checkpoint manager: snapshots written this step and the wall time
    # they took (checkpoint.py)
    ("ckpt_saves", "ckpt.saves", "counter"),
    ("ckpt_save_ms", "ckpt.save_ms", "hist_sum"),
    # numerics plane (numwatch.py): guard actions taken this step
    ("numwatch_skipped", "numwatch.skipped_steps", "counter"),
    ("numwatch_rolled_back", "numwatch.rollbacks", "counter"),
)

_STALL_FIELDS = ("io_stall_ms", "prefetch_stall_ms", "feed_stall_ms")


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------

class SlowStepDetector:
    """Flags a step whose latency exceeds ``k`` x the rolling median of
    the preceding ``window`` steps (after ``warmup`` steps, so compile
    steps don't poison the baseline)."""

    type = "slow_step"

    def __init__(self, k: float = 3.0, warmup: int = 10, window: int = 64):
        self.k = float(k)
        self.warmup = int(warmup)
        self._lat = deque(maxlen=int(window))

    def check(self, rec: dict) -> Optional[dict]:
        lat = rec["latency_ms"]
        prior = sorted(self._lat)
        self._lat.append(lat)
        if rec["step"] <= self.warmup or not prior:
            return None
        median = prior[len(prior) // 2]
        if median > 0 and lat > self.k * median:
            return {"type": self.type, "latency_ms": round(lat, 3),
                    "median_ms": round(median, 3),
                    "ratio": round(lat / median, 2)}
        return None


class RecompileDetector:
    """An ``executor.jit_build`` in steady state means a shape/dtype
    drifted and XLA recompiled mid-run — the silent multi-second stall
    the telemetry tier exists to catch."""

    type = "recompile"

    def __init__(self, warmup: int = 10):
        self.warmup = int(warmup)

    def check(self, rec: dict) -> Optional[dict]:
        n = rec["deltas"].get("recompiles", 0)
        nf = rec["deltas"].get("fused_recompiles", 0)
        nc = rec["deltas"].get("compiles", 0)
        if rec["step"] > self.warmup and (n > 0 or nf > 0 or nc > 0):
            ev = {"type": self.type, "recompiles": n,
                  "latency_ms": round(rec["latency_ms"], 3)}
            if nf:
                # a fused-step retrace past warmup: some batch shape or
                # optimizer structure drifted mid-run (recompile storm)
                ev["fused_recompiles"] = nf
            if nc:
                ev["compiles"] = nc
                ev["compile_ms"] = rec["deltas"].get("compile_ms", 0.0)
            # with the xprof registry armed, name the avals that drifted
            # ("(64,3,224,224)f32 -> (32,...)f32 on batch.data") instead
            # of just flagging that something retraced
            try:
                from . import xprof as _xprof

                cause = _xprof.last_retrace_cause()
            except Exception:
                cause = None
            if cause:
                ev["cause"] = cause
            return ev
        return None


class InputStallDetector:
    """Flags a step that spent more than ``frac`` of its wall time
    blocked on the input pipeline (ring stall + prefetch stall)."""

    type = "input_stall"

    def __init__(self, frac: float = 0.5, min_ms: float = 1.0):
        self.frac = float(frac)
        self.min_ms = float(min_ms)

    def check(self, rec: dict) -> Optional[dict]:
        stall = sum(rec["deltas"].get(f, 0.0) for f in _STALL_FIELDS)
        lat = rec["latency_ms"]
        if stall >= self.min_ms and lat > 0 and stall > self.frac * lat:
            return {"type": self.type, "stall_ms": round(stall, 3),
                    "latency_ms": round(lat, 3),
                    "stall_frac": round(stall / lat, 2)}
        return None


class SlowRequestDetector:
    """Serving-tier SLO guard: fires when a served request batch
    reports a worst-case per-request latency (``request_ms``, stamped
    into the record by ``serving.BatchScheduler``) over the SLO
    (``slo_ms``, stamped from ``MXNET_TPU_SERVE_SLO_MS``). Training
    records never carry ``request_ms``, so this is inert there.

    When the record carries the adaptive scheduler's controller state
    (``adaptive_wait_ms``, ``queue_depth``) the event copies it, so a
    breached SLO is attributable at a glance: a wide wait means the
    controller was still coalescing, a deep queue means overload. When
    the distributed tracer sampled the offending request the record
    also carries ``worst_trace_id``; copying it into the event links
    the anomaly straight to a kept span tree
    (``tools/trace_report.py --view waterfall <id>``)."""

    type = "slow_request"

    def check(self, rec: dict) -> Optional[dict]:
        req = rec.get("request_ms")
        slo = rec.get("slo_ms")
        if req is not None and slo and req > slo:
            ev = {"type": self.type, "request_ms": round(req, 3),
                  "slo_ms": round(float(slo), 3),
                  "over_frac": round(req / slo - 1.0, 3)}
            for k in ("adaptive_wait_ms", "queue_depth",
                      "worst_trace_id"):
                if rec.get(k) is not None:
                    ev[k] = rec[k]
            return ev
        return None


class FleetHealthDetector:
    """Fleet-tier guard: the :class:`~mxnet_tpu.fleet.FleetRouter`'s
    monitor stamps ``fleet_down`` (dead replicas awaiting respawn) and
    ``breaker_open`` (replicas currently shedding load) into a step
    record whenever either is nonzero; this turns that into an anomaly
    so /healthz and the flight recorder see a shrinking fleet the same
    way they see a slow request. Inert for training and single-replica
    serving records."""

    type = "fleet_degraded"

    def check(self, rec: dict) -> Optional[dict]:
        down = rec.get("fleet_down", 0)
        tripped = rec.get("breaker_open", 0)
        burn = rec.get("slo_burn_alert", 0)
        if down or tripped or burn:
            ev = {"type": self.type}
            if down:
                ev["replicas_down"] = int(down)
            if tripped:
                ev["breakers_open"] = int(tripped)
            if burn:
                # stamped by obswatch's burn-rate monitor: both the
                # fast and slow windows are burning error budget past
                # the alert threshold
                ev["slo_burn_alert"] = 1
                for k in ("slo_burn_fast", "slo_burn_slow",
                          "slo_budget_spent"):
                    if rec.get(k) is not None:
                        ev[k] = round(float(rec[k]), 4)
            if rec.get("fleet_size") is not None:
                ev["fleet_size"] = int(rec["fleet_size"])
            return ev
        return None


class LossSpikeDetector:
    """Numerics-plane guard: numwatch's cadence fetch stamps the
    in-graph loss (``numwatch_loss``) into the step record; a loss more
    than MXNET_TPU_NUMWATCH_SPIKE_K times its rolling median is a
    spike — bad batch, lr too hot, or the first visible symptom of a
    numeric blowup. Inert on records without the stamp (numwatch off,
    or an off-cadence step)."""

    type = "loss_spike"

    def __init__(self, k: Optional[float] = None, window: int = 32):
        self.k = float(k if k is not None
                       else _env.get("MXNET_TPU_NUMWATCH_SPIKE_K"))
        self._hist: deque = deque(maxlen=window)

    def check(self, rec: dict) -> Optional[dict]:
        loss = rec.get("numwatch_loss")
        if loss is None or not math.isfinite(loss):
            return None
        prior = sorted(self._hist)
        self._hist.append(float(loss))
        if len(prior) < 3:
            return None
        median = prior[len(prior) // 2]
        if median > 0 and loss > self.k * median:
            return {"type": self.type, "loss": round(float(loss), 6),
                    "median": round(median, 6),
                    "ratio": round(float(loss) / median, 2)}
        return None


class GradExplosionDetector:
    """Numerics-plane guard over the fetched global gradient norm
    (``numwatch_grad_norm``): a norm more than
    MXNET_TPU_NUMWATCH_EXPLODE_K times its rolling median means the
    backward pass is exploding — the classic precursor of the NaN the
    NonfiniteDetector would report a few steps later."""

    type = "grad_explosion"

    def __init__(self, k: Optional[float] = None, window: int = 32):
        self.k = float(k if k is not None
                       else _env.get("MXNET_TPU_NUMWATCH_EXPLODE_K"))
        self._hist: deque = deque(maxlen=window)

    def check(self, rec: dict) -> Optional[dict]:
        norm = rec.get("numwatch_grad_norm")
        if norm is None or not math.isfinite(norm):
            return None
        prior = sorted(self._hist)
        self._hist.append(float(norm))
        if len(prior) < 3:
            return None
        median = prior[len(prior) // 2]
        if median > 0 and norm > self.k * median:
            return {"type": self.type,
                    "grad_norm": round(float(norm), 6),
                    "median": round(median, 6),
                    "ratio": round(float(norm) / median, 2)}
        return None


class DeadUpdateDetector:
    """Numerics-plane guard over the largest per-tensor update-to-
    weight ratio (``numwatch_uw_max``): gradients flowing but every
    update below MXNET_TPU_NUMWATCH_DEAD_UW means training is inert —
    an lr schedule that collapsed to zero, a saturated optimizer state,
    or a frozen graph."""

    type = "dead_update"

    def __init__(self, threshold: Optional[float] = None):
        self.threshold = float(
            threshold if threshold is not None
            else _env.get("MXNET_TPU_NUMWATCH_DEAD_UW"))

    def check(self, rec: dict) -> Optional[dict]:
        uw = rec.get("numwatch_uw_max")
        if uw is None:
            return None
        norm = rec.get("numwatch_grad_norm") or 0.0
        if uw < self.threshold and norm > 0 and math.isfinite(norm):
            return {"type": self.type, "uw_max": float(uw),
                    "grad_norm": round(float(norm), 6),
                    "threshold": self.threshold}
        return None


class NonfiniteDetector:
    """Numerics-plane alarm: any nonfinite param or grad element seen
    by the fetch (``numwatch_nonfinite``) becomes an anomaly event
    carrying the provenance verdict (``numwatch_bad_tensor`` — the
    first tensor to go bad, in forward order) and the guard counters,
    so a crash dump names the layer, not just the symptom."""

    type = "nonfinite"

    def check(self, rec: dict) -> Optional[dict]:
        n = rec.get("numwatch_nonfinite")
        if not n:
            return None
        ev = {"type": self.type, "nonfinite": int(n)}
        for k in ("numwatch_bad_tensor", "numwatch_skips",
                  "numwatch_rollbacks"):
            if rec.get(k) is not None:
                ev[k.replace("numwatch_", "")] = rec[k]
        return ev


def default_detectors() -> list:
    return [SlowStepDetector(), RecompileDetector(), InputStallDetector(),
            SlowRequestDetector(), FleetHealthDetector(),
            LossSpikeDetector(), GradExplosionDetector(),
            DeadUpdateDetector(), NonfiniteDetector()]


# ---------------------------------------------------------------------------
# pluggable /healthz probes
# ---------------------------------------------------------------------------

_probe_lock = threading.Lock()
_health_probes: Dict[str, object] = {}


def register_health_probe(name: str, probe):
    """Register a liveness probe consulted by ``/healthz``: a callable
    returning None when healthy or a JSON-able failure detail when not.
    Any failing probe flips the endpoint to ``{"status": "degraded"}``
    with HTTP 503 — the serving tier registers its SLO check here so a
    load balancer drains a replica whose tail latency broke the SLO."""
    with _probe_lock:
        _health_probes[name] = probe


def unregister_health_probe(name: str):
    with _probe_lock:
        _health_probes.pop(name, None)


# identity/info providers: merged into the /healthz JSON regardless of
# health (probes above only surface when they FAIL; info is always on)
_health_info: Dict[str, object] = {}


def register_health_info(name: str, info):
    """Register an identity/info provider for ``/healthz``: a callable
    returning a JSON-able dict merged into the payload on every scrape
    (existing payload keys win). The serving tier registers its
    in-flight/served counts here so the fleet router and a human curl
    read one replica-identity signal."""
    with _probe_lock:
        _health_info[name] = info


def unregister_health_info(name: str):
    with _probe_lock:
        _health_info.pop(name, None)


def _run_health_info() -> Dict[str, object]:
    """Merged info payload ({} when none registered). A provider that
    raises contributes an error string instead of crashing the scrape."""
    with _probe_lock:
        infos = list(_health_info.items())
    merged: Dict[str, object] = {}
    for name, info in infos:
        try:
            detail = info()
            if detail:
                merged.update(dict(detail))
        except Exception as e:
            merged[name] = "info provider raised: %s" % (e,)
    return merged


def _run_health_probes() -> Dict[str, object]:
    """Failing probes by name ({} == healthy). A probe that raises is
    itself a failure — a broken health check must not read as green."""
    with _probe_lock:
        probes = list(_health_probes.items())
    failing = {}
    for name, probe in probes:
        try:
            detail = probe()
        except Exception as e:
            detail = "probe raised: %s" % (e,)
        if detail is not None:
            failing[name] = detail
    return failing


# ---------------------------------------------------------------------------
# anomaly-triggered profiling
# ---------------------------------------------------------------------------

class AnomalyProfiler:
    """Starts a short XLA trace window when an anomaly fires, so the
    evidence for a slow step is captured while it is still happening.

    Rate-limited: at most one window per ``cooldown_s`` (suppressed
    triggers are counted, not traced), and never while a capture —
    auto or user-started — is already running. ``start_fn``/``stop_fn``
    default to :func:`mxnet_tpu.profiler.start`/``stop`` and exist so
    tests can observe the windowing without a real jax trace."""

    def __init__(self, trace_dir: Optional[str] = None,
                 window_steps: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 start_fn: Optional[Callable] = None,
                 stop_fn: Optional[Callable] = None):
        self.trace_dir = trace_dir or _env.get(
            "MXNET_TPU_TRACE_DIR",
            default=os.path.join(tempfile.gettempdir(),
                                 "mxnet_tpu_anomaly_trace"))
        self.window_steps = int(window_steps if window_steps is not None
                                else _env.get("MXNET_TPU_TRACE_WINDOW"))
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else _env.get("MXNET_TPU_TRACE_COOLDOWN"))
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._last_start: Optional[float] = None
        self._stop_at: Optional[int] = None
        self.started = 0
        self.suppressed = 0

    def _start(self, path: str):
        if self._start_fn is not None:
            return self._start_fn(path)
        from . import profiler as _prof

        _prof.start(path)

    def _stop(self):
        if self._stop_fn is not None:
            return self._stop_fn()
        from . import profiler as _prof

        _prof.stop()

    def on_anomaly(self, step: int, event: dict) -> bool:
        """Maybe open a trace window for ``event``; True if started."""
        if self._stop_at is not None:
            return False
        if self._start_fn is None:
            from . import profiler as _prof

            if _prof.is_running():   # user capture in progress: stay out
                return False
        now = time.monotonic()
        if self._last_start is not None \
                and now - self._last_start < self.cooldown_s:
            self.suppressed += 1
            _tel.inc("tracing.auto_trace_suppressed")
            return False
        path = os.path.join(self.trace_dir,
                            "step%d_%s" % (step, event["type"]))
        try:
            os.makedirs(path, exist_ok=True)
            self._start(path)
        except Exception as e:
            _log.warning("anomaly trace start failed: %s", e)
            return False
        self._last_start = now
        self._stop_at = step + self.window_steps
        self.started += 1
        _tel.inc("tracing.auto_traces")
        _log.warning("anomaly at step %d (%s): capturing %d-step trace "
                     "into %s", step, event["type"], self.window_steps, path)
        return True

    def on_step(self, step: int):
        """Close the window once ``window_steps`` more steps elapsed."""
        if self._stop_at is not None and step >= self._stop_at:
            self._stop_at = None
            try:
                self._stop()
            except Exception as e:
                _log.warning("anomaly trace stop failed: %s", e)


# ---------------------------------------------------------------------------
# step trace recorder
# ---------------------------------------------------------------------------

class StepTrace:
    """Bounded ring of per-step records, each carrying the telemetry
    deltas accumulated during that step.

    ``record(latency_ms)`` is called once per training step (the fit
    loop, ``bench.py``). The baseline for step 1's deltas is the
    counter state at construction, so a recorder created at fit() start
    attributes everything to steps."""

    def __init__(self, capacity: Optional[int] = None, detectors=None,
                 profiler: Optional[AnomalyProfiler] = None,
                 event_cooldown: Optional[int] = None):
        cap = int(capacity if capacity is not None
                  else _env.get("MXNET_TPU_TRACE_RING"))
        self._ring: deque = deque(maxlen=max(1, cap))
        self._lock = threading.Lock()
        self._step = 0
        self._prev = self._raw_values()
        self.detectors = (default_detectors() if detectors is None
                          else list(detectors))
        if profiler is None and _env.get("MXNET_TPU_TRACE_ON_ANOMALY"):
            profiler = AnomalyProfiler()
        self.profiler = profiler
        self.events: deque = deque(maxlen=256)
        self.event_cooldown = int(
            event_cooldown if event_cooldown is not None
            else _env.get("MXNET_TPU_TRACE_EVENT_COOLDOWN"))
        self._last_event_step: Dict[str, int] = {}

    @staticmethod
    def _raw_values() -> Dict[str, float]:
        return {field: _tel.peek(metric, kind) or 0
                for field, metric, kind in DELTA_SOURCES}

    @staticmethod
    def _dominant(deltas: Dict[str, float], latency_ms: float) -> str:
        """Label the step with what it spent its time on: a measured
        compile (xprof registry) or a recompile trumps everything (it
        IS the latency), then whichever stall source claims >25% of
        the wall time; otherwise compute."""
        if deltas.get("compiles", 0) > 0:
            # xprof measured the compile itself — the most specific
            # label available (its CompileRecord carries the cause)
            return "compile"
        if deltas.get("recompiles", 0) > 0 \
                or deltas.get("fused_recompiles", 0) > 0:
            return "recompile"
        stalls = [(deltas.get(f, 0.0), f) for f in _STALL_FIELDS]
        worst, field = max(stalls)
        if latency_ms > 0 and worst > 0.25 * latency_ms:
            return field
        return "compute"

    def record(self, latency_ms: float, extra: Optional[dict] = None) -> dict:
        """Snapshot counters, compute deltas vs the previous step, run
        the detectors; returns the appended record."""
        raw = self._raw_values()
        with self._lock:
            self._step += 1
            step = self._step
            deltas = {}
            for field, _metric, kind in DELTA_SOURCES:
                d = raw[field] - self._prev.get(field, 0)
                if kind == "hist_sum":
                    deltas[field] = round(d, 3)
                else:
                    deltas[field] = int(d)
            self._prev = raw
            rec = {"step": step, "ts": round(time.time(), 6),
                   "latency_ms": round(float(latency_ms), 3),
                   "deltas": deltas,
                   "dominant": self._dominant(deltas, latency_ms)}
            if extra:
                rec.update(extra)
            self._ring.append(rec)
        if self.profiler is not None:
            self.profiler.on_step(step)
        for det in self.detectors:
            try:
                ev = det.check(rec)
            except Exception as e:
                _log.warning("anomaly detector %s failed: %s",
                             type(det).__name__, e)
                continue
            if ev is None:
                continue
            last = self._last_event_step.get(ev["type"])
            if last is not None and step - last < self.event_cooldown:
                continue
            self._last_event_step[ev["type"]] = step
            ev.update(step=step, ts=rec["ts"], dominant=rec["dominant"])
            self.events.append(ev)
            _tel.inc("tracing.anomalies")
            _tel.inc("tracing.anomaly.%s" % ev["type"])
            _log.warning("step %d anomaly %s: %s", step, ev["type"],
                         {k: v for k, v in ev.items()
                          if k not in ("type", "step", "ts")})
            if self.profiler is not None:
                if self.profiler.on_anomaly(step, ev):
                    ev["trace_started"] = True
        return rec

    @property
    def step(self) -> int:
        return self._step

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def dump_jsonl(self, path: str) -> int:
        """Write the ring, one record per line; returns record count."""
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
        return len(recs)

    def reset(self):
        with self._lock:
            self._ring.clear()
            self._step = 0
            self._prev = self._raw_values()
            self.events.clear()
            self._last_event_step.clear()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _format_all_stacks() -> str:
    """Every thread's current stack (the post-mortem "where was
    everyone" view: a wedged ring consumer, a dead heartbeat thread)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append("Thread %s (%d):" % (names.get(tid, "?"), tid))
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


# Preemption hooks: callables run from the SIGTERM handler before the
# signal is re-raised (signal-handler context: keep them short and
# non-blocking). A hook may return the string "defer" to suppress the
# immediate re-raise — the deferring component owns termination from
# that point and must re-deliver SIGTERM itself once it is safe (the
# checkpoint manager does this at the next step boundary, where the
# donated packs are whole). Hook exceptions are swallowed: a broken
# hook must not mask the preemption.
_preempt_hooks: List[Callable[[], Optional[str]]] = []
_preempt_lock = threading.Lock()


def register_preempt_hook(fn: Callable[[], Optional[str]]):
    """Run ``fn()`` on SIGTERM before default termination proceeds."""
    with _preempt_lock:
        if fn not in _preempt_hooks:
            _preempt_hooks.append(fn)
    return fn


def unregister_preempt_hook(fn: Callable[[], Optional[str]]):
    with _preempt_lock:
        try:
            _preempt_hooks.remove(fn)
        except ValueError:
            pass


def _run_preempt_hooks() -> bool:
    """Returns True when any hook asked to defer termination."""
    with _preempt_lock:
        hooks = list(_preempt_hooks)
    defer = False
    for fn in hooks:
        try:
            if fn() == "defer":
                defer = True
        except Exception as e:
            try:
                _log.error("preempt hook %r failed: %s", fn, e)
            except Exception:
                pass
    return defer


class FlightRecorder:
    """Dumps the step ring + all-thread stacks + telemetry snapshot
    into a crash directory on unhandled exception, SIGTERM (preemption)
    or SIGUSR1 (operator-requested, run continues).

    ``install()`` chains the previous ``sys.excepthook`` and signal
    handlers; SIGTERM runs the registered preemption hooks and then
    re-raises so the process still terminates with default semantics —
    unless a hook deferred, in which case that hook's owner re-delivers
    the signal itself at the next safe point."""

    def __init__(self, crash_dir: Optional[str] = None, trace=None):
        self.crash_dir = crash_dir or _env.get(
            "MXNET_TPU_CRASH_DIR",
            default=os.path.join(tempfile.gettempdir(), "mxnet_tpu_crash"))
        self._trace = trace
        self._installed = False
        self._prev_excepthook = None
        self._prev_handlers: Dict[int, object] = {}
        self._dump_count = 0

    def _ring(self):
        if self._trace is not None:
            return self._trace
        return _recorder   # the global recorder, if one exists

    def dump(self, reason: str, exc_info=None) -> Optional[str]:
        """Write one dump directory; never raises (a broken disk must
        not mask the original failure). Returns the path or None."""
        try:
            self._dump_count += 1
            d = os.path.join(
                self.crash_dir, "flight-%s-pid%d-%d"
                % (time.strftime("%Y%m%dT%H%M%S"), os.getpid(),
                   self._dump_count))
            os.makedirs(d, exist_ok=True)
            tr = self._ring()
            meta = {"reason": reason, "ts": round(time.time(), 6),
                    "pid": os.getpid(), "rank": worker_rank(),
                    "argv": list(sys.argv),
                    "steps_recorded": tr.step if tr is not None else 0,
                    "events": list(tr.events) if tr is not None else []}
            if exc_info is not None and exc_info[0] is not None:
                meta["exception"] = "".join(
                    traceback.format_exception(*exc_info))
            with open(os.path.join(d, "meta.json"), "w") as f:
                json.dump(meta, f, indent=1)
            with open(os.path.join(d, "stacks.txt"), "w") as f:
                f.write(_format_all_stacks())
            with open(os.path.join(d, "telemetry.json"), "w") as f:
                json.dump(_tel.snapshot(), f, indent=1)
            if tr is not None:
                tr.dump_jsonl(os.path.join(d, "steps.jsonl"))
            # last-K model-health rows from the numerics plane, so a
            # post-mortem shows the numeric trajectory into the failure
            try:
                from . import numwatch as _numwatch

                rows = _numwatch.health_rows()
                if rows:
                    with open(os.path.join(d, "numwatch.jsonl"),
                              "w") as f:
                        for row in rows:
                            f.write(json.dumps(row) + "\n")
            except Exception:
                pass
            _log.error("flight recorder dump (%s) written to %s", reason, d)
            return d
        except Exception as e:
            try:
                _log.error("flight recorder dump failed: %s", e)
            except Exception:
                pass
            return None

    # -- hook installation -------------------------------------------------
    def install(self):
        if self._installed:
            return self
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        for sig in (signal.SIGTERM, signal.SIGUSR1):
            try:
                self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):
                # not the main thread / unsupported platform: exception
                # and explicit dump() paths still work
                pass
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        if sys.excepthook is self._excepthook:
            sys.excepthook = self._prev_excepthook
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        self._installed = False

    def _excepthook(self, etype, value, tb):
        self.dump("exception:%s" % etype.__name__, (etype, value, tb))
        prev = self._prev_excepthook or sys.__excepthook__
        prev(etype, value, tb)

    def _on_signal(self, signum, frame):
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        self.dump("signal:%s" % name)
        if signum == signal.SIGTERM:
            if _run_preempt_hooks():
                # a hook deferred termination (e.g. the checkpoint
                # manager is mid-step and will save at the next step
                # boundary, then re-deliver SIGTERM itself)
                return
            # restore the prior disposition and re-raise so termination
            # proceeds exactly as it would have without us
            prev = self._prev_handlers.get(signum)
            try:
                signal.signal(signum, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            os.kill(os.getpid(), signum)
        # SIGUSR1: dump-and-continue


# ---------------------------------------------------------------------------
# live metrics exposition
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "mxnet_tpu_" + "".join(out)


def _prom_label_value(v) -> str:
    """Escape a label value per the exposition format: backslash, double
    quote and newline must be escaped or standard scrapers reject the
    whole page."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(**labels) -> str:
    """``{k="v",...}`` with escaped values, keys in the given order."""
    return "{%s}" % ",".join('%s="%s"' % (k, _prom_label_value(v))
                             for k, v in labels.items())


def _fmt_le(bound: float) -> str:
    """Prometheus convention: integral bounds print without the
    trailing ``.0`` (``le="10"``, not ``le="10.0"``)."""
    return "%g" % bound


def prometheus_text() -> str:
    """The full registry in the Prometheus text exposition format
    (version 0.0.4). Counters/gauges map directly; histograms emit real
    ``_bucket`` series with cumulative ``le`` labels (closing with
    ``+Inf``) plus exact ``_sum``/``_count``, so a standard scraper or
    the obswatch federator can bucket-merge across replicas. Every
    sample carries the worker rank label; label values are escaped."""
    rank = worker_rank()
    lbl = _prom_labels(rank=rank)
    lines = []
    for name, m in _tel.metrics_items():
        pname = _prom_name(name)
        if isinstance(m, _tel.Counter):
            lines.append("# TYPE %s counter" % pname)
            lines.append("%s%s %d" % (pname, lbl, m.value))
        elif isinstance(m, _tel.Gauge):
            lines.append("# TYPE %s gauge" % pname)
            lines.append("%s%s %s" % (pname, lbl, repr(m.value)))
        elif isinstance(m, _tel.Histogram):
            ex = m.export()
            count = ex.get("count", 0)
            buckets = ex.get("buckets") or {}
            lines.append("# TYPE %s histogram" % pname)
            for bound, cum in zip(buckets.get("bounds", ()),
                                  buckets.get("counts", ())):
                lines.append("%s_bucket%s %d"
                             % (pname,
                                _prom_labels(rank=rank, le=_fmt_le(bound)),
                                cum))
            lines.append("%s_bucket%s %d"
                         % (pname, _prom_labels(rank=rank, le="+Inf"),
                            count))
            lines.append("%s_sum%s %s" % (pname, lbl, repr(ex.get("sum", 0))))
            lines.append("%s_count%s %d" % (pname, lbl, count))
    return "\n".join(lines) + "\n"


class _MetricsHandler(http.server.BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-metrics/1"

    def do_GET(self):   # noqa: N802 (http.server API)
        if self.path.split("?")[0] == "/metrics":
            body = prometheus_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif self.path.split("?")[0] == "/healthz":
            tr = _recorder
            failing = _run_health_probes()
            payload = {
                "status": "degraded" if failing else "ok",
                "pid": os.getpid(),
                "rank": worker_rank(),
                "uptime_s": round(time.time() - self.server.started_at, 3),
                "steps": tr.step if tr is not None else 0,
                "anomalies": len(tr.events) if tr is not None else 0,
            }
            for k, v in _run_health_info().items():
                payload.setdefault(k, v)
            if failing:
                payload["probes"] = failing
            body = json.dumps(payload).encode()
            ctype = "application/json"
            if failing:
                # 503 so a load balancer health check drains the
                # replica without parsing the JSON
                self.send_response(503)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):   # scrapes must not spam stderr
        _log.debug("metrics server: " + fmt, *args)


class MetricsServer:
    """Threaded HTTP server for `/metrics` + `/healthz`; port 0 binds
    an ephemeral port (tests), exposed as ``.port``."""

    def __init__(self, port: int, host: str = ""):
        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _MetricsHandler)
        self._httpd.daemon_threads = True
        self._httpd.started_at = time.time()
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxtpu-metrics",
            daemon=True)
        self._thread.start()

    def stop(self):
        """Shut down the HTTP server AND join its serve thread: after
        this returns no ``mxtpu-metrics`` thread is alive (the
        thread/process-leak fixture in tests/conftest.py depends on
        that). Idempotent."""
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        th = self._thread
        if th is not None:
            self._thread = None
            th.join(timeout=5.0)
            if th.is_alive():
                _log.warning("MetricsServer.stop: serve thread still "
                             "alive after 5s join; leaking the (daemon) "
                             "thread rather than hanging teardown")

    # historical name, kept for callers that treat this like a file
    close = stop


# ---------------------------------------------------------------------------
# process-global wiring
# ---------------------------------------------------------------------------

_init_lock = threading.Lock()
_recorder: Optional[StepTrace] = None
_metrics_server: Optional[MetricsServer] = None
_flight_recorder: Optional[FlightRecorder] = None
_watchdog = None                 # sanitizers.DeadlockWatchdog
_atexit_registered = False
_worker_rank = int(os.environ.get("MXTPU_WORKER_RANK", "0") or 0)


def set_worker_rank(rank: int):
    """Tag exported metrics with this process's worker rank (called by
    ``kvstore.create`` so dist runs are distinguishable per-worker)."""
    global _worker_rank
    _worker_rank = int(rank)


def worker_rank() -> int:
    return _worker_rank


def step_trace() -> StepTrace:
    """The process-global step recorder (created on first use)."""
    global _recorder
    if _recorder is None:
        with _init_lock:
            if _recorder is None:
                _recorder = StepTrace()
    return _recorder


def record_step(latency_ms: float, extra: Optional[dict] = None):
    """Fit-loop hook: record one step into the global ring. No-op
    (one flag check) while telemetry is disabled."""
    if not _tel._ENABLED:
        return None
    return step_trace().record(latency_ms, extra)


def maybe_init():
    """Env-driven one-shot setup, called at fit()/bench entry: start
    the metrics server when ``MXNET_TPU_METRICS_PORT`` is set, install
    the flight recorder when ``MXNET_TPU_FLIGHT_RECORDER=1``, start
    the deadlock watchdog when ``MXNET_TPU_SANITIZE`` includes
    ``deadlock``. Registers :func:`shutdown` with atexit on first use,
    so a fit() that never reaches explicit teardown still stops the
    server/watchdog threads. Idempotent; one flag check while
    telemetry is disabled."""
    if not _tel._ENABLED:
        return None
    global _metrics_server, _flight_recorder, _watchdog, \
        _atexit_registered
    with _init_lock:
        port = _env.get("MXNET_TPU_METRICS_PORT")
        if _metrics_server is None and port:
            try:
                _metrics_server = MetricsServer(int(port))
                _log.info("metrics server listening on :%d (/metrics, "
                          "/healthz)", _metrics_server.port)
            except (OSError, ValueError) as e:
                _log.warning("metrics server failed to start on %r: %s",
                             port, e)
        if _flight_recorder is None \
                and _env.get("MXNET_TPU_FLIGHT_RECORDER"):
            _flight_recorder = FlightRecorder().install()
        if _watchdog is None:
            from .analysis import sanitizers as _san
            if _san.enabled("deadlock"):
                _watchdog = _san.DeadlockWatchdog().start()
                _log.info("deadlock watchdog armed (threshold %.0fs)",
                          _watchdog._threshold)
        if not _atexit_registered:
            import atexit
            atexit.register(shutdown)
            _atexit_registered = True
    return _metrics_server


def metrics_server() -> Optional[MetricsServer]:
    return _metrics_server


def flight_recorder() -> Optional[FlightRecorder]:
    return _flight_recorder


def ensure_flight_recorder() -> FlightRecorder:
    """Install the global flight recorder even when the
    ``MXNET_TPU_FLIGHT_RECORDER`` env flag is off. The checkpoint
    manager's SIGTERM grace path needs its signal routing (preempt
    hooks run from ``_on_signal``) regardless of whether the operator
    asked for crash dumps. Registers :func:`shutdown` with atexit so
    the handlers are uninstalled on interpreter exit."""
    global _flight_recorder, _atexit_registered
    with _init_lock:
        if _flight_recorder is None:
            _flight_recorder = FlightRecorder().install()
        if not _atexit_registered:
            import atexit
            atexit.register(shutdown)
            _atexit_registered = True
        return _flight_recorder


def shutdown():
    """Tear down global state (tests / end of run / atexit): stop the
    server (joining its thread), stop the watchdog, uninstall
    flight-recorder hooks, drop the recorder. Idempotent."""
    global _recorder, _metrics_server, _flight_recorder, _watchdog
    with _init_lock:
        server, _metrics_server = _metrics_server, None
        watchdog, _watchdog = _watchdog, None
        if _flight_recorder is not None:
            _flight_recorder.uninstall()
            _flight_recorder = None
        _recorder = None
    # join threads OUTSIDE _init_lock: the watchdog's progress probe
    # takes _init_lock via step_trace(), so joining it under the lock
    # would stall shutdown until the join timeout
    if server is not None:
        server.stop()
    if watchdog is not None:
        watchdog.stop()
