"""Training callbacks (reference ``python/mxnet/callback.py``)."""
from __future__ import annotations

import logging
import math
import time

from . import telemetry as _tel

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric",
           "module_checkpoint", "ProgressBar"]


def do_checkpoint(prefix: str, period: int = 1,
                  save_optimizer_states: bool = False, mod=None):
    """Save params every ``period`` epochs (reference do_checkpoint).

    ``save_optimizer_states=True`` additionally writes the updater's
    ``prefix-NNNN.states`` file so a resumed run keeps its momentum /
    update counts; it needs the module itself (the epoch-end callback
    signature only carries (sym, arg, aux)), so pass ``mod=``."""
    from .model import save_checkpoint

    period = int(max(1, period))
    if save_optimizer_states and mod is None:
        raise ValueError("do_checkpoint(save_optimizer_states=True) "
                         "needs mod= (the bound module that owns the "
                         "optimizer states)")

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            if save_optimizer_states:
                mod.save_checkpoint(prefix, iter_no + 1,
                                    save_optimizer_states=True)
            else:
                save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def module_checkpoint(mod, prefix: str, period: int = 1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def log_train_metric(period: int, auto_reset: bool = False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """samples/sec logging (reference Speedometer), plus a partial
    tail-window report at epoch end (``epoch_end``, invoked by the fit
    loop) so the batches after the last frequent boundary are accounted
    instead of silently dropped."""

    def __init__(self, batch_size: int, frequent: int = 50):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0.0
        self.last_count = 0
        self._tic_count = 0

    def _emit(self, epoch, count, n_batches, elapsed, eval_metric,
              tail=False):
        # a sub-clock-resolution window on a very fast loop must not
        # ZeroDivisionError the whole training run
        speed = n_batches * self.batch_size / max(elapsed, 1e-9)
        if _tel.enabled():
            _tel.set_gauge("train.samples_per_sec", speed)
            _tel.inc("train.batches", n_batches)
        where = "Batch [%d]%s" % (count, " tail(%d)" % n_batches
                                  if tail else "")
        if eval_metric is not None:
            msg = "Epoch[%d] %s\tSpeed: %.2f samples/sec" \
                % (epoch, where, speed)
            for name, value in eval_metric.get_name_value():
                msg += "\t%s=%f" % (name, value)
            # model health without full tracing: numwatch's cadence
            # fetch leaves the latest global grad norm in a gauge
            gn = _tel.peek("numwatch.grad_norm", kind="gauge")
            if gn is not None:
                msg += "\tgrad_norm=%.4g" % gn
            logging.info(msg)
        else:
            logging.info("Iter[%d] %s\tSpeed: %.2f samples/sec",
                         epoch, where, speed)

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0 and count > self._tic_count:
                self._emit(param.epoch, count, count - self._tic_count,
                           time.time() - self.tic, param.eval_metric)
                self.tic = time.time()
                self._tic_count = count
        else:
            self.init = True
            self.tic = time.time()
            self._tic_count = count

    def epoch_end(self, param):
        """Report the window still open when the epoch ends off a
        frequent boundary; the fit loop calls this after its last batch."""
        if not self.init:
            return
        tail = self.last_count - self._tic_count
        if tail > 0:
            self._emit(param.epoch, param.nbatch, tail,
                       time.time() - self.tic, param.eval_metric, tail=True)
        self.init = False


class ProgressBar:
    """Simple progress bar (reference ProgressBar)."""

    def __init__(self, total: int, length: int = 80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        filled_len = int(round(self.bar_len * param.nbatch / float(self.total)))
        percents = math.ceil(100.0 * param.nbatch / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        print("[%s] %s%s\r" % (prog_bar, percents, "%"), end="")
