"""Fused train step: one donated XLA dispatch per training batch.

The classic fit() loop issues three host dispatches per batch —
``forward_backward`` (one fused fwd+bwd computation), ``update`` (one
donated kernel per optimizer structure group) and ``update_metric``
(a fold or an eager ``asnumpy`` sync) — and the gaps between them are
pure host overhead on an accelerator (BENCH_r05: 15.8% model MFU vs
30.7% XLA-reported MFU, i.e. roughly half the step was dispatch gaps
and syncs). This module compiles the whole batch into a SINGLE
``jax.jit`` call:

    params', outputs, aux', opt_states', metric_acc' =
        step(params, data/labels, aux, opt_states, hyper_vec, acc, key)

* forward+backward via ``jax.vjp`` through the executor's own
  ``_run_graph`` (same numerics, same mixed-precision casts),
* the optimizer update via the same ``_update_math`` pure functions the
  unfused donated kernels use (hyperparameters ride in traced f32
  matrices, so an LRScheduler never forces a recompile),
* the metric fold via :meth:`EvalMetric.device_fold` into a cumulative
  on-device ``(sum, count)`` accumulator (host fetch only in ``get()``).

Params, aux states, optimizer states and the metric accumulator are
DONATED: XLA writes the new values into the old HBM buffers, so the
step holds one copy of the training state. The data/label buffers are
NOT donated — the caller's batch arrays stay readable after the step.

Data parallelism rides for free: the executor group shards the batch
over its device mesh (GSPMD), so the gradient all-reduce happens inside
this same computation — there is no separate aggregation phase to fuse.

Opt-in via ``MXNET_TPU_FUSED_STEP=1`` — or DEFAULT under a
``device_sync`` kvstore (the in-jit GSPMD gradient exchange: batch
sharded along the ``dp`` mesh axis, params/optimizer state replicated,
and the vjp gradients pinned to a replicated ``NamedSharding`` so the
mean-psum all-reduce runs inside this one dispatch; gate with
``MXNET_TPU_DEVICE_SYNC_FUSED=0``). :func:`make_fused_step` returns
None (-> classic three-phase loop) whenever a precondition fails:
``dist_*`` kvstores, ``update_on_kvstore``, custom-update optimizers
without a fusable plan, grad_req "add", ``inputs_need_grad``, or a
monitor with a custom ``stat_func`` (which needs every internal
tensor; default-stat monitors ride the numwatch stats pack instead —
see ``mxnet_tpu/numwatch.py``). A requested-but-failed precondition
counts ``step.fused_fallback[.reason]`` and warns once naming the
reason.

Telemetry: ``step.dispatches`` counts XLA computation launches per
batch on both paths (the fused-vs-unfused delta BENCH_r06 reports);
``step.fused_recompiles`` counts fresh trace signatures (a shape-driven
recompile storm trips the tracing RecompileDetector);
``step.fused_fallback`` counts requested-but-refused configurations.
"""
from __future__ import annotations

from . import telemetry as _tel
from . import env as _env
from . import xprof as _xprof
from .analysis import sanitizers as _san
from .engine import get_engine
from .executor import zero_cotangent

__all__ = ["enabled", "make_fused_step", "FusedTrainStep",
           "make_fused_infer", "FusedInfer"]


def enabled() -> bool:
    """MXNET_TPU_FUSED_STEP=1 requests the fused path (default off)."""
    return _env.get("MXNET_TPU_FUSED_STEP")


_FALLBACK_WARNED = set()


def _fallback(module, reason, detail):
    """A config requested the fused step but a precondition failed: count
    it (`step.fused_fallback` + per-reason key, the trace_report
    `fallbacks` column) and warn ONCE per reason naming what to change —
    the old silent None meant exactly the configs that matter at scale
    quietly ran the three-dispatch loop."""
    _tel.inc("step.fused_fallback")
    _tel.inc("step.fused_fallback." + reason)
    if reason not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(reason)
        import logging

        getattr(module, "logger", logging).warning(
            "fused train step requested but falling back to the classic "
            "three-phase loop: %s [reason=%s]", detail, reason)
    return None


def make_fused_step(module, eval_metric):
    """Build a :class:`FusedTrainStep` for a bound, optimizer-initialized
    Module, or None when any precondition fails (fit() then runs the
    classic forward_backward/update/update_metric loop). The fused path
    is requested by MXNET_TPU_FUSED_STEP=1 — or by default under a
    ``device_sync`` kvstore (in-jit GSPMD gradient exchange; gate with
    MXNET_TPU_DEVICE_SYNC_FUSED=0). A requested-but-failed precondition
    is NOT silent: it counts ``step.fused_fallback[.reason]`` and warns
    once per reason."""
    kv = module._kvstore
    requested = enabled()
    if not requested:
        # device_sync asks for the fused path by contract: its gradient
        # exchange IS the in-jit collective, there is no push/pull round
        # for the classic loop to ride
        requested = (getattr(kv, "in_jit_gradient_exchange", False)
                     and _env.get("MXNET_TPU_DEVICE_SYNC_FUSED"))
    if not requested:
        return None   # not a fallback: fused was never asked for
    if not module.optimizer_initialized or module._update_on_kvstore:
        return _fallback(module, "kvstore_update",
                         "the optimizer update runs on the kvstore "
                         "(dist server-side update), which the fused "
                         "step cannot subsume")
    # inline-dispatch engines only: the write-back closure assigns
    # executor/metric state the fit loop reads right back; a threaded
    # engine would run it on a worker while the loop races ahead
    from .engine import NaiveEngine, XLAEngine

    if type(get_engine()) not in (XLAEngine, NaiveEngine):
        return _fallback(module, "threaded_engine",
                         "a threaded engine is active; the fused step "
                         "needs an inline engine (MXNET_ENGINE_TYPE="
                         "XLAEngine or NaiveEngine)")
    if kv is not None and not getattr(kv, "fused_step_compatible", False):
        # a kvstore that knows WHY it can't fuse names the surviving
        # host path (dist_host_exchange / dist_async_host) so the
        # telemetry points at the actual byte movement, not just "dist"
        reason, detail = getattr(kv, "fused_fallback", None) or (
            "dist_kvstore",
            "kvstore %r moves gradient bytes between dispatches; use a "
            "local/device/device_sync store to fuse" % kv.type)
        return _fallback(module, reason, detail)
    if module.inputs_need_grad:
        return _fallback(module, "inputs_need_grad",
                         "inputs_need_grad=True requires materialized "
                         "input gradients the fused step never builds")
    ex = module._exec_group.executor
    if ex._monitor_callback is not None:
        # a default-stat Monitor is expressible from the numwatch stats
        # pack and rides the fused step (maybe_plane routes it); only a
        # custom stat_func still needs every internal tensor host-side
        from . import numwatch as _numwatch

        mon = getattr(ex._monitor_callback, "__self__", None)
        if not _numwatch.monitor_routable(mon):
            return _fallback(module, "monitor_custom",
                             "an installed monitor with a custom "
                             "stat_func needs every internal tensor; "
                             "the fused step keeps them in-graph "
                             "(default-stat monitors ride the numwatch "
                             "pack)")
    # grad_req "add" accumulates across batches in the grad arrays; the
    # fused step never materializes per-param grads, so it can't honor it
    if any(ex._grad_req[ex.arg_names[i]] != "write" for i in ex._grad_idx):
        return _fallback(module, "grad_req",
                         "grad_req != \"write\" accumulates into grad "
                         "arrays the fused step never materializes")
    opt = module._optimizer
    if not opt._fusable() or not _env.get("MXNET_TPU_FUSED_UPDATE"):
        return _fallback(module, "optimizer",
                         "optimizer %s has no fusable update plan (or "
                         "MXNET_TPU_FUSED_UPDATE=0)"
                         % type(opt).__name__)
    # every grad-bearing arg must map onto an updater slot
    param_idx = {n: i for i, n in enumerate(module._param_names)}
    if any(ex.arg_names[i] not in param_idx for i in ex._grad_idx):
        return _fallback(module, "unmapped_grad_arg",
                         "a grad-bearing arg has no updater slot "
                         "(param list out of sync with the graph)")
    return FusedTrainStep(module, eval_metric)


class FusedTrainStep:
    """One-dispatch training step bound to a Module's executor group.

    Host work per batch is only what CANNOT trace: ``load_data_batch``
    (H2D), the optimizer's per-step plan (update counts, lr schedule —
    plans must not read the gradient, which never exists host-side
    here), and the engine push of the write-back closure.
    """

    def __init__(self, module, eval_metric):
        self._module = module
        self._group = module._exec_group
        self._executor = ex = self._group.executor
        self._optimizer = module._optimizer
        self._updater = module._updater

        param_idx = {n: i for i, n in enumerate(module._param_names)}
        self._p_arg_idx = list(ex._grad_idx)
        in_p = set(self._p_arg_idx)
        self._o_arg_idx = [i for i in range(len(ex.arg_names))
                           if i not in in_p]
        self._p_upd_idx = [param_idx[ex.arg_names[i]]
                           for i in self._p_arg_idx]

        # label positions within the non-donated arg pack, for the fold
        o_pos = {arg_i: pos for pos, arg_i in enumerate(self._o_arg_idx)}
        arg_pos = {n: i for i, n in enumerate(ex.arg_names)}
        self._label_o_pos = [o_pos[arg_pos[d.name]]
                             for d in self._group.label_shapes
                             if d.name in arg_pos]
        # data positions, for the device-feed mode: a CachedImageRecordIter
        # batch with ``batch.aug`` ships raw uint8 frames that ride these
        # slots of the non-donated pack; cast+crop+mirror+normalize run
        # inside the jit before the forward pass
        self._data_o_pos = [o_pos[arg_pos[d.name]]
                            for d in self._group.data_shapes
                            if d.name in arg_pos]
        self._fold_leaves = self._foldable_leaves(eval_metric)

        # the numerics plane (env-armed, or implicitly by a routable
        # Monitor): its stats pack rides this step's donated state
        from . import numwatch as _numwatch

        self._numwatch = _numwatch.maybe_plane(self)

        # optimizer states must exist before the first trace
        for upd_i, arg_i in zip(self._p_upd_idx, self._p_arg_idx):
            if upd_i not in self._updater.states:
                self._updater.states[upd_i] = \
                    self._optimizer.create_state(upd_i,
                                                 ex.arg_arrays[arg_i])

        self._jit_cache = {}
        self._seen_sigs = set()
        self._retrace_san = (_san.RetraceSanitizer()
                             if _san.enabled("retrace") else None)

    def _foldable_leaves(self, eval_metric):
        """The metric's leaves when EVERY one can fold on device (and a
        label exists per output); None -> metric updates host-side from
        the step's outputs (still one dispatch for fwd+bwd+update)."""
        from . import metric as _metric

        leaves = (list(eval_metric.metrics)
                  if isinstance(eval_metric, _metric.CompositeEvalMetric)
                  else [eval_metric])
        if not leaves or not self._label_o_pos:
            return None
        if len(self._label_o_pos) != len(self._executor.output_names):
            return None
        if not all(lf.has_device_fold and lf.num is None for lf in leaves):
            return None
        return leaves

    # ------------------------------------------------------------------
    # checkpoint support (checkpoint.py)
    @property
    def trace_cache_size(self) -> int:
        """Distinct trace signatures seen (== jit retraces). A resume
        that re-places restored state with the same avals/shardings as
        fresh init must NOT grow this — the elastic-rejoin tests assert
        the delta across a restore is zero."""
        return len(self._seen_sigs)

    def state_arrays(self):
        """The donated training-state NDArrays by role — the exact
        packs :mod:`mxnet_tpu.checkpoint` snapshots/restores, derived
        from the same index maps the dispatch uses so the two can never
        disagree about what "full state" means.

        Returns ``{"params": {name: NDArray}, "aux": {name: NDArray},
        "updater_slots": {upd_i: param_name}}``.
        """
        ex = self._executor
        params = {ex.arg_names[i]: ex.arg_arrays[i]
                  for i in self._p_arg_idx}
        aux = dict(zip(self._group.aux_names, ex.aux_arrays))
        slots = {upd_i: ex.arg_names[arg_i]
                 for upd_i, arg_i in zip(self._p_upd_idx,
                                         self._p_arg_idx)}
        return {"params": params, "aux": aux, "updater_slots": slots}

    # ------------------------------------------------------------------
    def step(self, data_batch, eval_metric):
        """Run one training batch as one XLA dispatch."""
        import jax.numpy as jnp

        ex = self._executor
        aug = getattr(data_batch, "aug", None)
        if aug is not None and len(self._data_o_pos) != 1:
            # in-graph augmentation is defined for the single image input
            # the cached iterators produce; anything else materializes
            from .io_cache import materialize_device_feed

            data_batch = materialize_device_feed(data_batch)
            aug = None
        if aug is None:
            self._group.load_data_batch(data_batch)
        else:
            # device feed: only the labels go through the normal loader;
            # the raw uint8 frames bypass the executor's (float, cropped)
            # data buffer and ride the non-donated pack directly
            self._group.load_label_batch(data_batch)

        opt = self._optimizer
        states = self._updater.states
        clip = opt.clip_gradient
        rescale = opt.rescale_grad
        # host-side per-step plans (update counts, lr schedule); grouped
        # by (kind, n_states) exactly like Optimizer.update_multi
        groups = {}
        for pos, upd_i in zip(range(len(self._p_arg_idx)),
                              self._p_upd_idx):
            w = ex.arg_arrays[self._p_arg_idx[pos]]
            kind, st, scalars = opt._plan(upd_i, w, w, states[upd_i])
            full = (rescale,) + tuple(scalars) \
                + ((clip,) if clip is not None else ())
            groups.setdefault((kind, len(st)), []).append(
                (pos, tuple(st), full))
        specs = []
        state_nds = []
        sv_mats = []
        # sanctioned H2D: the host-side update plans become one small
        # device mat per param group (graftlint: jnp.asarray of a host
        # list; transfer sanitizer: explicit allow window)
        mesh = getattr(self._group, "_mesh", None)
        with _san.intentional_transfer():
            rep = None
            if mesh is not None:
                # pre-place replicated on the mesh: leaving the mats on
                # device 0 would make every dispatch an implicit d2d
                import jax
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(mesh, PartitionSpec())
            for (kind, n_states), members in groups.items():
                specs.append((kind, n_states,
                              tuple(m[0] for m in members)))
                state_nds.append(tuple(m[1] for m in members))
                mat = jnp.asarray([m[2] for m in members], jnp.float32)
                if rep is not None:
                    mat = jax.device_put(mat, rep)
                sv_mats.append(mat)
        specs = tuple(specs)

        from .optimizer import _donation_ok

        donate = _donation_ok()
        fold = self._fold_leaves is not None
        feed = None
        if aug is not None:
            # static augmentation config; the per-batch offsets/flags and
            # mean/scale are traced arguments, so a new batch (or an lr-
            # style mean/scale change) never recompiles
            d0 = self._group.data_shapes[0].shape
            nchw = aug["layout"] == "NCHW"
            if nchw:
                c, h, w = d0[1], d0[2], d0[3]
            else:
                h, w, c = d0[1], d0[2], d0[3]
            feed = (nchw, h, w, c)
        nw = self._numwatch
        ck = (specs, clip is not None, donate, fold, feed,
              None if nw is None else nw.trace_key)
        fn = self._jit_cache.get(ck)
        if fn is None:
            fn = self._build(specs, clip is not None, donate, fold, feed,
                             watch=nw)
            self._jit_cache[ck] = fn

        with _san.intentional_transfer():
            # fold_in of the host step counter: the one int H2D per step
            key = ex._key()
        ex._last_key = key
        p_nds = [ex.arg_arrays[i] for i in self._p_arg_idx]
        o_nds = [ex.arg_arrays[i] for i in self._o_arg_idx]
        p_vals = [nd._data for nd in p_nds]
        o_vals = [nd._data for nd in o_nds]
        aug_vals = None
        if aug is not None:
            grp = self._group
            # uint8 frames, batch-sharded like any data arg (the H2D
            # moved 1/4 the float bytes; nd.array counted it already)
            o_vals[self._data_o_pos[0]] = \
                grp._place(data_batch.data[0], 0)._data
            import numpy as _np

            aug_vals = (
                grp._place(_np.asarray(aug["tops"],  # graft: host-sync
                                       _np.int32), 0)._data,
                grp._place(_np.asarray(aug["lefts"],  # graft: host-sync
                                       _np.int32), 0)._data,
                grp._place(_np.asarray(aug["mirror"],  # graft: host-sync
                                       bool), 0)._data,
                grp._place(_np.asarray(aug["mean"],  # graft: host-sync
                                       _np.float32), None)._data,
                grp._place(_np.asarray(aug["scale"],  # graft: host-sync
                                       _np.float32), None)._data,
            )
            _tel.inc("step.fused_feed_batches")
        aux_vals = [a._data for a in ex.aux_arrays]
        st_vals = tuple(
            tuple(tuple(s._data for s in member) for member in grp)
            for grp in state_nds)
        leaves = self._fold_leaves if fold else ()
        accs = []
        for leaf in leaves:
            acc = leaf._device_acc
            if acc is None:
                # placed to match the (possibly mesh-sharded) params so
                # the jit sees one consistent device set; two distinct
                # buffers because the acc pack is donated
                from .metric import _replicated_zero

                like = p_vals[0] if p_vals else None
                with _san.intentional_transfer():
                    acc = (_replicated_zero(like),
                           _replicated_zero(like))
            accs.append(tuple(acc))
        accs = tuple(accs)
        stats = None
        if nw is not None:
            # the numerics stats pack is donated like the accs: placed
            # once (replicated on the params' mesh), swapped in-place by
            # every dispatch's write-back
            with _san.intentional_transfer():
                stats = nw.device_pack(p_vals[0] if p_vals else None)

        # a fresh (shape, dtype, spec) signature means jax retraces and
        # XLA recompiles — in steady state that's the silent stall the
        # RecompileDetector turns into an anomaly event
        sig = ck + (tuple((v.shape, str(v.dtype))
                          for v in p_vals + o_vals + aux_vals),)
        if sig not in self._seen_sigs:
            self._seen_sigs.add(sig)
            _tel.inc("step.fused_recompiles")
        if self._retrace_san is not None:
            self._retrace_san.check(len(self._seen_sigs))

        module = self._module
        mut = [nd._var for nd in p_nds] \
            + [a._var for a in ex.aux_arrays] \
            + [s._var for grp in state_nds for member in grp
               for s in member]

        def _do():
            _tel.inc("step.dispatches")
            if nw is not None:
                args = (p_vals, o_vals, aux_vals, st_vals, sv_mats,
                        accs, stats, key)
            else:
                args = (p_vals, o_vals, aux_vals, st_vals, sv_mats,
                        accs, key)
            if aug_vals is not None:
                args = args + (aug_vals,)
            res = fn(*args)
            if nw is not None:
                new_p, outs, aux_out, new_st, new_accs, new_stats = res
                nw.write_back(new_stats)
            else:
                new_p, outs, aux_out, new_st, new_accs = res
            for nd, v in zip(p_nds, new_p):
                nd._data = v
            for nd, v in zip(ex.aux_arrays, aux_out):
                nd._data = v
            for grp, new_grp in zip(state_nds, new_st):
                for member, new_member in zip(grp, new_grp):
                    for snd, sv in zip(member, new_member):
                        snd._data = sv
            for leaf, acc in zip(leaves, new_accs):
                leaf._device_acc = acc
            ex._set_outputs(outs)
            ex._train_pending = False
            if donate and _san.enabled("donation"):
                # argnums (0, 2, 3, 5[, 6]): params, aux, opt states,
                # accs, and the numwatch stats pack when armed
                _san.DonationSanitizer.check(
                    "the fused step",
                    p_vals + aux_vals
                    + [s for g in st_vals for m in g for s in m]
                    + [a for acc in accs for a in acc]
                    + ([stats] if stats is not None else []))
            return list(new_p)

        get_engine().push(_do, const_vars=[nd._var for nd in o_nds],
                          mutable_vars=mut, prop="fused_step")
        module._params_dirty = True
        _tel.inc("step.fused_steps")
        if not fold:
            # unsupported metric: update host-side from the fused step's
            # outputs — still one dispatch for fwd+bwd+update
            eval_metric.update(data_batch.label, ex.outputs)

    # ------------------------------------------------------------------
    def _build(self, specs, clipped, donate, fold, feed=None, watch=None):
        """Trace+compile the whole-batch step for one (structure,
        donation, fold, feed) configuration. With ``feed`` set the data
        slot of the non-donated pack holds raw uint8 stored frames and
        ``aug`` carries (tops, lefts, mirror, mean, scale): cast + crop +
        mirror + normalize + layout run in-graph, the same math (and so
        the same bits) as CachedImageRecordIter._device_augment, fused
        into the one donated dispatch."""
        import jax
        import jax.numpy as jnp

        from .optimizer import _update_math

        ex = self._executor
        run_graph = ex._run_graph
        n_args = len(ex.arg_names)
        # in-jit gradient exchange: with the batch sharded over the
        # mesh's data axes, pinning each vjp gradient to its PARAM's
        # sharding makes GSPMD lower the exchange INSIDE this dispatch
        # (rescale_grad is 1/global_batch, so the sum over shards is the
        # mean). A replicated param gets a mean-psum all-reduce; an
        # fsdp-sharded param gets the ZeRO reduce-scatter (each device
        # keeps only its shard of the reduced grad, then updates only
        # its shard of the param/opt-state). Without the constraint the
        # partitioner may defer the reduce into the update — correct but
        # unpinned; with it the collective is a guaranteed,
        # xprof-visible op between backward and update. The kvstore's
        # reduce spec (DeviceSyncKVStore.grad_reduce_sharding) owns the
        # mapping so future recipes can widen it without touching this.
        grad_shardings = None
        mesh = getattr(self._group, "_mesh", None)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            kv = getattr(self._module, "_kvstore", None)
            reduce_spec = getattr(kv, "grad_reduce_sharding", None)
            grad_shardings = []
            param_shardings = []
            for i in self._p_arg_idx:
                ps = self._group.param_sharding(ex.arg_names[i]) or rep
                param_shardings.append(ps)
                if reduce_spec is not None:
                    ps = reduce_spec(mesh, ps) or ps
                grad_shardings.append(ps)
        p_idx = list(self._p_arg_idx)
        o_idx = list(self._o_arg_idx)
        label_pos = list(self._label_o_pos)
        data_pos = self._data_o_pos[0] if self._data_o_pos else None
        leaves = self._fold_leaves or ()
        math_fns = {(kind, n): _update_math(kind, n, clipped)
                    for kind, n, _ in specs}

        _tel.inc("executor.jit_build")

        def _augment(x, aug):
            nchw, h, w, c = feed
            tops, lefts, mirror, mean, scale = aug

            def one(img, t, l, mi):
                crop = jax.lax.dynamic_slice(img, (t, l, 0), (h, w, c))
                return jnp.where(mi, crop[:, ::-1], crop)

            y = jax.vmap(one)(x, tops, lefts, mirror)
            y = (y.astype(jnp.float32) - mean) * scale
            return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y

        def _core(p_vals, o_vals, aux, st, sv_mats, accs, stats, key,
                  aug=None):
            full = [None] * n_args
            for pos, i in enumerate(o_idx):
                full[i] = o_vals[pos]
            if feed is not None:
                full[o_idx[data_pos]] = _augment(o_vals[data_pos], aug)

            def f(pv):
                fl = list(full)
                for pos, i in enumerate(p_idx):
                    fl[i] = pv[pos]
                return run_graph(fl, aux, key, True)

            res, vjp = jax.vjp(f, list(p_vals))
            outs, aux_out = res
            heads = [jnp.ones_like(o)
                     if jnp.issubdtype(o.dtype, jnp.inexact)
                     else zero_cotangent(o) for o in outs]
            cts = (heads, jax.tree_util.tree_map(zero_cotangent, aux_out))
            grads, = vjp(cts)
            if grad_shardings is not None:
                grads = [jax.lax.with_sharding_constraint(g, s)
                         for g, s in zip(grads, grad_shardings)]
            new_p = list(p_vals)
            new_st = []
            for gi, (kind, n_states, positions) in enumerate(specs):
                math_fn = math_fns[(kind, n_states)]
                grp = []
                for j, pos in enumerate(positions):
                    nw, ns = math_fn(new_p[pos], grads[pos], st[gi][j],
                                     sv_mats[gi][j])
                    new_p[pos] = nw
                    grp.append(ns)
                new_st.append(tuple(grp))
            if grad_shardings is not None:
                # keep the updated params on their (fsdp) shardings so
                # GSPMD never gathers them just to re-scatter on entry
                # to the next step
                new_p = [jax.lax.with_sharding_constraint(p, s)
                         for p, s in zip(new_p, param_shardings)]
            new_accs = accs
            labels = [o_vals[p] for p in label_pos]
            if fold:
                new_accs = []
                for leaf, (s, c) in zip(leaves, accs):
                    for lab, pred in zip(labels, outs):
                        ds, dc = leaf.device_fold(lab, pred)
                        s = s + ds
                        c = c + dc
                    new_accs.append((s, c))
                new_accs = tuple(new_accs)
            new_p = tuple(new_p)
            new_st = tuple(new_st)
            if watch is None:
                return (new_p, outs, aux_out, new_st, new_accs)
            # numerics stats fold — same trace, same dispatch
            new_stats, grads_ok = watch.fold(stats, p_vals, grads,
                                             new_p, outs, labels)
            if watch.skip_guard:
                # nonfinite grads: select the step k-1 training state
                # in-graph (params/opt-state/metric accs bit-identical
                # to the pre-step buffers) — still one dispatch; the
                # pack itself always advances so the host sees the skip
                keep = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(grads_ok, new, old),
                    (new_p, new_st, new_accs),
                    (tuple(p_vals), tuple(st), tuple(accs)))
                new_p, new_st, new_accs = keep
            return (new_p, outs, aux_out, new_st, new_accs, new_stats)

        # route the compile through the device observability plane: a
        # plain jax.jit when xprof is off, else the AOT wrapper that
        # times the compile, records FLOPs/memory/op breakdown and the
        # retrace-cause diff — still the same one donated dispatch.
        # leaf names come from the executor, so a retrace diff says
        # "batch.data" / "params.fc1_weight" instead of "arg1[0]"
        names = [ex.arg_names[i] for i in self._p_arg_idx]
        batch_names = [ex.arg_names[i] for i in self._o_arg_idx]
        # consult the autotuner's best-config cache once per build:
        # tuned kernel choices were already applied while tracing the
        # ops above (ops/nn.py reads the same cache), this records the
        # consultation for observability — nothing runs per dispatch
        from . import autotune as _autotune
        _autotune.note_build("fused_step")
        if watch is not None:
            # the stats pack joins the donated set (argnum 6)
            def step(p_vals, o_vals, aux, st, sv_mats, accs, stats, key,
                     aug=None):
                return _core(p_vals, o_vals, aux, st, sv_mats, accs,
                             stats, key, aug)

            arg_names = (tuple("params." + n for n in names),
                         tuple("batch." + n for n in batch_names),
                         "aux", "opt_state", "hyper", "metric_acc",
                         "numwatch_pack", "rng_key", "aug")
            donate_argnums = (0, 2, 3, 5, 6)
        else:
            def step(p_vals, o_vals, aux, st, sv_mats, accs, key,
                     aug=None):
                return _core(p_vals, o_vals, aux, st, sv_mats, accs,
                             None, key, aug)

            arg_names = (tuple("params." + n for n in names),
                         tuple("batch." + n for n in batch_names),
                         "aux", "opt_state", "hyper", "metric_acc",
                         "rng_key", "aug")
            donate_argnums = (0, 2, 3, 5)
        return _xprof.jit(
            step, site="fused_step", arg_names=arg_names,
            donate_argnums=donate_argnums if donate else ())


# ---------------------------------------------------------------------------
# fused inference
# ---------------------------------------------------------------------------

def make_fused_infer(executor, data_names, top_k=0, mesh=None):
    """Build a :class:`FusedInfer` over a bound executor: forward plus
    on-device argmax/top-k post-processing compiled into ONE dispatch
    per batch, with the non-data args (params + BN stats) packed and
    device-placed once. Unlike the train step nothing is donated — the
    same executable serves every subsequent batch of the same shape.

    ``data_names`` are the per-request argument slots; every other arg
    is part of the params pack. ``top_k=0`` skips post-processing,
    ``top_k=1`` appends an argmax over the last axis of the first
    output, ``top_k>1`` appends ``jax.lax.top_k`` values+indices.
    ``mesh`` shards the batch axis of incoming data across its data
    axes (``dp``); on a ``(dp, tp)`` mesh the params pack additionally
    NamedSharding-shards along ``tp`` (per-param dim via
    :func:`~mxnet_tpu.parallel.sharding.tp_param_spec`) so a model
    bigger than one chip's HBM serves from the shards, with the
    activation resharding collectives emitted by GSPMD INSIDE the one
    dispatch. Off a tp mesh the pack replicates as before."""
    return FusedInfer(executor, data_names, top_k=top_k, mesh=mesh)


class FusedInfer:
    """Compiled-once single-dispatch inference step.

    Host work per batch is only the H2D of the request data (sanctioned
    transfer window; skipped entirely when the caller hands over
    already-placed jax arrays) and the executable lookup. Params are
    packed at construction (refresh with :meth:`refresh_params` after a
    weight update); the rng key is fixed — ``is_train=False`` disables
    dropout, so it never feeds randomness.

    Telemetry: ``infer.dispatches`` counts XLA launches (exactly one
    per call), ``infer.recompiles`` counts fresh data-shape signatures
    — under the serving bucket ladder this saturates at
    ``len(buckets)`` and stays flat in steady state (the xprof
    ``fused_infer`` site proves it at the compile registry).
    """

    #: Retry-safety contract: a dispatch donates nothing and mutates no
    #: state, so serving a duplicate (hedged/retried) request twice is
    #: harmless — the scheduler's request-id dedup keys off this tag.
    idempotent = True

    def __init__(self, executor, data_names, top_k=0, mesh=None):
        from .base import MXNetError

        self._ex = ex = executor
        arg_pos = {n: i for i, n in enumerate(ex.arg_names)}
        missing = [n for n in data_names if n not in arg_pos]
        if missing:
            raise MXNetError("fused_infer data args %s not in the "
                             "executor's arguments" % (missing,))
        self._data_names = list(data_names)
        self._d_idx = [arg_pos[n] for n in data_names]
        d_set = set(self._d_idx)
        self._p_idx = [i for i in range(len(ex.arg_names))
                       if i not in d_set]
        self._top_k = int(top_k)
        self._mesh = mesh
        self._tp = 1
        if mesh is not None and "tp" in mesh.axis_names:
            self._tp = int(mesh.shape["tp"])
        self._fn = self._build()
        self._seen_sigs = set()
        self._param_vals = None
        self._aux_vals = None
        # per-param content digests (sha256 over host bytes, the same
        # hashing checkpoint.snapshot records in its manifest): the
        # resident-pack side of the delta-aware refresh. None = unknown
        # provenance, so the next streamed refresh transfers everything
        # and re-seeds.
        self._digests = None
        self.last_refresh_bytes = 0
        self.last_refresh_ms = 0.0
        self.last_refresh_changed = 0
        self.last_refresh_skipped = 0
        with _san.intentional_transfer():
            # one fixed key for every dispatch: is_train=False, so the
            # graph's rng is inert — a per-call fold_in would be one
            # host int H2D per request batch for nothing
            self._key = ex._key()
        self.refresh_params()

    # ------------------------------------------------------------------
    @property
    def compiles(self) -> int:
        """Distinct data-shape signatures seen (== jit retraces)."""
        return len(self._seen_sigs)

    @property
    def mesh_key(self):
        """Mesh-factoring fingerprint this executable was built for
        (``(("dp", 4), ("tp", 2))``-style tuple, None off-mesh) — the
        cache key a re-bind across meshes must miss on."""
        if self._mesh is None:
            return None
        from .parallel.sharding import mesh_axis_sizes

        return tuple(mesh_axis_sizes(self._mesh).items())

    @staticmethod
    def factoring_key(mesh):
        """The :attr:`mesh_key` a FusedInfer built over ``mesh`` would
        carry — for callers checking a cached instance without one."""
        if mesh is None:
            return None
        from .parallel.sharding import mesh_axis_sizes

        return tuple(mesh_axis_sizes(mesh).items())

    def stale_for(self, executor, mesh=None) -> bool:
        """True when this cached executable no longer matches the
        caller's executor or mesh factoring: dispatching it would reuse
        an AOT executable compiled for the OLD placement. Rebuild
        instead (predictor.py and InferenceServer both key off this)."""
        return (executor is not self._ex
                or self.factoring_key(mesh) != self.mesh_key)

    def _replicated(self):
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self._mesh, PartitionSpec())

    def _param_sharding(self, arg_i):
        """NamedSharding for one params-pack member: tp-sharded on the
        per-param dim :func:`tp_param_spec` picks when the mesh carries
        a ``tp`` axis (replicated when no dim divides), replicated on a
        data-only mesh, None off-mesh."""
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        if self._tp > 1:
            from .parallel.sharding import tp_param_spec

            shape = tuple(self._ex.arg_arrays[arg_i]._data.shape)
            spec = tp_param_spec(shape, self._mesh) or PartitionSpec()
            return NamedSharding(self._mesh, spec)
        return NamedSharding(self._mesh, PartitionSpec())

    def _batch_sharding(self, ndim):
        """Request batches shard over the mesh's DATA axes only —
        ``dp`` (and ``fsdp`` when a training mesh is reused), never
        ``tp``: the model axis splits params, not rows."""
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding

        from .parallel.sharding import batch_spec

        return NamedSharding(self._mesh, batch_spec(self._mesh, 0))

    def refresh_params(self, host_params=None, digests=None,
                       torn_ms: float = 0.0):
        """(Re)pack the non-data args + aux states, placed per
        :meth:`_param_sharding` (tp-sharded on a ``(dp, tp)`` mesh,
        replicated otherwise).

        Two entry modes:

        * **full re-pack** (no arguments) — after ``module.set_params``
          the whole pack re-places from the executor's arrays, exactly
          the pre-delta behaviour. Resident digests reset to unknown.
        * **delta stream** (``host_params``: name -> host ndarray) —
          the checkpoint-streamed path. Each incoming param's sha256
          (``digests[name]`` when the caller already has it from the
          snapshot manifest, hashed here otherwise) is diffed against
          the resident pack's digest and ONLY changed params transfer
          and re-place inside the ``intentional_transfer`` window; the
          executor's arrays are written through so a later full re-pack
          agrees. ``MXNET_TPU_REFRESH_DELTA=0`` transfers everything
          regardless (the diff bypass hatch).

        Telemetry either way: ``infer.refresh_bytes`` (host bytes
        moved), ``infer.refresh_ms``, ``infer.refresh_changed`` /
        ``infer.refresh_skipped`` param counts — mirrored on
        ``last_refresh_*`` attributes for the bench.

        ``torn_ms > 0`` (the ``torn_swap`` injected fault) makes the
        swap deliberately non-atomic: half the new pack lands, then a
        sleep of ``torn_ms``, then the rest — a dispatch inside that
        window reads mixed param versions. Serving callers must drain
        the replica first; the fleet's rolling swap does."""
        import time as _time

        import jax

        ex = self._ex
        t0 = _time.perf_counter()
        moved = 0
        changed = skipped = 0
        if host_params is not None:
            from .checkpoint import param_digest

            delta_on = (_env.get("MXNET_TPU_REFRESH_DELTA")
                        and self._digests is not None)
            new_params = list(self._param_vals)
            new_aux = self._aux_vals
            new_digests = dict(self._digests or {})
            pos_of = {ex.arg_names[i]: pos
                      for pos, i in enumerate(self._p_idx)}
            with _san.intentional_transfer():
                for name, host in host_params.items():
                    pos = pos_of.get(name)
                    if pos is None:
                        continue   # a data arg, not part of the pack
                    dg = ((digests or {}).get(name)
                          or param_digest(host))
                    if delta_on and new_digests.get(name) == dg:
                        skipped += 1
                        continue
                    arg_i = self._p_idx[pos]
                    sh = self._param_sharding(arg_i)
                    val = (jax.device_put(host, sh) if sh is not None
                           else jax.device_put(host))
                    new_params[pos] = val
                    # write-through so a later full re-pack (or a
                    # host-side get_params) sees the streamed values
                    ex.arg_arrays[arg_i]._data = val
                    new_digests[name] = dg
                    changed += 1
                    moved += int(getattr(host, "nbytes", 0))
        else:
            with _san.intentional_transfer():
                new_params = []
                for i in self._p_idx:
                    sh = self._param_sharding(i)
                    v = ex.arg_arrays[i]._data
                    new_params.append(jax.device_put(v, sh)
                                      if sh is not None else v)
                rep = self._replicated()
                new_aux = [jax.device_put(a._data, rep)
                           if rep is not None else a._data
                           for a in ex.aux_arrays]
            changed = len(new_params)
            moved = sum(int(v.nbytes) for v in new_params)
            new_digests = None   # unknown provenance: next delta
            #                      refresh transfers all and re-seeds
        self.last_refresh_bytes = moved
        self.last_refresh_changed = changed
        self.last_refresh_skipped = skipped
        _tel.inc("infer.refresh_bytes", moved)
        _tel.inc("infer.refresh_changed", changed)
        _tel.inc("infer.refresh_skipped", skipped)
        if torn_ms > 0 and self._param_vals is not None and new_params:
            half = max(1, len(new_params) // 2)
            self._param_vals = (new_params[:half]
                                + self._param_vals[half:])
            _time.sleep(torn_ms / 1e3)
            self._param_vals = new_params
            self._aux_vals = new_aux
            self._digests = new_digests
            self.last_refresh_ms = (_time.perf_counter() - t0) * 1e3
            _tel.observe("infer.refresh_ms", self.last_refresh_ms)
            return
        self._param_vals = new_params
        self._aux_vals = new_aux
        self._digests = new_digests
        self.last_refresh_ms = (_time.perf_counter() - t0) * 1e3
        _tel.observe("infer.refresh_ms", self.last_refresh_ms)

    def place_batch(self, arrays):
        """Device-place one request batch (numpy or jax arrays), batch
        axis sharded along ``dp`` under a mesh. Already-placed jax
        arrays pass through untouched off-mesh."""
        import jax
        import numpy as _np

        placed = []
        with _san.intentional_transfer():
            for a in arrays:
                sh = self._batch_sharding(getattr(a, "ndim", 0) or 1)
                if sh is not None:
                    placed.append(jax.device_put(a, sh))
                elif isinstance(a, _np.ndarray):
                    placed.append(jax.device_put(a))
                else:
                    placed.append(a)
        return placed

    # ------------------------------------------------------------------
    def __call__(self, arrays):
        """One batch -> (outputs, post) in ONE dispatch. ``arrays``
        follow ``data_names`` order and must already be padded to a
        stable shape (the serving bucket ladder / the bound batch
        size); ``post`` is ``()`` for top_k=0, ``(argmax,)`` for
        top_k=1, ``(values, indices)`` otherwise. Results stay on
        device — the caller decides what (and when) to fetch."""
        d_vals = self.place_batch(arrays)
        sig = tuple((tuple(v.shape), str(v.dtype)) for v in d_vals)
        if sig not in self._seen_sigs:
            self._seen_sigs.add(sig)
            _tel.inc("infer.recompiles")
        _tel.inc("infer.dispatches")
        return self._fn(self._param_vals, d_vals, self._aux_vals,
                        self._key)

    # ------------------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        ex = self._ex
        run_graph = ex._run_graph
        n_args = len(ex.arg_names)
        p_idx = list(self._p_idx)
        d_idx = list(self._d_idx)
        top_k = self._top_k
        # tensor-sharded serving: pin every forward output back to the
        # batch (data-axes) sharding. With params split along ``tp``
        # the activations come out of the matmuls partially-summed or
        # model-sharded; the constraint makes GSPMD emit the
        # all-reduce/all-gather INSIDE this one dispatch (the xprof
        # collective bucket is the proof) instead of deferring a
        # gather to the host fetch. Off a tp mesh the outputs are
        # already batch-sharded and no constraint is needed.
        batch_out = None
        if self._mesh is not None and self._tp > 1:
            from jax.sharding import NamedSharding

            from .parallel.sharding import batch_spec

            batch_out = NamedSharding(self._mesh,
                                      batch_spec(self._mesh, 0))

        _tel.inc("executor.jit_build")

        def infer(p_vals, d_vals, aux, key):
            full = [None] * n_args
            for pos, i in enumerate(p_idx):
                full[i] = p_vals[pos]
            for pos, i in enumerate(d_idx):
                full[i] = d_vals[pos]
            outs, _ = run_graph(full, aux, key, False)
            if batch_out is not None:
                outs = [jax.lax.with_sharding_constraint(o, batch_out)
                        if getattr(o, "ndim", 0) >= 1 else o
                        for o in outs]
            post = ()
            if top_k and outs:
                head = outs[0]
                if (head.ndim >= 2
                        and jnp.issubdtype(head.dtype, jnp.inexact)):
                    if top_k == 1:
                        post = (jnp.argmax(head, axis=-1),)
                    else:
                        post = tuple(jax.lax.top_k(head, top_k))
            return tuple(outs), post

        names = [ex.arg_names[i] for i in p_idx]
        return _xprof.jit(
            infer, site="fused_infer",
            arg_names=(tuple("params." + n for n in names),
                       tuple("batch." + n for n in self._data_names),
                       "aux", "rng_key"),
            donate_argnums=())
