"""Graph executor.

TPU-native re-design of the reference's GraphExecutor
(``src/symbol/graph_executor.h:23-279``): binding a Symbol yields an
executor whose forward and forward+backward paths are each ONE jitted XLA
computation over the whole graph. This is the reference's bulk-execution
design (``InitOpSegs``, ``graph_executor.cc:842-892``) taken to its
conclusion: instead of pushing per-node engine ops, XLA fuses, schedules and
plans memory for the entire graph (subsuming the reference's
GraphStorageAllocator, ``src/symbol/graph_memory_allocator.h``).

Autodiff: the reference builds an explicit backward graph
(``StaticGraph::MakeBackwardPass``, ``static_graph.cc:395``); here the
backward computation is ``jax.vjp`` through the same graph-eval function,
with op-custom gradients (SoftmaxOutput etc.) supplied via
``jax.custom_vjp`` in each op's ``apply``.

Training-step laziness: ``forward(is_train=True)`` records inputs;
``backward()`` then runs a single fused fwd+bwd XLA computation that also
materializes the outputs — so a fit() iteration costs exactly one device
dispatch. Auxiliary states (BatchNorm moving stats) commit on ``backward()``
(divergence from the reference: a train-mode forward with no backward does
not update moving stats).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import telemetry as _tel
from .base import MXNetError, getenv
from .context import Context
from .engine import get_engine
from .ndarray import NDArray
from .ops.registry import OpContext
from . import random as _random

__all__ = ["Executor", "make_graph_eval", "zero_cotangent"]


def zero_cotangent(x):
    """A vjp cotangent of zeros for ``x``: float0 for non-differentiable
    (integer/bool) primal outputs — a plain zeros_like would make
    ``jax.vjp`` reject graphs with integer internals (Cast). Shared by
    the executor's fused fwd+bwd and the whole-batch fused train step
    (:mod:`mxnet_tpu.fused_step`)."""
    import jax
    import jax.numpy as jnp

    if jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.zeros_like(x)
    return np.zeros(x.shape, jax.dtypes.float0)


def make_graph_eval(symbol, node_device=None, remat=False):
    """Build the pure graph-eval function for a symbol.

    Returns ``(eval_graph, n_aux)`` where
    ``eval_graph(arg_list, aux_list, key, is_train, want_internals=False)``
    evaluates the whole DAG over jnp arrays. Shared by :class:`Executor`
    and the sharded training-step builders in :mod:`mxnet_tpu.parallel`.

    ``node_device(node) -> jax.Device | None`` implements model-parallel
    placement (the reference's ``ctx_group``/``AssignContext`` +
    ``_CrossDeviceCopy`` insertion, ``graph_executor.cc:391-508``): inputs
    of a placed node are ``device_put`` to its device inside the single
    jitted program, so XLA emits the cross-device transfers — and their
    reverse transfers in the backward pass — in one compiled computation.

    ``remat=True`` is the memonger design behind the reference's
    ``MXNET_BACKWARD_DO_MIRROR`` (``static_graph.cc:395-439``): the topo
    order is split into ~sqrt(N) segments and each segment evaluates
    under ``jax.checkpoint``, so the backward pass stores only segment
    BOUNDARY activations and recomputes inside each segment — sublinear
    activation memory for chain-like graphs. (Wrapping the whole
    function in one checkpoint would save nothing: the recompute would
    materialize every activation again at once.) Internals-mode calls
    fall back to the unsegmented path (monitoring wants every tensor
    live anyway).
    """
    import math

    import jax

    nodes = symbol._topo()
    arg_index = {}
    i = 0
    for n in nodes:
        if n.is_variable:
            arg_index[n.uid] = i
            i += 1
    aux_slots = {}
    slot = 0
    for n in nodes:
        if not n.is_variable:
            k = len(n.op.list_auxiliary_states())
            if k:
                aux_slots[n.uid] = list(range(slot, slot + k))
                slot += k
    n_aux = slot
    out_index = [(n.uid, i) for n, i in symbol._outputs]

    def _eval_nodes(node_list, env, aux_out, key, is_train,
                    internals=None):
        """Evaluate op nodes into env (uid -> outputs list) in place."""
        for n in node_list:
            ins = [env[src.uid][i] for src, i in n.inputs]
            if node_device is not None:
                dev = node_device(n)
                if dev is not None:
                    ins = [jax.device_put(x, dev) for x in ins]
            slots = aux_slots.get(n.uid, [])
            aux_in = [aux_out[s] for s in slots]
            rng = jax.random.fold_in(key, n.uid) if key is not None else None
            octx = OpContext(is_train, rng)
            outs, new_aux = n.op.apply(octx, ins, aux_in)
            for s, a in zip(slots, new_aux):
                aux_out[s] = a
            env[n.uid] = list(outs)
            if internals is not None:
                for oi, o in enumerate(outs):
                    oname = "%s_%s" % (n.name, n.op.list_outputs()[oi])
                    internals[oname] = o

    def eval_graph(arg_list, aux_list, key, is_train, want_internals=False):
        env = {}
        aux_out = list(aux_list)
        internals = {} if want_internals else None
        for n in nodes:
            if n.is_variable:
                env[n.uid] = [arg_list[arg_index[n.uid]]]
        _eval_nodes([n for n in nodes if not n.is_variable], env, aux_out,
                    key, is_train, internals)
        outputs = [env[uid][i] for uid, i in out_index]
        if want_internals:
            return outputs, aux_out, internals
        return outputs, aux_out

    if not remat:
        return eval_graph, n_aux

    # ---- segmented remat (memonger / sqrt schedule) -------------------
    op_nodes = [n for n in nodes if not n.is_variable]
    n_seg = max(2, int(math.isqrt(len(op_nodes))))
    seg_size = max(1, (len(op_nodes) + n_seg - 1) // n_seg)
    segments = [op_nodes[i:i + seg_size]
                for i in range(0, len(op_nodes), seg_size)]

    # static plan: which (uid, out_idx) values cross each segment
    # boundary (consumed by a later segment or by the graph outputs)
    seg_of = {}
    for si, seg in enumerate(segments):
        for n in seg:
            seg_of[n.uid] = si
    # for each segment: values it must emit = those it produces that a
    # later segment or the graph outputs consume. Variables are never
    # segment outputs — they sit in the caller's store for the duration.
    consumed_later = [set() for _ in segments]
    for si, seg in enumerate(segments):
        for n in seg:
            for src, i in n.inputs:
                src_seg = seg_of.get(src.uid, -1)  # -1: a variable
                if 0 <= src_seg < si:
                    consumed_later[src_seg].add((src.uid, i))
    for uid, i in out_index:
        src_seg = seg_of.get(uid, -1)
        if src_seg >= 0:
            consumed_later[src_seg].add((uid, i))

    plans = []
    for si, seg in enumerate(segments):
        in_keys = sorted(
            {(src.uid, i) for n in seg for src, i in n.inputs
             if seg_of.get(src.uid, -1) != si},
            key=lambda k: (k[0], k[1]))
        out_keys = sorted(consumed_later[si], key=lambda k: (k[0], k[1]))
        plans.append((seg, in_keys, out_keys))

    def eval_graph_remat(arg_list, aux_list, key, is_train,
                         want_internals=False):
        if want_internals:
            return eval_graph(arg_list, aux_list, key, is_train,
                              want_internals=True)
        store = {}
        for n in nodes:
            if n.is_variable:
                store[(n.uid, 0)] = arg_list[arg_index[n.uid]]
        aux_state = list(aux_list)
        for seg, in_keys, out_keys in plans:
            def seg_fn(in_vals, aux_vals, _seg=seg, _in=in_keys,
                       _out=out_keys):
                # boundary values keyed as {uid: {out_idx: val}} — both
                # dict and the list envs produced by _eval_nodes support
                # the env[uid][i] indexing the node loop uses
                env = {}
                for (uid, i), v in zip(_in, in_vals):
                    env.setdefault(uid, {})[i] = v
                aux_out = list(aux_vals)
                _eval_nodes(_seg, env, aux_out, key, is_train)
                return [env[uid][i] for uid, i in _out], aux_out

            in_vals = [store[k] for k in in_keys]
            out_vals, aux_state = jax.checkpoint(seg_fn)(in_vals,
                                                         aux_state)
            store.update(zip(out_keys, out_vals))
        outputs = [store[(uid, i)] for uid, i in out_index]
        return outputs, aux_state

    return eval_graph_remat, n_aux


_UNSET = object()  # distinguishes "not passed" from explicit None


class Executor:
    def __init__(self, symbol, ctx: Context, args, args_grad=None,
                 grad_req: Union[str, Dict[str, str], List[str]] = "write",
                 aux_states=None, group2ctx=None, shared_exec=None,
                 compute_dtype=_UNSET, label_names=None):
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = group2ctx or {}
        # mixed precision: compute in this dtype (e.g. "bfloat16") with
        # full-precision params/grads outside the jitted graph. Default
        # comes from MXNET_COMPUTE_DTYPE so existing scripts opt in via
        # env; pass compute_dtype=None to force full precision for this
        # executor even when the env var is set.
        if compute_dtype is _UNSET:
            compute_dtype = getenv("MXNET_COMPUTE_DTYPE", None)
        self._compute_dtype = compute_dtype
        # args that must never be cast under mixed precision; when the
        # binder doesn't say (plain symbol.bind), fall back to the
        # "*label" naming convention
        self._label_names = (set(label_names) if label_names is not None
                             else {n for n in symbol.list_arguments()
                                   if n.endswith("label")})
        self.arg_names = symbol.list_arguments()
        if len(set(self.arg_names)) != len(self.arg_names):
            # two distinct Variable nodes sharing a name: name-keyed
            # binding would silently drop one (reference GraphExecutor
            # rejects this with "Find duplicate argument name")
            dups = sorted({n for n in self.arg_names
                           if self.arg_names.count(n) > 1})
            raise MXNetError(
                "duplicate argument name(s) %s: reuse one Variable "
                "instance instead of creating it twice" % dups)
        self.output_names = symbol.list_outputs()
        self.aux_names = symbol.list_auxiliary_states()

        self.arg_arrays = self._to_list(args, self.arg_names, "args")
        self.arg_dict = dict(zip(self.arg_names, self.arg_arrays))
        if args_grad is None:
            self.grad_arrays = [None] * len(self.arg_names)
        else:
            self.grad_arrays = self._to_list(args_grad, self.arg_names,
                                             "args_grad", allow_missing=True)
        self.grad_dict = {n: g for n, g in zip(self.arg_names, self.grad_arrays)
                          if g is not None}

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in self.arg_names}
        for n in self.arg_names:
            if self.grad_dict.get(n) is None:
                self._grad_req[n] = "null"

        aux_states = aux_states or []
        self.aux_arrays = self._to_list(aux_states, self.aux_names, "aux_states")
        self.aux_dict = dict(zip(self.aux_names, self.aux_arrays))

        self._outputs: Optional[List[NDArray]] = None
        self._train_pending = False
        self._monitor_callback = None
        self._step = 0
        self._base_key = None

        self._build()

    @staticmethod
    def _to_list(arrays, names, what, allow_missing=False):
        if arrays is None:
            arrays = {}
        if isinstance(arrays, dict):
            out = [arrays.get(n) for n in names]
            if not allow_missing and any(a is None for a in out):
                missing = [n for n, a in zip(names, out) if a is None]
                raise MXNetError("%s: missing arrays for %s" % (what, missing))
            return out
        arrays = list(arrays)
        if len(arrays) != len(names):
            raise MXNetError("%s: expected %d arrays, got %d"
                             % (what, len(names), len(arrays)))
        return arrays

    # ------------------------------------------------------------------
    # graph -> pure function
    # ------------------------------------------------------------------
    def _build(self):
        import jax

        _tel.inc("executor.bind")
        node_device = None
        if self._group2ctx:
            group2dev = {g: c.jax_device() for g, c in self._group2ctx.items()}

            def node_device(n):  # noqa: F811
                group = n.attrs.get("ctx_group")
                return group2dev.get(group)

        # MXNET_BACKWARD_DO_MIRROR (reference static_graph.cc:395-439
        # memonger mirroring): segmented remat — see make_graph_eval
        do_mirror = getenv("MXNET_BACKWARD_DO_MIRROR", False)
        eval_graph, self._n_aux = make_graph_eval(self._symbol, node_device,
                                                  remat=do_mirror)
        self._eval_graph = eval_graph

        grad_idx = [i for i, n in enumerate(self.arg_names)
                    if self._grad_req.get(n, "null") != "null"]
        self._grad_idx = grad_idx

        cdtype = None
        if self._compute_dtype is not None:
            import jax.numpy as jnp
            if isinstance(self._compute_dtype, str):
                cdtype = getattr(jnp, self._compute_dtype, None)
                if cdtype is None or not isinstance(cdtype, type):
                    raise MXNetError(
                        "invalid compute dtype %r (MXNET_COMPUTE_DTYPE / "
                        "compute_dtype); expected a jax dtype name like "
                        "'bfloat16' or 'float16'" % (self._compute_dtype,))
            else:
                cdtype = self._compute_dtype
        # label args keep full precision (bf16 cannot represent class ids
        # >= 256 exactly); everything else float casts to compute dtype
        cast_arg = [cdtype is not None and n not in self._label_names
                    for n in self.arg_names]

        def cast_in(args):
            if cdtype is None:
                return args
            import jax.numpy as jnp
            return [a.astype(cdtype)
                    if c and jnp.issubdtype(a.dtype, jnp.floating) else a
                    for a, c in zip(args, cast_arg)]

        def cast_out(outs):
            if cdtype is None:
                return outs
            import jax.numpy as jnp
            return [o.astype(jnp.float32)
                    if jnp.issubdtype(o.dtype, jnp.floating) else o
                    for o in outs]

        def run_graph(args, aux, key, is_train, **kw):
            res = eval_graph(cast_in(args), aux, key, is_train, **kw)
            if kw.get("want_internals"):
                outs, aux_out, internals = res
                return cast_out(outs), aux_out, internals
            outs, aux_out = res
            return cast_out(outs), aux_out

        # the mixed-precision-aware pure graph function, exposed so the
        # fused train step (fused_step.py) can trace fwd+bwd+update as
        # ONE jitted computation with the exact same numerics
        self._run_graph = run_graph

        @jax.jit
        def fwd_infer(args, aux, key):
            outs, _ = run_graph(args, aux, key, False)
            return outs

        @jax.jit
        def fwd_train(args, aux, key):
            return run_graph(args, aux, key, True)

        # Donate the aux buffers (BN running stats) into the fused train
        # step: backward() always replaces them with aux_out, so XLA can
        # write the new stats into the old HBM buffers. Args (params) are
        # NOT donated — they outlive the step (the optimizer update, which
        # donates them itself, runs outside this computation) — and neither
        # are head_grads (self._head_ones is cached across steps). Donation
        # follows the same engine-safety rule as the optimizer kernels,
        # re-checked at every call: set_engine() may switch to a threaded
        # engine after bind, and a donation decision frozen at bind time
        # would keep deleting buffers a queued reader still sees.
        from .optimizer import _donation_ok

        fwd_bwd_cache = {}

        def get_fwd_bwd(want_internals):
            k = (want_internals, _donation_ok())
            if k not in fwd_bwd_cache:
                # a build here means XLA traces + compiles a fresh fused
                # step — the recompile events the telemetry tier exists
                # to make visible (a flapping donation decision or
                # monitor flag shows up as a climbing jit_build count)
                _tel.inc("executor.jit_build")
                fwd_bwd_cache[k] = make_fwd_bwd(*k)
            else:
                _tel.inc("executor.jit_cache_hit")
            return fwd_bwd_cache[k]

        def make_fwd_bwd(want_internals, donate):
            # one builder for the plain and the monitored training step:
            # with want_internals the SAME fused fwd+bwd also emits every
            # internal output, so a monitored batch costs one forward
            # (the naive monitor-forward-then-train scheme doubled it)
            def step(args, aux, key, head_grads):
                garr = [args[i] for i in grad_idx]

                def f(garr):
                    full = list(args)
                    for pos, i in enumerate(grad_idx):
                        full[i] = garr[pos]
                    # casts live inside the vjp'd fn: gradients come back
                    # in the arrays' own (full) precision automatically
                    return run_graph(full, aux, key, True,
                                     want_internals=want_internals)

                res, vjp = jax.vjp(f, garr)
                # zero cotangents for everything but the heads
                cts = (head_grads,) + tuple(
                    jax.tree_util.tree_map(zero_cotangent, r)
                    for r in res[1:])
                grads, = vjp(cts)
                return res + (grads,)

            # compile registry site (xprof off -> plain jax.jit; the
            # wrapper keeps .lower() for the HLO regression gates)
            from . import xprof as _xprof

            return _xprof.jit(
                step, site="executor.fwd_bwd",
                arg_names=(tuple(self.arg_names), tuple(self.aux_names),
                           "rng_key", "head_grads"),
                donate_argnums=(1,) if donate else ())

        def fwd_bwd(args, aux, key, head_grads):
            outs, aux_out, grads = get_fwd_bwd(False)(args, aux, key,
                                                      head_grads)
            return outs, grads, aux_out

        def fwd_bwd_monitor(args, aux, key, head_grads):
            outs, aux_out, internals, grads = get_fwd_bwd(True)(
                args, aux, key, head_grads)
            return outs, grads, aux_out, internals

        @jax.jit
        def fwd_monitor(args, aux, key):
            return run_graph(args, aux, key, True, want_internals=True)

        self._fwd_infer = fwd_infer
        self._fwd_train = fwd_train
        self._fwd_bwd = fwd_bwd
        # raw jitted step factory, exposed for the HLO regression gates
        # (tests/test_hlo_gates.py asserts aux donation aliasing on
        # _get_fwd_bwd(False) under the default engine)
        self._get_fwd_bwd = get_fwd_bwd
        self._fwd_monitor = fwd_monitor
        self._fwd_bwd_monitor = fwd_bwd_monitor

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _key(self):
        import jax

        if self._base_key is None:
            self._base_key = _random.next_key()
        self._step += 1
        return jax.random.fold_in(self._base_key, self._step)

    def _arg_data(self):
        return [a._data for a in self.arg_arrays]

    def _aux_data(self):
        return [a._data for a in self.aux_arrays]

    def forward(self, is_train: bool = False, **kwargs):
        """Run forward (reference ``GraphExecutor::Forward``,
        ``graph_executor.cc:990``). kwargs update named input arrays."""
        for name, arr in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("forward: unknown argument '%s'" % name)
            self.arg_dict[name][:] = arr
        _tel.inc("executor.forward")
        if is_train:
            _tel.inc("executor.forward_train")
        self._last_key = self._key()
        if is_train:
            # lazy: the fused fwd+bwd in backward() materializes outputs;
            # accessing .outputs before backward triggers a fwd-only run.
            # Returns None here — materializing now would double the forward
            # work of every fit() iteration.
            self._train_pending = True
            self._outputs = None
            # monitoring is deferred into the fused fwd+bwd (or the lazy
            # outputs fetch) so the forward runs exactly once per batch;
            # whether to monitor is decided there, so a callback installed
            # between forward and backward still sees this batch. The
            # emitted flag keeps it once per batch even when .outputs is
            # read before backward().
            self._monitor_emitted = False
            return None
        self._train_pending = False
        outs = self._fwd_infer(self._arg_data(), self._aux_data(),
                               self._last_key)
        self._set_outputs(outs)
        return self.outputs

    def backward(self, out_grads=None):
        """Fused forward+backward in one XLA computation (reference
        ``GraphExecutor::Backward``, ``graph_executor.cc:1003``)."""
        import jax.numpy as jnp

        if not self._train_pending:
            raise MXNetError("backward called without forward(is_train=True)")
        _tel.inc("executor.backward")
        # the fused fwd+bwd below is one XLA computation launch; the
        # optimizer update and any metric fold launch separately on this
        # (unfused) path — step.dispatches makes the per-batch dispatch
        # count measurable against MXNET_TPU_FUSED_STEP=1
        _tel.inc("step.dispatches")
        if out_grads is None:
            import jax

            sig = tuple((a.shape, str(a.dtype)) for a in self.arg_arrays)
            if getattr(self, "_head_sig", None) != sig:
                # exact output shapes AND dtypes from abstract evaluation —
                # jax.vjp requires cotangents to match primal dtypes, so
                # fp16/bf16 graphs need fp16/bf16 head grads
                outs_spec, _ = jax.eval_shape(
                    self._fwd_train, self._arg_data(), self._aux_data(),
                    self._last_key)
                self._head_ones = [jnp.ones(s.shape, dtype=s.dtype)
                                   for s in outs_spec]
                self._head_sig = sig
            heads = self._head_ones
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = [g._data for g in out_grads]
        if self._monitor_callback is not None \
                and not getattr(self, "_monitor_emitted", False):
            outs, grads, aux_out, internals = self._fwd_bwd_monitor(
                self._arg_data(), self._aux_data(), self._last_key, heads)
            self._emit_monitor(internals)
        else:
            outs, grads, aux_out = self._fwd_bwd(
                self._arg_data(), self._aux_data(), self._last_key, heads)
        self._set_outputs(outs)
        self._train_pending = False
        for pos, i in enumerate(self._grad_idx):
            name = self.arg_names[i]
            garr = self.grad_arrays[i]
            g = grads[pos]
            req = self._grad_req[name]

            def _assign(garr=garr, g=g, req=req):
                import jax.dtypes

                if getattr(g, "dtype", None) == jax.dtypes.float0:
                    # integer-dtype arg: jax emits a float0 zero-tangent
                    g = jnp.zeros(g.shape, garr.dtype)
                garr._data = (garr._data + g.astype(garr.dtype)
                              if req == "add" else g.astype(garr.dtype))
            get_engine().push(_assign, mutable_vars=[garr._var])
        for arr, new in zip(self.aux_arrays, aux_out):
            def _assign_aux(arr=arr, new=new):
                arr._data = new

            get_engine().push(_assign_aux, mutable_vars=[arr._var])

    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs is None:
            if self._train_pending:
                if self._monitor_callback is not None \
                        and not getattr(self, "_monitor_emitted", False):
                    outs, _, internals = self._fwd_monitor(
                        self._arg_data(), self._aux_data(), self._last_key)
                    self._emit_monitor(internals)
                else:
                    outs, _ = self._fwd_train(
                        self._arg_data(), self._aux_data(), self._last_key)
                self._set_outputs(outs)
            else:
                raise MXNetError("no forward has been run")
        return self._outputs

    def _set_outputs(self, outs):
        self._outputs = [NDArray(o, ctx=self._ctx) for o in outs]

    # ------------------------------------------------------------------
    # monitor (reference MXExecutorSetMonitorCallback ->
    # GraphExecutor::RunOps monitor hook, graph_executor.cc:937-951)
    # ------------------------------------------------------------------
    def set_monitor_callback(self, callback: Callable[[str, NDArray], None]):
        """Install a per-internal-output callback. Semantics are
        per-BATCH, not per-forward: emission happens inside the fused
        fwd+bwd (or the lazy outputs fetch), so each training batch
        fires the callbacks exactly once, and a callback installed
        between forward and backward still observes that batch."""
        self._monitor_callback = callback

    def _emit_monitor(self, internals):
        self._monitor_emitted = True
        for name, value in internals.items():
            self._monitor_callback(name, NDArray(value, ctx=self._ctx))

    # ------------------------------------------------------------------
    # utilities
    # ------------------------------------------------------------------
    def copy_params_from(self, arg_params: Dict[str, NDArray],
                         aux_params: Optional[Dict[str, NDArray]] = None,
                         allow_extra_params: bool = False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError("unknown param '%s'" % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name][:] = arr
                elif not allow_extra_params:
                    raise MXNetError("unknown aux '%s'" % name)

    def reshape(self, partial_shaping: bool = False, allow_up_sizing: bool = False,
                fresh_args=(), **kwargs) -> "Executor":
        """Rebind to new input shapes, sharing parameter arrays whose shape
        is unchanged (reference ``executor.py:270``). Names in
        ``fresh_args`` always get new storage even at the same shape, so
        writes through the new executor can't alias the old one's inputs."""
        from . import ndarray as nd

        fresh = set(fresh_args)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = []
        new_grads: Dict[str, NDArray] = {}
        for name, shape, arr, grad in zip(self.arg_names, arg_shapes,
                                          self.arg_arrays, self.grad_arrays):
            if shape == arr.shape and name not in fresh:
                new_args.append(arr)
                if grad is not None:
                    new_grads[name] = grad
            else:
                new_args.append(nd.zeros(shape, ctx=self._ctx, dtype=arr.dtype))
                if grad is not None:
                    new_grads[name] = nd.zeros(shape, ctx=self._ctx)
        new_aux = []
        for shape, arr in zip(aux_shapes, self.aux_arrays):
            new_aux.append(arr if shape == arr.shape
                           else nd.zeros(shape, ctx=self._ctx, dtype=arr.dtype))
        return Executor(self._symbol, self._ctx, new_args,
                        new_grads or None, self._grad_req, new_aux,
                        group2ctx=self._group2ctx,
                        compute_dtype=self._compute_dtype,
                        label_names=self._label_names)

    def debug_str(self) -> str:
        """Allocation/graph plan dump (reference GraphExecutor::Print)."""
        lines = ["Symbol outputs: %s" % self.output_names]
        for n in self._symbol._topo():
            kind = "var" if n.is_variable else n.op.op_name
            lines.append("  %-30s %s <- %s" % (
                n.name, kind, [src.name for src, _ in n.inputs]))
        return "\n".join(lines)
