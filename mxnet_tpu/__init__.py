"""mxnet_tpu — a TPU-native deep learning framework with the capabilities of
2016-era MXNet (reference: hschen0712/mxnet).

The public API mirrors ``import mxnet as mx``:

* ``mx.nd`` — imperative NDArray over jax.Array + dependency engine
* ``mx.sym`` — symbolic graph with autodiff, compiled whole-graph to XLA
* ``mx.io`` — data iterators (NDArray/MNIST/CSV/ImageRecord) with prefetch
* ``mx.kv`` — KVStore (local / device / tpu_sync collective all-reduce)
* ``mx.mod`` / ``mx.model`` — Module and FeedForward training loops
* ``mx.optimizer`` / ``mx.metric`` / ``mx.init`` — training utilities
"""
from __future__ import annotations

from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_devices
from . import engine
from . import ndarray
from . import ndarray as nd
from . import random
from .ndarray import NDArray
from .name import NameManager
from .attribute import AttrScope

__version__ = "0.1.0"

# Submodules below are imported lazily-but-eagerly in dependency order; each
# maps to a reference frontend module (python/mxnet/*.py).
from . import operator        # noqa: E402  (registers the Custom op before
#                                            symbol generates creators)
from . import symbol          # noqa: E402
from .ndarray_ops import init_ndarray_ops  # noqa: E402

init_ndarray_ops(ndarray)  # SimpleOp unification: ops usable imperatively
from . import symbol as sym   # noqa: E402
from .symbol import Symbol    # noqa: E402
from . import executor        # noqa: E402
from . import initializer     # noqa: E402
from . import initializer as init  # noqa: E402
from . import optimizer       # noqa: E402
from . import metric          # noqa: E402
from . import lr_scheduler    # noqa: E402
from . import io              # noqa: E402
from . import io_pipeline     # noqa: E402
from . import io_cache        # noqa: E402
from . import recordio        # noqa: E402
from . import filesystem      # noqa: E402
from . import kvstore         # noqa: E402
from . import kvstore as kv   # noqa: E402
from . import callback        # noqa: E402
from . import monitor         # noqa: E402
from .monitor import Monitor  # noqa: E402
from . import model           # noqa: E402
from .model import FeedForward  # noqa: E402
from . import module          # noqa: E402
from . import module as mod   # noqa: E402
from . import visualization   # noqa: E402
from . import visualization as viz  # noqa: E402
from . import test_utils      # noqa: E402
from . import export          # noqa: E402
from . import profiler        # noqa: E402
from . import telemetry       # noqa: E402
from . import tracing         # noqa: E402
