"""Pre-decoded image cache: decode JPEG recordio ONCE, feed forever.

The reference scales JPEG decode with an OMP pool
(``/root/reference/src/io/iter_image_recordio.cc:109-455``) — on a GPU
box with dozens of cores that feeds the device. A TPU v5e consumes
~2,500 img/s at 224px while one host core decodes ~90 img/s, so decoding
per epoch can never feed the chip from a few cores. The TPU-native
answer is to move the expensive work out of the steady state:

* ``build_decoded_cache``  — one offline pass: decode + resize every
  record, store raw uint8 HWC tensors in a memmapped flat file (plus a
  float32 label table and a JSON header). Decode cost is paid once per
  dataset, not once per epoch.
* ``CachedImageRecordIter`` — training-time iterator over the memmap.
  Per-epoch augmentation keeps the cheap ops (random crop = array
  slicing, mirror = negative stride) on the host, and runs the
  arithmetic (cast, mean/scale normalize, HWC->CHW) on DEVICE in one
  fused jitted kernel. Batches cross the host->device link as uint8 —
  4x fewer bytes than float32.

Cache layout (``<prefix>.meta.json`` / ``.data`` / ``.label``)::

    meta:  {"num": N, "height": H, "width": W, "channels": C,
            "label_width": L, "version": 1}
    data:  uint8  [N, H, W, C]   (memmapped at iteration time)
    label: float32 [N, L]

The stored H/W should be the training crop plus the augmentation margin
(e.g. store 256, crop 224 — the classic ImageNet recipe).
"""
from __future__ import annotations

import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from . import telemetry as _tel
from . import env as _env
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["build_decoded_cache", "CachedImageRecordIter",
           "materialize_device_feed"]


def _decode_record(rec: bytes, store_hw: Tuple[int, int], channels: int):
    """JPEG record -> (uint8 HWC resized to store_hw, label vector)."""
    from PIL import Image

    from . import recordio as rio

    header, img = rio.unpack_img(rec, iscolor=1 if channels == 3 else 0)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w = store_hw
    if img.shape[0] != h or img.shape[1] != w:
        img = np.asarray(Image.fromarray(img.astype(np.uint8))
                         .resize((w, h)))
        if img.ndim == 2:
            img = img[:, :, None]
    return img.astype(np.uint8), np.atleast_1d(
        np.asarray(header.label, dtype=np.float32))


def build_decoded_cache(path_imgrec: str, cache_prefix: str,
                        store_shape: Tuple[int, int, int],
                        preprocess_threads: int = 4,
                        overwrite: bool = False) -> dict:
    """Decode every record of ``path_imgrec`` once into a memmapped
    uint8 cache at ``cache_prefix``. ``store_shape`` is (C, H, W) — use
    crop size + margin (e.g. (3, 256, 256) for 224 training).

    Returns the meta dict. Idempotent: an existing complete cache with
    the SAME store shape is reused; a shape mismatch (or ``overwrite``)
    rebuilds. The write is atomic (tmp + rename) so a killed build can't
    leave a torn cache that later runs trust. Memory stays bounded at
    one decode chunk regardless of dataset size."""
    import socket
    import time

    c, h, w = store_shape
    meta_path = cache_prefix + ".meta.json"
    try:
        src_stat = os.stat(path_imgrec)
    except FileNotFoundError:
        # "decode once, feed forever": deleting the source .rec after a
        # successful build is a legitimate disk-reclaim move — a shape-
        # matching complete cache stays usable (staleness can no longer
        # be judged, which is fine: there is nothing to be stale against)
        if not overwrite and os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if (meta.get("height"), meta.get("width"),
                    meta.get("channels")) == (h, w, c):
                return meta
        raise MXNetError("no recordio at %s and no matching decoded "
                         "cache at %s" % (path_imgrec, cache_prefix))

    def _fresh(meta):
        # the cache must match BOTH the requested store shape and the
        # source .rec it was decoded from — a regenerated rec (new
        # size/mtime) silently training on old decoded data is the
        # worst failure mode a cache can have. mtime at ns resolution:
        # whole seconds leave a same-second-regeneration hole.
        return ((meta.get("height"), meta.get("width"),
                 meta.get("channels")) == (h, w, c)
                and meta.get("src_size") == src_stat.st_size
                and meta.get("src_mtime") == src_stat.st_mtime_ns)

    def _existing():
        if overwrite or not os.path.exists(meta_path):
            return None
        with open(meta_path) as f:
            meta = json.load(f)
        return meta if _fresh(meta) else None

    meta = _existing()
    if meta is not None:
        return meta

    # single-builder lock: in a multi-rank job every worker calls this
    # over a shared filesystem — exactly one decodes, the rest wait for
    # the finished cache (O_CREAT|O_EXCL is atomic on POSIX and NFSv3+)
    lock_path = cache_prefix + ".build.lock"
    deadline = time.time() + float(
        os.environ.get("MXTPU_CACHE_BUILD_TIMEOUT", 24 * 3600))
    while True:
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, ("%s:%d" % (socket.gethostname(),
                                     os.getpid())).encode())
            os.close(fd)
        except FileExistsError:
            # another rank is building: wait, then re-evaluate. The lock
            # records host:pid, so a waiter on the SAME host can detect a
            # SIGKILLed builder and break the lock instead of sleeping to
            # the 24h deadline; cross-host liveness stays unjudgeable and
            # falls back to the timeout.
            while os.path.exists(lock_path):
                if _lock_owner_dead(lock_path):
                    logging.warning(
                        "io_cache: cache-build lock %s held by a dead "
                        "local builder; breaking it", lock_path)
                    try:
                        os.unlink(lock_path)
                    except OSError:
                        pass
                    break
                if time.time() > deadline:
                    raise MXNetError(
                        "timed out waiting for another rank's cache "
                        "build (lock %s); if the builder crashed, "
                        "delete the lock file and retry" % lock_path)
                time.sleep(2.0)
            meta = _existing()
            if meta is not None:
                return meta
            continue    # builder produced a different cache — our turn
        break           # lock held: we build
    try:
        # holders re-check: the cache may have been completed between
        # our freshness check and winning the lock
        meta = _existing()
        if meta is not None:
            return meta
        return _locked_build(path_imgrec, cache_prefix, store_shape,
                             preprocess_threads, src_stat)
    finally:
        try:
            os.unlink(lock_path)
        except OSError:
            pass


def _lock_owner_dead(lock_path: str) -> bool:
    """True only when the lock names a builder on THIS host whose pid no
    longer exists. Unparseable/mid-write lock content and remote hosts
    read as alive — breaking a live builder's lock would let two ranks
    write the cache concurrently, which is worse than waiting."""
    import socket

    try:
        with open(lock_path) as f:
            owner = f.read().strip()
        host, pid = owner.rsplit(":", 1)
        pid = int(pid)
    except (OSError, ValueError):
        return False
    if host != socket.gethostname():
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except OSError:
        pass
    return False


def _locked_build(path_imgrec, cache_prefix, store_shape,
                  preprocess_threads, src_stat):
    import socket

    from . import recordio as rio

    c, h, w = store_shape
    meta_path = cache_prefix + ".meta.json"

    # pass 1: count records (framing reads only, no decode, no
    # retention — an ImageNet-scale .rec must never be resident in RAM)
    n = 0
    reader = rio.MXRecordIO(path_imgrec, "r")
    while reader.read() is not None:
        n += 1
    reader.close()
    if n == 0:
        raise MXNetError("no records found in %s" % path_imgrec)

    # pass 2: stream decode in bounded chunks — peak RAM is one chunk of
    # compressed records + its decoded rows, independent of dataset size
    reader = rio.MXRecordIO(path_imgrec, "r")
    first = reader.read()
    _, first_label = _decode_record(first, (h, w), c)
    label_width = first_label.size
    # host+pid: two ranks on different hosts can share a bare PID, and
    # colliding tmp paths would cross-corrupt the builds
    pid_sfx = ".tmp.%s.%d" % (socket.gethostname(), os.getpid())
    data_tmp = cache_prefix + ".data" + pid_sfx
    label_tmp = cache_prefix + ".label" + pid_sfx
    meta_tmp = meta_path + pid_sfx
    try:
        data_mm = np.lib.format.open_memmap(
            data_tmp, mode="w+", dtype=np.uint8, shape=(n, h, w, c))
        labels = np.zeros((n, label_width), dtype=np.float32)

        def _work(args):
            i, rec = args
            img, label = _decode_record(rec, (h, w), c)
            data_mm[i] = img
            labels[i, :] = label

        threads = max(1, int(preprocess_threads))
        chunk_size = max(64, 16 * threads)
        pool = ThreadPoolExecutor(threads) if threads > 1 else None
        try:
            i, rec = 0, first
            chunk = []
            while rec is not None:
                chunk.append((i, rec))
                if len(chunk) >= chunk_size:
                    if pool is not None:
                        list(pool.map(_work, chunk))
                    else:
                        for item in chunk:
                            _work(item)
                    chunk = []
                i += 1
                rec = reader.read()
            if chunk:
                if pool is not None:
                    list(pool.map(_work, chunk))
                else:
                    for item in chunk:
                        _work(item)
        finally:
            if pool is not None:
                pool.shutdown()
            reader.close()
        data_mm.flush()
        del data_mm
        np.save(label_tmp, labels)
        # np.save appends .npy; normalize the tmp name back
        if os.path.exists(label_tmp + ".npy"):
            os.replace(label_tmp + ".npy", label_tmp)

        meta = {"num": n, "height": h, "width": w, "channels": c,
                "label_width": int(label_width), "version": 1,
                # staleness fingerprint of the source .rec: a regenerated
                # rec (different size/mtime) forces a rebuild
                "src_size": src_stat.st_size,
                "src_mtime": src_stat.st_mtime_ns}
        with open(meta_tmp, "w") as f:
            json.dump(meta, f)
        # publish data before meta: meta's existence is the completeness
        # marker
        os.replace(data_tmp, cache_prefix + ".data")
        os.replace(label_tmp, cache_prefix + ".label")
        os.replace(meta_tmp, meta_path)
    except BaseException:
        # a failed build (bad record, decode exception, ^C) must not
        # leak dataset-sized tmp files into the shared cache dir
        for p in (data_tmp, label_tmp, label_tmp + ".npy", meta_tmp):
            try:
                os.unlink(p)
            except OSError:
                pass
        raise
    return meta


class CachedImageRecordIter(DataIter):
    """Iterator over a pre-decoded uint8 cache (see module docstring).

    Augmentation model (the steady-state-cheap subset of
    ``ImageRecordIter``): per-epoch reshuffle, random/center crop from
    the stored margin, random mirror. Color jitter and affine transforms
    belong in the one-off cache build or the model, not the per-epoch
    loop. ``mean_rgb``/``scale`` normalization and HWC->CHW run fused on
    device; the host only slices uint8.

    Sharding mirrors ``ImageRecordIter`` (``num_parts``/``part_index``
    give each worker a disjoint shard, reference
    iter_image_recordio.cc:109-170)."""

    def __init__(self, cache_prefix: str, data_shape, batch_size: int,
                 shuffle: bool = True, rand_crop: bool = False,
                 rand_mirror: bool = False, num_parts: int = 1,
                 part_index: int = 0, seed: int = 0,
                 mean_r: float = 0.0, mean_g: float = 0.0,
                 mean_b: float = 0.0, scale: float = 1.0,
                 device_normalize: bool = True,
                 device_augment: bool = False,
                 device_feed: Optional[bool] = None,
                 output_layout: str = "NCHW",
                 label_name: str = "softmax_label",
                 aug_replicas: Optional[int] = None):
        super().__init__()
        meta_path = cache_prefix + ".meta.json"
        if not os.path.exists(meta_path):
            raise MXNetError(
                "no decoded cache at %s (build one with "
                "mxnet_tpu.io_cache.build_decoded_cache or "
                "tools/im2tensor.py)" % meta_path)
        with open(meta_path) as f:
            self.meta = json.load(f)
        c, h, w = data_shape
        if c != self.meta["channels"]:
            raise MXNetError("cache stores %d channels, asked for %d"
                             % (self.meta["channels"], c))
        if h > self.meta["height"] or w > self.meta["width"]:
            raise MXNetError(
                "crop %dx%d exceeds stored size %dx%d — rebuild the "
                "cache with a larger store_shape"
                % (h, w, self.meta["height"], self.meta["width"]))
        self.data_shape = (c, h, w)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.scale = scale
        self.mean = np.asarray([mean_r, mean_g, mean_b][:c], np.float32)
        self.device_normalize = device_normalize
        # device_augment ships the FULL stored frame as uint8 and runs
        # crop + mirror + normalize fused on the accelerator (vmapped
        # dynamic_slice): the host's only per-batch work is one memmap
        # gather (~27k img/s/core measured at 256px — >10x a v5e's
        # 2.5k img/s ResNet-50 consumption); the crop FLOPs vanish into
        # the device step. The host-crop mode (~3k img/s/core) stays the
        # default for CPU-only runs where device cycles are host cycles.
        self.device_augment = device_augment
        # device_feed defers EVERYTHING to the training dispatch: the
        # batch ships the full stored frames as raw uint8 (4x fewer H2D
        # bytes than float32, and (sh*sw)/(4*h*w) of the host-crop float
        # path) and the crop offsets / mirror flags / mean / scale ride
        # along in ``batch.aug`` so the fused train step (fused_step.py)
        # can run cast+crop+mirror+normalize+layout INSIDE the one
        # donated XLA call — a cached epoch is memmap -> one dispatch ->
        # metrics. The same host RNG draws as device_augment mode keep
        # the two bit-identical in what the model sees.
        if device_feed is None:
            device_feed = _env.get("MXNET_TPU_DEVICE_FEED")
        self.device_feed = bool(device_feed)
        # data-parallel aug independence: with the batch sharded along a
        # dp mesh axis, the crop/mirror draws are keyed per (epoch,
        # cursor, replica) so each replica's rows come from its OWN
        # stream — replicas never apply one shared crop schedule to
        # different shards, and a replica's stream is stable however
        # the other shards change. aug_replicas=1 (the default) is
        # bit-identical to the historical single-stream draws.
        if aug_replicas is None:
            aug_replicas = _env.get("MXNET_TPU_AUG_REPLICAS") or 1
        self.aug_replicas = max(1, int(aug_replicas))
        if batch_size % self.aug_replicas:
            raise MXNetError(
                "batch_size %d not divisible by aug_replicas %d"
                % (batch_size, self.aug_replicas))
        # NHWC consumers (channels-last towers) read batches without the
        # NCHW transpose — emitting their layout directly avoids a
        # cancelling transpose pair per batch in the consumer
        if output_layout not in ("NCHW", "NHWC"):
            raise MXNetError("output_layout must be NCHW or NHWC, got %r"
                             % (output_layout,))
        self.output_layout = output_layout
        self.label_name = label_name
        self._data = np.load(cache_prefix + ".data", mmap_mode="r")
        self._labels = np.load(cache_prefix + ".label", mmap_mode="r")
        self._seed = int(seed)
        self._epoch = 0
        # rank sharding: contiguous stripes, same contract as
        # ImageRecordIter (disjoint, near-equal)
        n = self.meta["num"]
        if not (0 <= part_index < num_parts):
            raise MXNetError("part_index %d out of range for num_parts %d"
                             % (part_index, num_parts))
        per = n // num_parts
        extra = n % num_parts
        start = part_index * per + min(part_index, extra)
        count = per + (1 if part_index < extra else 0)
        self._indices = np.arange(start, start + count)
        self.num_data = count
        if count % batch_size != 0:
            # the final batch wraps around and reports the overlap via
            # getpad() (reference round_batch semantics); silence by
            # picking a batch_size that divides the shard
            logging.warning(
                "CachedImageRecordIter: %d samples in this shard is not "
                "a multiple of batch_size=%d; the last batch of each "
                "epoch wraps to the epoch start and reports pad=%d via "
                "getpad()", count, batch_size,
                batch_size - count % batch_size)
        self.cursor = -batch_size
        self._order = None
        self._norm_fn = None

    # -- normalize-on-device kernel -------------------------------------
    def _normalize(self, batch_u8: np.ndarray):
        """uint8 NHWC -> float32 NCHW, (x - mean) * scale, one fused XLA
        kernel on the default device. The uint8 host->device transfer
        moves 4x fewer bytes than shipping float32."""
        import jax
        import jax.numpy as jnp

        if self._norm_fn is None:
            mean = jnp.asarray(self.mean, jnp.float32)
            scale = float(self.scale)

            nchw = self.output_layout == "NCHW"

            @jax.jit
            def norm(x):
                y = (x.astype(jnp.float32) - mean) * scale
                return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y

            self._norm_fn = norm
        from .analysis import sanitizers as _san

        # sanctioned H2D: the uint8 batch enters the device here
        with _san.intentional_transfer():
            return self._norm_fn(batch_u8)

    def _device_augment(self, full_u8, tops, lefts, mirror):
        """uint8 NHWC full frames + per-image crop offsets/mirror mask ->
        augmented, normalized float32 NCHW, all in one jitted kernel."""
        import jax
        import jax.numpy as jnp

        if getattr(self, "_aug_fn", None) is None:
            c, h, w = self.data_shape
            mean = jnp.asarray(self.mean, jnp.float32)
            scale = float(self.scale)

            nchw = self.output_layout == "NCHW"

            @jax.jit
            def aug(x, top, left, m):
                def one(img, t, l, mi):
                    crop = jax.lax.dynamic_slice(img, (t, l, 0), (h, w, c))
                    return jnp.where(mi, crop[:, ::-1], crop)

                y = jax.vmap(one)(x, top, left, m)
                y = (y.astype(jnp.float32) - mean) * scale
                return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y

            self._aug_fn = aug
        from .analysis import sanitizers as _san

        # sanctioned H2D: stored frames + crop params enter the device
        with _san.intentional_transfer():
            return self._aug_fn(full_u8, tops, lefts, mirror)

    # -- DataIter interface ---------------------------------------------
    @property
    def provide_data(self):
        c, h, w = self.data_shape
        shape = (self.batch_size, c, h, w) if self.output_layout == "NCHW" \
            else (self.batch_size, h, w, c)
        return [DataDesc("data", shape)]

    @property
    def provide_label(self):
        lw = self.meta["label_width"]
        shape = (self.batch_size,) if lw == 1 else (self.batch_size, lw)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self.cursor = -self.batch_size
        self._epoch += 1
        self._order = None
        self._batch_cursor = None   # cursor values repeat across epochs

    # -- checkpoint support (checkpoint.py) ---------------------------
    def get_checkpoint_state(self) -> dict:
        """Stream identity for the snapshot. The aug RNG needs no
        explicit keys: crop/mirror draws and the shuffle order are pure
        functions of (seed, epoch, cursor, replica) — restoring those
        scalars restores every per-replica ``batch.aug`` stream."""
        return {"kind": type(self).__name__,
                "batch_size": self.batch_size,
                "seed": self._seed,
                "epoch": self._epoch,
                "aug_replicas": self.aug_replicas}

    def set_checkpoint_state(self, state: dict) -> None:
        """Seek to ``state["batches"]`` batches consumed within epoch
        ``state["epoch"]``; the next batch drawn reproduces the
        uninterrupted run's order and aug params bit-for-bit."""
        if "epoch" in state:
            self._epoch = int(state["epoch"])
        k = int(state.get("batches", 0))
        self.cursor = (k - 1) * self.batch_size
        self._order = None
        self._batch_cursor = None

    def _epoch_order(self):
        if self._order is None:
            if self.shuffle:
                rng = np.random.RandomState(
                    (self._seed * 0x9E3779B1 + self._epoch * 1000003)
                    & 0xFFFFFFFF)
                self._order = self._indices[rng.permutation(self.num_data)]
            else:
                self._order = self._indices
        return self._order

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    # C-API / base-DataIter accessor protocol (MXDataIterNext then
    # GetData/GetLabel): the batch for the current cursor is built once
    # and cached, so getdata()+getlabel() cost one construction
    def getdata(self):
        return self._current_batch().data
    def getlabel(self):
        return self._current_batch().label
    def getpad(self):
        # wrapped samples in the trailing partial batch — consumers
        # (predict/score) slice them off so every sample counts once
        return max(0, self.cursor + self.batch_size - self.num_data)
    def getindex(self):
        return self._current_batch().index

    def next(self) -> DataBatch:
        if not self.iter_next():
            raise StopIteration
        _tel.inc("io.batches")
        return self._current_batch()

    def _current_batch(self) -> DataBatch:
        if getattr(self, "_batch_cursor", None) != self.cursor:
            self._batch = self._make_batch()
            self._batch_cursor = self.cursor
        else:
            _tel.inc("io.batch_cache_hit")
        return self._batch

    def _aug_params(self, sh, sw, h, w):
        """Per-sample crop offsets and mirror flags for one batch, drawn
        per REPLICA: replica r's rows [r*B/R, (r+1)*B/R) come from a
        RandomState keyed (seed, epoch, cursor, r), so when ``batch.aug``
        is sharded along ``dp`` (batch axis 0, contiguous blocks) every
        replica augments its shard from an independent stream.
        ``aug_replicas=1`` reproduces the historical single-stream draws
        bit-for-bit. Shared by the device_feed and device_augment paths,
        which therefore stay bit-identical to each other."""
        R = self.aug_replicas
        shard = self.batch_size // R
        tops_l, lefts_l, mir_l = [], [], []
        for r in range(R):
            rs = np.random.RandomState(
                (self._seed * 2654435761 + self._epoch * 1000003
                 + self.cursor + r * 0x85EBCA6B) & 0xFFFFFFFF)
            if self.rand_crop and (sh > h or sw > w):
                tops_l.append(rs.randint(0, sh - h + 1, shard))
                lefts_l.append(rs.randint(0, sw - w + 1, shard))
            else:
                tops_l.append(np.full(shard, (sh - h) // 2))
                lefts_l.append(np.full(shard, (sw - w) // 2))
            mir_l.append((rs.rand(shard) < 0.5) if self.rand_mirror
                         else np.zeros(shard, bool))
        return (np.concatenate(tops_l), np.concatenate(lefts_l),
                np.concatenate(mir_l))

    def _make_batch(self) -> DataBatch:
        from . import ndarray as nd

        order = self._epoch_order()
        idx = order[self.cursor:self.cursor + self.batch_size]
        pad = self.getpad()
        if pad:
            # wrap the trailing partial batch to the epoch start
            # (reference round_batch): every sample is seen exactly once
            # and the duplicate count is reported through getpad()
            idx = np.concatenate([idx, np.resize(order, pad)])
            _tel.inc("io.pad_samples", pad)
        c, h, w = self.data_shape
        sh, sw = self.meta["height"], self.meta["width"]
        rng = np.random.RandomState(
            (self._seed * 2654435761 + self._epoch * 1000003
             + self.cursor) & 0xFFFFFFFF)

        if self.device_feed or self.device_augment:
            # order within a batch is irrelevant to SGD; sorting the
            # gather improves memmap locality
            gidx = np.sort(idx)
            full = np.ascontiguousarray(self._data[gidx])
            tops, lefts, mirror = self._aug_params(sh, sw, h, w)
            labels = np.asarray(self._labels[gidx])
            if self.meta["label_width"] == 1:
                labels = labels[:, 0]
            if self.device_feed:
                # raw uint8 crosses the link (ndarray.h2d_bytes counts
                # it); augmentation params ride host-side in batch.aug —
                # the consumer (fused step, or materialize_device_feed
                # for eager loops) owns the device math
                batch = DataBatch([nd.array(full)], [nd.array(labels)],
                                  pad=pad, index=gidx)
                batch.aug = {"tops": tops.astype(np.int32),
                             "lefts": lefts.astype(np.int32),
                             "mirror": mirror,
                             "mean": self.mean,
                             "scale": float(self.scale),
                             "layout": self.output_layout,
                             "crop": (h, w)}
                _tel.inc("io.feed_batches")
                return batch
            data = nd.NDArray(self._device_augment(full, tops, lefts,
                                                   mirror))
            return DataBatch([data], [nd.array(labels)], pad=pad,
                             index=gidx)

        out = np.empty((self.batch_size, h, w, c), dtype=np.uint8)
        for k, i in enumerate(idx):
            if self.rand_crop and (sh > h or sw > w):
                top = rng.randint(0, sh - h + 1)
                left = rng.randint(0, sw - w + 1)
            else:
                top, left = (sh - h) // 2, (sw - w) // 2
            img = self._data[i, top:top + h, left:left + w]
            if self.rand_mirror and rng.rand() < 0.5:
                img = img[:, ::-1]
            out[k] = img
        labels = np.asarray(self._labels[idx])
        if self.meta["label_width"] == 1:
            labels = labels[:, 0]

        if self.device_normalize:
            data = nd.NDArray(self._normalize(out))
        else:
            x = (out.astype(np.float32) - self.mean) * self.scale
            if self.output_layout == "NCHW":
                x = np.transpose(x, (0, 3, 1, 2))
            data = nd.array(x)
        return DataBatch([data], [nd.array(labels)], pad=pad,
                         index=np.asarray(idx))


_MATERIALIZE_CACHE: dict = {}


def materialize_device_feed(batch: DataBatch) -> DataBatch:
    """Eagerly apply a device-feed batch's deferred augmentation.

    Fallback for consumers without in-graph augmentation (the classic
    three-phase fit loop, score/predict): runs the SAME kernel math the
    fused step traces — dynamic-slice crop, mirror, (x - mean) * scale,
    layout — as its own jitted dispatch, and returns an ordinary batch.
    A batch without ``aug`` passes through untouched."""
    aug = getattr(batch, "aug", None)
    if aug is None:
        return batch
    import jax
    import jax.numpy as jnp

    from . import ndarray as nd

    h, w = aug["crop"]
    x = batch.data[0]
    c = x.shape[3]
    nchw = aug["layout"] == "NCHW"
    ck = (h, w, c, nchw)
    fn = _MATERIALIZE_CACHE.get(ck)
    if fn is None:
        @jax.jit
        def fn(x, tops, lefts, mirror, mean, scale):
            def one(img, t, l, mi):
                crop = jax.lax.dynamic_slice(img, (t, l, 0), (h, w, c))
                return jnp.where(mi, crop[:, ::-1], crop)

            y = jax.vmap(one)(x, tops, lefts, mirror)
            y = (y.astype(jnp.float32) - mean) * scale
            return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y

        _MATERIALIZE_CACHE[ck] = fn
    data = nd.NDArray(fn(x._data, np.asarray(aug["tops"], np.int32),
                         np.asarray(aug["lefts"], np.int32),
                         np.asarray(aug["mirror"], bool),
                         np.asarray(aug["mean"], np.float32),
                         np.asarray(aug["scale"], np.float32)))
    return DataBatch([data], batch.label, pad=batch.pad,
                     index=batch.index,
                     provide_data=batch.provide_data,
                     provide_label=batch.provide_label)


# registry entry: reachable from the C API (MXListDataIters /
# MXDataIterCreateIter) and therefore from every non-Python frontend,
# like the three reference iterators
from .io import _REG as _IO_REG  # noqa: E402

_IO_REG.register("CachedImageRecordIter")(CachedImageRecordIter)
