"""Symbolic graph layer.

TPU-native re-design of the reference's Symbol
(``include/mxnet/symbolic.h:40-317``, ``src/symbol/symbol.cc``): a Symbol is
a list of (node, output_index) heads over a DAG of op nodes; composition,
grouping, slicing, attributes and JSON save/load match the reference API.
Where the reference lowers Symbol -> StaticGraph -> GraphExecutor with its
own autodiff (``static_graph.cc:395`` MakeBackwardPass), here the executor
compiles the whole graph into ONE jitted XLA computation and gets gradients
from ``jax.vjp`` — the reference's bulk-execution segments
(``graph_executor.cc:842-892`` InitOpSegs) generalized to the full graph.

Symbol creation functions for every registered operator are generated at
import, mirroring ``python/mxnet/symbol.py`` ``_init_symbol_module``.
"""
from __future__ import annotations

import itertools
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError
from .name import NameManager
from .attribute import AttrScope
from .ops import OP_REGISTRY, Operator, create_operator

__all__ = ["Symbol", "Variable", "Group", "load", "load_json"]

_node_uid = itertools.count()


class _Node:
    """One graph node: an operator application or (op is None) a variable."""

    __slots__ = ("op", "name", "inputs", "attrs", "uid")

    def __init__(self, op: Optional[Operator], name: str,
                 inputs: List[Tuple["_Node", int]], attrs: Dict[str, str]):
        self.op = op
        self.name = name
        self.inputs = inputs
        self.attrs = attrs
        self.uid = next(_node_uid)

    @property
    def is_variable(self) -> bool:
        return self.op is None

    def num_outputs(self) -> int:
        return 1 if self.op is None else self.op.num_outputs


def topo_order(head_nodes: Sequence[_Node]) -> List[_Node]:
    """DFS post-order (reference ``Symbol::DFSVisit``, ``symbol.cc:119``)."""
    seen = set()
    order: List[_Node] = []

    def visit(node: _Node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for src, _ in node.inputs:
            visit(src)
        order.append(node)

    for node in head_nodes:
        visit(node)
    return order


class Symbol:
    """Immutable symbolic expression; composes via op creation functions and
    python operators exactly like ``mx.sym``."""

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = outputs

    # -- introspection -----------------------------------------------------
    @property
    def name(self) -> Optional[str]:
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def _head_nodes(self) -> List[_Node]:
        seen, heads = set(), []
        for node, _ in self._outputs:
            if id(node) not in seen:
                seen.add(id(node))
                heads.append(node)
        return heads

    def _topo(self) -> List[_Node]:
        return topo_order(self._head_nodes())

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._topo() if n.is_variable]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.is_variable:
                names.append(node.name)
            else:
                onames = node.op.list_outputs()
                suffix = onames[idx]
                names.append("%s_%s" % (node.name, suffix))
        return names

    def list_auxiliary_states(self) -> List[str]:
        names = []
        for node in self._topo():
            if not node.is_variable:
                for aux in node.op.list_auxiliary_states():
                    names.append("%s_%s" % (node.name, aux))
        return names

    # -- attributes (reference symbol attributes / ListAttr) ---------------
    def attr(self, key: str) -> Optional[str]:
        if len(self._outputs) == 1:
            return self._outputs[0][0].attrs.get(key)
        return None

    def list_attr(self) -> Dict[str, str]:
        if len(self._outputs) == 1:
            return dict(self._outputs[0][0].attrs)
        return {}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        ret = {}
        for node in self._topo():
            if node.attrs:
                ret[node.name] = dict(node.attrs)
        return ret

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            for k, v in kwargs.items():
                node.attrs[k] = v

    # -- composition -------------------------------------------------------
    def __getitem__(self, index) -> "Symbol":
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output '%s' not found in %s" % (index, names))
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    def get_internals(self) -> "Symbol":
        """Symbol exposing every internal node output, names ``<n>_output``
        (reference ``Symbol::GetInternals``)."""
        outputs = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                outputs.append((node, i))
        return Symbol(outputs)

    def get_children(self) -> Optional["Symbol"]:
        if len(self._outputs) != 1 or self._outputs[0][0].is_variable:
            return None
        return Symbol(list(self._outputs[0][0].inputs))

    def grad(self, wrt: Sequence[str]) -> "Symbol":
        """Gradient symbol (reference ``Symbol::Grad``, ``symbol.cc:570`` /
        C API ``MXSymbolGrad``): a bindable Symbol whose outputs are
        d(sum of this symbol's outputs)/d(arg) for each name in ``wrt``.
        Where the reference splices backward nodes into the graph, here one
        wrapper node closes over the whole-graph ``jax.vjp`` — binding it
        compiles forward+backward into a single XLA computation. Not
        JSON-serializable (the reference's grad symbols weren't load/save
        round-trippable either)."""
        wrt = list(wrt)
        arg_names = self.list_arguments()
        missing = [w for w in wrt if w not in arg_names]
        if missing:
            raise MXNetError("grad: unknown arguments %s (args: %s)"
                             % (missing, arg_names))
        base = self

        class _GradOp(Operator):
            name_hint = "grad"

            def __init__(op_self):
                super().__init__()
                op_self._eval = None

            def list_arguments(op_self):
                return list(arg_names)

            def list_outputs(op_self):
                return ["%s_grad" % w for w in wrt]

            def list_auxiliary_states(op_self):
                return base.list_auxiliary_states()

            def infer_shape(op_self, in_shapes):
                known = {n: s for n, s in zip(arg_names, in_shapes)
                         if s is not None}
                in_filled, _, aux_shapes = base._infer_shape_impl(
                    True, **known)
                by_name = dict(zip(arg_names, in_filled))
                out_shapes = [by_name[w] for w in wrt]
                if any(s is None for s in out_shapes):
                    raise MXNetError("grad: wrt shapes not inferable")
                return in_filled, out_shapes, aux_shapes

            def infer_type(op_self, in_types, out_types=None):
                import numpy as np

                dtype = next((t for t in in_types if t is not None), None)
                # aux states (BatchNorm moving stats) stay float32 under
                # mixed precision — same invariant as Operator.infer_type
                n_aux = len(base.list_auxiliary_states())
                aux_types = [np.dtype(np.float32)] * n_aux
                if dtype is None:
                    return (list(in_types), [None] * len(wrt), aux_types)
                return ([t if t is not None else dtype for t in in_types],
                        [dtype] * len(wrt), aux_types)

            def apply(op_self, octx, inputs, aux):
                import jax

                if op_self._eval is None:
                    from .executor import make_graph_eval
                    op_self._eval = make_graph_eval(base)[0]
                eval_graph = op_self._eval
                idx = [arg_names.index(w) for w in wrt]

                def f(wrt_vals):
                    args = list(inputs)
                    for i, v in zip(idx, wrt_vals):
                        args[i] = v
                    return eval_graph(args, list(aux), octx.rng,
                                      octx.is_train)

                (outs, aux_out), vjp = jax.vjp(
                    f, [inputs[i] for i in idx])
                import jax.numpy as jnp
                import numpy as np

                def head_ct(x):
                    # non-inexact heads (argmax_channel/Cast-to-int) take
                    # float0 cotangents — same rule as the executor's
                    # fused path (executor.py zero_cotangent); ones_like
                    # would make jax.vjp reject the graph
                    if jnp.issubdtype(x.dtype, jnp.inexact):
                        return jnp.ones_like(x)
                    return np.zeros(x.shape, jax.dtypes.float0)

                def zero_ct(x):
                    if jnp.issubdtype(x.dtype, jnp.inexact):
                        return jnp.zeros_like(x)
                    return np.zeros(x.shape, jax.dtypes.float0)

                heads = [head_ct(o) for o in outs]
                zero_aux = [zero_ct(a) for a in aux_out]
                grads, = vjp((heads, zero_aux))
                # integer wrt inputs come back as float0 zero-tangents;
                # materialize them so downstream graph nodes see arrays
                grads = [jnp.zeros(inputs[i].shape, inputs[i].dtype)
                         if getattr(g, "dtype", None) == jax.dtypes.float0
                         else g for g, i in zip(grads, idx)]
                return list(grads), list(aux_out)

        name = NameManager.current().get(None, "grad")
        node = _Node(_GradOp(), name,
                     [(n, 0) for n in self._topo() if n.is_variable], {})
        return Symbol([(node, i) for i in range(len(wrt))])

    # -- operator overloading (reference registered _Plus etc.) ------------
    def __add__(self, other):
        return _binary_create("_Plus", "_PlusScalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary_create("_Minus", "_MinusScalar", self, other)

    def __rsub__(self, other):
        return _scalar_create("_RMinusScalar", self, other)

    def __mul__(self, other):
        return _binary_create("_Mul", "_MulScalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary_create("_Div", "_DivScalar", self, other)

    def __rtruediv__(self, other):
        return _scalar_create("_RDivScalar", self, other)

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __pow__(self, other):
        return _binary_create("_Power", "_PowerScalar", self, other)

    def __rpow__(self, other):
        return _scalar_create("_RPowerScalar", self, other)

    def __neg__(self):
        return _scalar_create("_MulScalar", self, -1.0)

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group[%d]" % len(self._outputs))

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer_shape_impl(
            False, *args, **kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, Optional[tuple]] = {}
        if args:
            if len(args) > len(arg_names):
                raise MXNetError("too many positional shapes")
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        for name, shape in kwargs.items():
            if name not in arg_names:
                raise MXNetError("infer_shape: unknown argument '%s' (args: %s)"
                                 % (name, arg_names))
            known[name] = tuple(shape)

        nodes = self._topo()
        # node -> list of output shapes
        shapes: Dict[int, List[Optional[tuple]]] = {}
        aux_shapes: Dict[int, List[tuple]] = {}
        for node in nodes:
            shapes[node.uid] = [None] * node.num_outputs()
            if node.is_variable:
                if node.name in known:
                    shapes[node.uid][0] = known[node.name]
                elif node.attrs.get("__shape__"):
                    # Variable(shape=...) seeds inference (reference
                    # mx.sym.Variable shape attr, e.g. the (1, H)
                    # peephole biases in speech-demo's lstm_proj.py)
                    shapes[node.uid][0] = tuple(
                        int(v) for v in
                        node.attrs["__shape__"].strip("()").split(",")
                        if v.strip())

        # fixpoint forward propagation with write-back into variables
        # (reference StaticGraph::InferNodeShapes iterates to fixpoint,
        # static_graph.cc:59)
        last_err: Optional[MXNetError] = None
        for _ in range(3):
            changed = False
            for node in nodes:
                if node.is_variable:
                    continue
                in_shapes = [shapes[src.uid][i] for src, i in node.inputs]
                try:
                    in_filled, out_filled, aux = node.op.infer_shape(in_shapes)
                except MXNetError as e:
                    # may just mean "inputs not known yet" mid-fixpoint;
                    # keep the message for the final diagnostic
                    last_err = e
                    continue
                for (src, i), s in zip(node.inputs, in_filled):
                    if s is not None and shapes[src.uid][i] != tuple(s):
                        shapes[src.uid][i] = tuple(s)
                        changed = True
                for i, s in enumerate(out_filled):
                    if shapes[node.uid][i] != tuple(s):
                        shapes[node.uid][i] = tuple(s)
                        changed = True
                aux_shapes[node.uid] = [tuple(s) for s in aux]
            if not changed:
                break

        arg_shapes = [shapes[n.uid][0] for n in nodes if n.is_variable]
        out_shapes = [shapes[n.uid][i] for n, i in self._outputs]
        aux_list: List[tuple] = []
        for node in nodes:
            if not node.is_variable and node.op.list_auxiliary_states():
                if node.uid not in aux_shapes:
                    if partial:
                        aux_list.extend([None] * len(node.op.list_auxiliary_states()))
                        continue
                    raise MXNetError("cannot infer aux shapes of %s" % node.name)
                aux_list.extend(aux_shapes[node.uid])
        if not partial:
            if any(s is None for s in arg_shapes):
                missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
                raise MXNetError(
                    "infer_shape incomplete; unknown args: %s%s"
                    % (missing, " (last node error: %s)" % last_err
                       if last_err is not None else ""))
            if any(s is None for s in out_shapes):
                raise MXNetError(
                    "infer_shape could not infer outputs%s"
                    % (" (last node error: %s)" % last_err
                       if last_err is not None else ""))
        return arg_shapes, out_shapes, aux_list

    def infer_type(self, *args, **kwargs):
        """Fixpoint dtype propagation through per-op ``infer_type`` rules
        (reference ``StaticGraph::InferNodeTypes``,
        ``src/symbol/static_graph.cc:160-213``): forward passes fill output
        dtypes from inputs; write-back into still-unknown inputs propagates
        dtypes to variables (so ``infer_type(data=float16)`` types every
        downstream weight float16). Variables with no information after the
        fixpoint default to float32, matching the reference's default dtype
        for untyped arguments."""
        import numpy as np

        arg_names = self.list_arguments()
        known: Dict[str, Any] = {}
        if args:
            if len(args) > len(arg_names):
                raise MXNetError("too many positional types")
            for name, t in zip(arg_names, args):
                if t is not None:
                    known[name] = np.dtype(t)
        for name, t in kwargs.items():
            if name not in arg_names:
                raise MXNetError("infer_type: unknown argument '%s' (args: %s)"
                                 % (name, arg_names))
            if t is not None:  # None = unknown (np.dtype(None) is float64!)
                known[name] = np.dtype(t)

        nodes = self._topo()
        types: Dict[int, List[Optional[Any]]] = {}
        aux_types_map: Dict[int, List[Any]] = {}
        seeded = set()
        for node in nodes:
            types[node.uid] = [None] * node.num_outputs()
            if node.is_variable and node.name in known:
                types[node.uid][0] = known[node.name]
                seeded.add(node.uid)

        def _store(uid, i, t, by):
            # NB: don't compare a None slot with ``!=`` — numpy coerces
            # None to float64 (np.dtype(None) is float64), which would make
            # a float64 write into an unknown slot look like a no-op
            t = np.dtype(t)
            cur = types[uid][i]
            if cur is None:
                types[uid][i] = t
                return True
            if cur != t:
                # genuine dtype inconsistency (two producers/consumers
                # disagree, or a seed is contradicted) — the reference's
                # InferNodeTypes errors on mismatch rather than flapping
                raise MXNetError(
                    "infer_type: op '%s' infers dtype %s where %s was "
                    "%s" % (by, t,
                            "explicitly given" if uid in seeded
                            else "already inferred", cur))
            return False

        def _visit(node):
            in_types = [types[src.uid][i] for src, i in node.inputs]
            out_types = list(types[node.uid])
            cls = type(node.op)
            takes_out = cls.__dict__.get("_infer_type_takes_out")
            if takes_out is None:
                # detect once per op class whether infer_type accepts the
                # out_types argument (catching TypeError at call time would
                # misclassify genuine TypeErrors from user op bodies)
                import inspect

                try:
                    params = inspect.signature(cls.infer_type).parameters
                    takes_out = len(params) >= 3 or any(
                        p.kind is inspect.Parameter.VAR_POSITIONAL
                        for p in params.values())
                except (ValueError, TypeError):
                    takes_out = False
                cls._infer_type_takes_out = takes_out
            try:
                if takes_out:
                    in_filled, out_filled, aux = node.op.infer_type(
                        in_types, out_types)
                else:
                    in_filled, out_filled, aux = node.op.infer_type(in_types)
            except MXNetError:
                return False
            changed = False
            for (src, i), t in zip(node.inputs, in_filled):
                if t is not None:
                    changed |= _store(src.uid, i, t, node.name)
            for i, t in enumerate(out_filled):
                if t is not None:
                    changed |= _store(node.uid, i, t, node.name)
            aux_types_map[node.uid] = [np.dtype(t) for t in aux]
            return changed

        op_nodes = [n for n in nodes if not n.is_variable]

        def _fixpoint():
            # forward + reverse sweep per iteration (reference
            # InferNodeTypes' bidirectional iteration): a dtype seeded on
            # the last node of a chain reaches the first in one iteration
            for _ in range(len(op_nodes) + 2):
                changed = False
                for node in op_nodes:
                    changed |= _visit(node)
                for node in reversed(op_nodes):
                    changed |= _visit(node)
                if not changed:
                    break

        _fixpoint()
        # untyped variables default to float32; one more pass fills outputs
        # that depended on them
        defaulted = False
        for node in nodes:
            if node.is_variable and types[node.uid][0] is None:
                types[node.uid][0] = np.dtype("float32")
                defaulted = True
        if defaulted:
            _fixpoint()

        arg_types = [types[n.uid][0] for n in nodes if n.is_variable]
        out_types = [types[n.uid][i] for n, i in self._outputs]
        aux_list: List[Any] = []
        for node in nodes:
            if not node.is_variable and node.op.list_auxiliary_states():
                aux_list.extend(aux_types_map.get(
                    node.uid,
                    [np.dtype("float32")] * len(node.op.list_auxiliary_states())))
        if any(t is None for t in out_types):
            raise MXNetError("infer_type could not infer output dtypes")
        return arg_types, out_types, aux_list

    # -- serialization (reference static_graph.cc:551-615 JSON) ------------
    def tojson(self) -> str:
        nodes = self._topo()
        nid = {n.uid: i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": "null" if n.is_variable else n.op.op_name,
                "name": n.name,
                "param": {} if n.is_variable else n.op.param_str_dict(),
                "inputs": [[nid[src.uid], i] for src, i in n.inputs],
                "attr": dict(n.attrs),
            })
        heads = [[nid[n.uid], i] for n, i in self._outputs]
        return json.dumps({"nodes": jnodes,
                           "arg_nodes": [i for i, n in enumerate(nodes)
                                         if n.is_variable],
                           "heads": heads}, indent=2)

    def save(self, fname):
        from .filesystem import open_uri

        with open_uri(fname, "wb") as f:
            f.write(self.tojson().encode("utf-8"))

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None, **kwargs):
        """Infer shapes, allocate arrays, bind (reference symbol.py:635)."""
        from . import ndarray as nd
        from .executor import Executor

        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        # dtype propagation: type_dict seeds (e.g. data=float16) flow through
        # per-op infer_type so weights/grads/aux get their inferred dtypes
        arg_types, _, aux_types = self.infer_type(**(type_dict or {}))
        args = [nd.zeros(s, ctx=ctx, dtype=t)
                for s, t in zip(arg_shapes, arg_types)]
        if grad_req == "null":
            args_grad = None
        else:
            args_grad = {}
            reqs = grad_req if isinstance(grad_req, dict) else \
                {n: grad_req for n in arg_names}
            for n, s, t in zip(arg_names, arg_shapes, arg_types):
                if reqs.get(n, "null") != "null":
                    args_grad[n] = nd.zeros(s, ctx=ctx, dtype=t)
        aux_states = [nd.zeros(s, ctx=ctx, dtype=t)
                      for s, t in zip(aux_shapes, aux_types)]
        return Executor(self, ctx, args, args_grad, grad_req, aux_states)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None, **kwargs):
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        **kwargs)

    # evaluation convenience (not in reference; handy for tests)
    def eval(self, ctx=None, **kwargs):
        from .context import current_context

        ctx = ctx or current_context()
        args = {k: v for k, v in kwargs.items()}
        executor = self.bind(ctx, args, grad_req="null")
        return executor.forward(is_train=False)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def Variable(name: str, attr: Optional[Dict[str, str]] = None,
             shape=None, lr_mult=None, wd_mult=None, dtype=None,
             init=None) -> Symbol:
    """Create a symbolic variable (reference ``mx.sym.Variable``)."""
    if not isinstance(name, str):
        raise TypeError("Variable name must be a string")
    attr = AttrScope.current().get(attr)
    if shape is not None:
        attr["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        attr["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attr["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        attr["__dtype__"] = str(dtype)
    node = _Node(None, name, [], attr)
    return Symbol([(node, 0)])


def Group(symbols: Sequence[Symbol]) -> Symbol:
    """Group symbols into one multi-output symbol (reference CreateGroup)."""
    outputs: List[Tuple[_Node, int]] = []
    for s in symbols:
        if not isinstance(s, Symbol):
            raise TypeError("Group expects Symbols")
        outputs.extend(s._outputs)
    return Symbol(outputs)


def load_json(json_str: str) -> Symbol:
    data = json.loads(json_str)
    nodes: List[_Node] = []
    for jn in data["nodes"]:
        inputs = [(nodes[i], idx) for i, idx in jn["inputs"]]
        if jn["op"] == "null":
            node = _Node(None, jn["name"], inputs, dict(jn.get("attr", {})))
        else:
            op = create_operator(jn["op"], **jn.get("param", {}))
            node = _Node(op, jn["name"], inputs, dict(jn.get("attr", {})))
        nodes.append(node)
    outputs = [(nodes[i], idx) for i, idx in data["heads"]]
    return Symbol(outputs)


def load(fname) -> Symbol:
    from .filesystem import open_uri

    with open_uri(fname, "rb") as f:
        return load_json(f.read().decode("utf-8"))


def _create(op_name: str, *args, **kwargs) -> Symbol:
    """Create a symbol by applying a registered operator — the generated
    creation functions call this (reference ``Symbol::Create`` +
    ``Compose``, ``symbol.cc:335-403``)."""
    name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)
    sym_kwargs = {}
    param_kwargs = {}
    for k, v in kwargs.items():
        if isinstance(v, Symbol):
            sym_kwargs[k] = v
        else:
            param_kwargs[k] = v
    # variadic ops (Concat, ElementWiseSum): the reference frontend filled
    # num_args from the positional input count (symbol.py Compose)
    if args and "num_args" not in param_kwargs:
        from .ops.registry import get_operator_class

        cls = get_operator_class(op_name)
        if cls is not None and "num_args" in getattr(cls, "PARAMS", {}):
            param_kwargs["num_args"] = len(args)
    op = create_operator(op_name, **param_kwargs)
    arg_names = op.list_arguments()
    name = NameManager.current().get(name, op.name_hint)
    attrs = AttrScope.current().get(attr)

    # positional then keyword matching (Compose semantics)
    if args and sym_kwargs:
        raise MXNetError(
            "%s: cannot mix positional and keyword symbol inputs" % op_name)
    inputs_by_name: Dict[str, Symbol] = dict(sym_kwargs)
    for argn, s in zip(arg_names, args):
        if not isinstance(s, Symbol):
            raise TypeError("%s: positional inputs must be Symbols" % op_name)
        inputs_by_name[argn] = s
    for k in inputs_by_name:
        if k not in arg_names:
            raise MXNetError("%s: unknown input '%s' (expects %s)"
                             % (op_name, k, arg_names))

    inputs: List[Tuple[_Node, int]] = []
    for argn in arg_names:
        if argn in inputs_by_name:
            s = inputs_by_name[argn]
            if len(s._outputs) != 1:
                raise MXNetError("%s: input '%s' must be single-output"
                                 % (op_name, argn))
            inputs.append(s._outputs[0])
        else:
            # auto-create missing inputs as variables (reference behavior:
            # weights/bias become arguments named <op>_<arg>)
            var = _Node(None, "%s_%s" % (name, argn), [],
                        AttrScope.current().get(None))
            inputs.append((var, 0))
    node = _Node(op, name, inputs, attrs)
    return Symbol([(node, i) for i in range(op.num_outputs)])


def _binary_create(op_name, scalar_op_name, lhs, rhs) -> Symbol:
    if isinstance(rhs, Symbol):
        return _create(op_name, lhs=lhs, rhs=rhs)
    return _scalar_create(scalar_op_name, lhs, rhs)


def _scalar_create(op_name, data, scalar) -> Symbol:
    return _create(op_name, data=data, scalar=float(scalar))


# ---------------------------------------------------------------------------
# auto-generate creation functions from the registry (reference
# _init_symbol_module, python/mxnet/symbol.py:1187)
# ---------------------------------------------------------------------------

def _make_creator(op_name: str):
    def creator(*args, **kwargs):
        return _create(op_name, *args, **kwargs)
    creator.__name__ = op_name
    cls = OP_REGISTRY.get(op_name)
    creator.__doc__ = cls.__doc__ or "Apply operator %s." % op_name
    return creator


def _make_minmax(fname, op, scalar_op, number_fn):
    """mx.symbol.maximum/minimum (reference python/mxnet/symbol.py):
    symbol x symbol, symbol x scalar (either order), or two plain numbers
    (returns the number, like the reference)."""

    def fn(lhs, rhs):
        if isinstance(lhs, Symbol) and isinstance(rhs, Symbol):
            return _binary_create(op, scalar_op, lhs, rhs)
        if isinstance(lhs, Symbol):
            return _scalar_create(scalar_op, lhs, rhs)
        if isinstance(rhs, Symbol):
            return _scalar_create(scalar_op, rhs, lhs)
        return number_fn(lhs, rhs)

    fn.__name__ = fname
    fn.__doc__ = _make_minmax.__doc__
    return fn


maximum = _make_minmax("maximum", "_Maximum", "_MaximumScalar",
                       lambda a, b: a if a > b else b)
minimum = _make_minmax("minimum", "_Minimum", "_MinimumScalar",
                       lambda a, b: a if a < b else b)


def pow(lhs, rhs):
    """lhs ** rhs for symbol/scalar mixes; two numbers give the plain
    power (reference mx.symbol.pow)."""
    if isinstance(lhs, Symbol):
        return lhs ** rhs
    if isinstance(rhs, Symbol):
        return rhs.__rpow__(lhs)
    return lhs ** rhs


__all__ += ["maximum", "minimum", "pow"]


def _init_symbol_module():
    done = set()
    for lname, cls in list(OP_REGISTRY.items()):
        for op_name in (cls.op_name,) + getattr(cls, "op_aliases", ()):
            if op_name in done:
                continue
            done.add(op_name)
            fn = _make_creator(cls.op_name)
            fn.__name__ = op_name
            globals()[op_name] = fn
            if not op_name.startswith("_"):
                __all__.append(op_name)


_init_symbol_module()
