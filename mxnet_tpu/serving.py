"""Continuous-batching inference serving tier.

The reference framework stopped at a predict-only C ABI (one
synchronous forward per caller); this module is the throughput/latency
path the ROADMAP's "millions of users" north star actually needs. It
composes pieces that already exist — the single-dispatch
:class:`~mxnet_tpu.fused_step.FusedInfer` executable, the ``dp`` device
mesh + NamedSharding batch placement from the executor group, the xprof
compile registry and the Prometheus :class:`~mxnet_tpu.tracing.MetricsServer`
— into three layers:

* :class:`BatchScheduler` — a deadline-aware continuous batcher.
  Every request carries a ``priority`` lane (interactive/batch) and a
  ``deadline_ms`` (explicit, or derived from the SLO); the dispatch
  decision is driven by the earliest deadline in the queue — dispatch
  immediately when any pending request's slack (deadline minus the
  rolling service-time estimate) is about to run out, otherwise keep
  coalescing toward the next bucket rung. A closed-loop
  :class:`AdaptiveWaitController` replaces the fixed ``max_wait_ms``:
  it reads the sliding-window SLO probe and an EWMA arrival-rate
  estimator, widening the coalescing window while p99 headroom exists
  (filling bigger buckets) and collapsing it when the probe nears
  breach. Every dispatched batch is padded up to a small ladder of
  bucket sizes (default powers of two), so mixed request rates compile
  at most ``len(buckets)`` executables EVER and steady state runs
  retrace-free at exactly one XLA dispatch per served batch. Under
  overload the scheduler sheds the lowest-priority, most-expired
  requests with a typed :class:`RequestShed` error instead of
  convoying every queued request past the SLO.
* :class:`InferenceServer` — wires a bound Module to a FusedInfer
  (params packed once, replicated across the mesh; request batches
  sharded along ``dp``), owns the scheduler, exports `/metrics` +
  `/healthz` (including the controller state: adaptive wait, queue
  depth, arrival rate), and registers the SLO health probe: when the
  sliding-window p99 exceeds ``MXNET_TPU_SERVE_SLO_MS``, `/healthz`
  flips to ``degraded`` (HTTP 503) and a ``slow_request`` anomaly
  fires through the step-trace detectors.
* latency decomposition — every request's wall time splits exactly
  into intake wait / scheduler hold / H2D+pad / dispatch / D2H
  (``serve.queue_ms``, ``serve.sched_idle_ms``, ``serve.h2d_ms``,
  ``serve.dispatch_ms``, ``serve.d2h_ms``; the five sum to
  ``serve.request_ms`` per request, pinned by test) with p50/p99
  exported through the metrics server and summarized by
  ``trace_report --view serve``. ``serve.pad_waste_ms`` stays an
  overlay (dispatch time × padded fraction), not a wall-time term.

Shutdown contract: ``close()`` stops intake, DRAINS every queued
request (each gets a result or an error — nothing hangs a caller), and
joins the worker thread; the tests' thread/process leak gate holds.

``bench.py serve`` drives this with an open-loop Poisson load sweep and
writes ``SERVE_bench.json`` (requests/sec, goodput at SLO, p50/p99/p999
latency, per-tier batch occupancy, the adaptive-wait trajectory and
per-lane goodput under ``--lanes``).
"""
from __future__ import annotations

import collections
import logging
import queue as _queue
import threading
import time
import uuid
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import dtrace as _dtrace
from . import env as _env
from . import faults as _faults
from . import telemetry as _tel
from . import tracing as _tracing
from .base import MXNetError
from .io_pipeline import RequestStager

__all__ = ["bucket_ladder", "LANES", "Request", "RequestShed",
           "ArrivalRateEstimator", "ServiceTimeEstimator",
           "AdaptiveWaitController", "BatchScheduler", "InferenceServer"]

_log = logging.getLogger(__name__)


def _corr_ids(reqs, cap: int = 8) -> str:
    """Correlation ids for a server-side log line: request ids, each
    with its trace id when the request rode in sampled — a
    client-reported failure greps straight to the server event."""
    parts = []
    for r in list(reqs)[:cap]:
        ctx = getattr(r, "trace_ctx", None)
        parts.append("%s(trace=%s)" % (r.request_id, ctx["t"])
                     if ctx else r.request_id)
    if len(reqs) > cap:
        parts.append("... +%d more" % (len(reqs) - cap))
    return ", ".join(parts)

#: The two priority lanes. ``interactive`` requests default to the SLO
#: deadline; ``batch`` requests default to a 4x looser one and are the
#: first shed under overload — in exchange they ride along in whatever
#: bucket capacity the interactive lane leaves free, which is what
#: keeps them starvation-free AND keeps occupancy high.
LANES = ("interactive", "batch")


class RequestShed(MXNetError):
    """Typed overload-shed error: the scheduler dropped this request
    (lowest-priority, most-expired first) instead of convoying every
    queued request past the SLO. Safe to retry on another replica —
    the fleet router maps it onto its retryable taxonomy."""


def bucket_ladder(max_batch: int, dp: int = 1,
                  spec: Optional[str] = None,
                  mesh=None) -> Tuple[int, ...]:
    """The padded batch-size ladder: every dispatched batch rounds up
    to the next rung, so the serving path compiles at most
    ``len(ladder)`` executables total. Default rungs are powers of two
    from ``dp`` up to ``max_batch``; an explicit ``spec`` (or
    ``MXNET_TPU_SERVE_BUCKETS``) is a comma list. Every rung is rounded
    up to a multiple of the mesh's BATCH-SHARDING EXTENT so the batch
    axis always shards evenly: pass ``mesh`` and the extent is the
    product of its data axes (``dp``, ``dp x fsdp`` — and on a
    ``(dp, tp)`` serving mesh just ``dp``: rounding to ``mesh.size``
    there would over-pad every bucket by the tp factor), or pass the
    extent directly as ``dp``."""
    if mesh is not None:
        from .parallel.sharding import batch_shard_extent

        dp = batch_shard_extent(mesh)
    dp = max(1, int(dp))
    if spec is None:
        spec = _env.get("MXNET_TPU_SERVE_BUCKETS")
    if spec:
        rungs = [int(s) for s in str(spec).split(",") if s.strip()]
    else:
        rungs, b = [], 1
        while b < max_batch:
            rungs.append(b)
            b *= 2
        rungs.append(max_batch)
    ladder = sorted({max(dp, -(-r // dp) * dp) for r in rungs})
    if any(r <= 0 for r in ladder) or not ladder:
        raise MXNetError("invalid bucket ladder %r" % (ladder,))
    if ladder[-1] < max_batch:
        ladder.append(-(-max_batch // dp) * dp)
    return tuple(ladder)


# ---------------------------------------------------------------------------
# the adaptive control plane: arrival rate, service time, wait window
# ---------------------------------------------------------------------------

class ArrivalRateEstimator:
    """EWMA of the request arrival rate (req/s), fed one ``observe()``
    per accepted request. ``rate()`` decays toward zero while no
    requests arrive (bounded above by ``1/idle``), so a burst followed
    by silence does not keep the scheduler waiting for phantom
    arrivals. ``clock`` is injectable for fake-clock tests."""

    def __init__(self, clock=time.perf_counter, alpha: float = 0.2):
        self._clock = clock
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self._rate = 0.0

    def observe(self):
        now = self._clock()
        with self._lock:
            if self._last is not None:
                dt = max(now - self._last, 1e-6)
                self._rate += self._alpha * (1.0 / dt - self._rate)
            self._last = now

    def rate(self) -> float:
        with self._lock:
            if self._last is None:
                return 0.0
            idle = self._clock() - self._last
            if idle <= 1e-6:
                return self._rate
            return min(self._rate, 1.0 / idle)


class ServiceTimeEstimator:
    """EWMA of the per-batch service wall time (stage + dispatch +
    d2h) keyed by bucket rung — the scheduler subtracts this from a
    request's deadline to know how long it can keep coalescing before
    the request can no longer be served in time. Unseen rungs borrow
    the worst known estimate (conservative), or ``default_ms`` before
    any dispatch has completed."""

    def __init__(self, default_ms: float = 2.0, alpha: float = 0.25):
        self._default = float(default_ms)
        self._alpha = float(alpha)
        self._est: dict = {}

    def observe(self, bucket: int, ms: float):
        cur = self._est.get(bucket)
        self._est[bucket] = (float(ms) if cur is None
                             else cur + self._alpha * (float(ms) - cur))

    def estimate_ms(self, bucket: int) -> float:
        est = self._est.get(bucket)
        if est is not None:
            return est
        return max(self._est.values()) if self._est else self._default


class AdaptiveWaitController:
    """Closed-loop coalescing window: widen the wait while the SLO
    probe shows p99 headroom (bigger buckets, better occupancy),
    collapse it toward the floor as the probe nears breach. The law is
    deliberately monotone: for the same state, a worse p99 never
    produces a longer wait — pinned by test.

    The ceiling defaults to half the SLO (capped at 50 ms) so the
    window alone can never spend the whole latency budget; the
    deadline-slack check in the scheduler bounds the rest.
    """

    def __init__(self, slo_ms: float, start_ms: float,
                 floor_ms: float = 0.2, ceil_ms: Optional[float] = None,
                 widen: float = 1.5, collapse: float = 0.5,
                 lo: float = 0.15, hi: float = 0.35):
        self.slo_ms = float(slo_ms or 0.0)
        if ceil_ms is None:
            ceil_ms = (min(50.0, 0.5 * self.slo_ms) if self.slo_ms
                       else float(start_ms))
        self.floor_ms = float(floor_ms)
        self.ceil_ms = max(self.floor_ms, float(ceil_ms))
        self.widen = float(widen)
        self.collapse = float(collapse)
        self.lo = float(lo)
        self.hi = float(hi)
        self.wait_ms = min(max(float(start_ms), self.floor_ms),
                           self.ceil_ms)
        self.updates = 0

    def update(self, p99_ms: Optional[float]) -> float:
        """One control step: feed the sliding-window p99, get the new
        wait. ``p99_ms=None`` (no samples yet) reads as full headroom."""
        self.updates += 1
        if not self.slo_ms:
            return self.wait_ms
        headroom = (1.0 if p99_ms is None
                    else 1.0 - float(p99_ms) / self.slo_ms)
        w = self.wait_ms
        if headroom < self.lo:
            w *= self.collapse
        elif headroom > self.hi:
            w *= self.widen
        self.wait_ms = min(self.ceil_ms, max(self.floor_ms, w))
        return self.wait_ms


class Request:
    """One in-flight inference request: the payload arrays (one per
    data name, leading axis = rows, normally 1) plus the completion
    event the scheduler signals once results (or an error) land.

    Every request carries a stable ``request_id`` (caller-provided or
    a fresh uuid): a hedged or retried duplicate re-submitted with the
    same id is deduped at the scheduler instead of dispatched twice —
    safe because the ``FusedInfer`` dispatch is idempotent (nothing
    donated, no state mutated). ``deadline_ms``/``priority`` form the
    scheduling envelope: the deadline drives earliest-deadline-first
    dispatch and overload shedding; the lane picks the default
    deadline and the shed order."""

    __slots__ = ("arrays", "rows", "t_enq", "_done", "result", "error",
                 "queue_ms", "latency_ms", "request_id", "deadline_ms",
                 "priority", "t_deadline", "t_adm", "sched_idle_ms",
                 "components", "trace_ctx")

    def __init__(self, arrays: Sequence[np.ndarray],
                 request_id: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[str] = None,
                 trace_ctx: Optional[dict] = None):
        self.arrays = [np.asarray(a) for a in arrays]
        self.rows = int(self.arrays[0].shape[0])
        self.t_enq = time.perf_counter()
        self._done = threading.Event()
        self.result: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.queue_ms = 0.0
        self.latency_ms = 0.0
        self.sched_idle_ms = 0.0
        self.request_id = request_id or uuid.uuid4().hex
        self.deadline_ms = (None if not deadline_ms
                            else float(deadline_ms))
        self.priority = priority or "interactive"
        self.t_deadline: Optional[float] = None   # stamped at submit
        self.t_adm = self.t_enq
        self.components: Optional[dict] = None
        # the distributed-trace context this request rode in with
        # (None = untraced); the scheduler parents its decomposition
        # spans under it
        self.trace_ctx = trace_ctx

    def get(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until the scheduler served this request; returns the
        per-row result arrays (post-processing outputs when the server
        was built with ``top_k``, else the raw forward outputs)."""
        if not self._done.wait(timeout):
            raise MXNetError("inference request timed out after %ss"
                             % timeout)
        if self.error is not None:
            raise self.error
        return self.result

    def done(self) -> bool:
        return self._done.is_set()


class BatchScheduler:
    """Deadline-aware continuous batcher in front of a compiled-once
    infer callable.

    ``infer_fn(placed_arrays) -> (outs, post)`` is dispatched once per
    coalesced batch (a :class:`~mxnet_tpu.fused_step.FusedInfer`); the
    scheduler owns request admission, the priority lanes, the bucket
    ladder, padding (via
    :class:`~mxnet_tpu.io_pipeline.RequestStager`), per-request result
    slicing, the latency decomposition and the SLO window. One daemon
    worker thread ("mxtpu-serve-batcher") runs the loop; ``close()``
    joins it after draining the queue.

    The dispatch decision (``_decide``) fires on the first of:

    * **full** — pending rows reached ``max_batch``;
    * **deadline** — the earliest pending deadline minus the rolling
      service-time estimate (x2 safety) is about to run out;
    * **rung_fill** — pending rows sit exactly on a bucket rung and
      the arrival-rate estimate says the next rung is out of reach;
    * **idle** — (adaptive) the arrival rate says nothing more is
      plausibly arriving inside the window, so holding a nearly-empty
      bucket open buys nothing;
    * **window** — the coalescing window (adaptive or static
      ``max_wait_ms``) expired. When crossing the next bucket rung is
      reachable within both the remaining deadline slack and twice the
      window, the window stretches to meet the fill.

    ``clock`` and ``autostart=False`` make the whole decision plane
    drivable from a fake-clock test via :meth:`step`.
    """

    #: deadline-slack safety: dispatch when ``deadline - now`` falls
    #: below ``SVC_SAFETY * service_estimate + SLACK_MARGIN_MS``
    SVC_SAFETY = 2.0
    SLACK_MARGIN_MS = 2.0
    #: the window may stretch to this multiple of itself to finish
    #: filling a bucket rung that is reachable within the slack
    FILL_STRETCH = 2.0

    def __init__(self, infer_fn, data_shapes: Sequence[tuple],
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 slo_ms: Optional[float] = None,
                 dp: int = 1, place=None, slo_window: int = 512,
                 adaptive: Optional[bool] = None,
                 default_deadline_ms: Optional[float] = None,
                 batch_deadline_ms: Optional[float] = None,
                 clock=time.perf_counter, autostart: bool = True):
        self._infer = infer_fn
        self._data_shapes = [tuple(s) for s in data_shapes]
        dp = max(1, int(dp))
        if max_batch is None:
            max_batch = _env.get("MXNET_TPU_SERVE_MAX_BATCH")
        max_batch = max(dp, -(-int(max_batch) // dp) * dp)
        self.max_batch = max_batch
        self.max_wait_ms = float(
            _env.get("MXNET_TPU_SERVE_MAX_WAIT_MS")
            if max_wait_ms is None else max_wait_ms)
        if buckets is None:
            self.buckets = bucket_ladder(max_batch, dp=dp)
        else:
            self.buckets = bucket_ladder(max_batch, dp=dp,
                                         spec=",".join(map(str, buckets)))
        self._rung_set = frozenset(self.buckets)
        self.slo_ms = float(_env.get("MXNET_TPU_SERVE_SLO_MS")
                            if slo_ms is None else slo_ms)
        self._clock = clock
        # adaptive control plane: needs an SLO to close the loop on
        if adaptive is None:
            adaptive = _env.get("MXNET_TPU_SERVE_ADAPTIVE")
        self.adaptive = bool(adaptive) and self.slo_ms > 0
        self._arrival = ArrivalRateEstimator(clock=clock)
        self._svc = ServiceTimeEstimator()
        self._ctl = AdaptiveWaitController(self.slo_ms, self.max_wait_ms)
        # lane deadline defaults: explicit arg > env knob > SLO (and 4x
        # the interactive default for the batch lane)
        dflt = float(_env.get("MXNET_TPU_SERVE_DEADLINE_MS")
                     if default_deadline_ms is None
                     else default_deadline_ms)
        if dflt <= 0:
            dflt = self.slo_ms if self.adaptive else 0.0
        bdflt = float(_env.get("MXNET_TPU_SERVE_BATCH_DEADLINE_MS")
                      if batch_deadline_ms is None else batch_deadline_ms)
        if bdflt <= 0:
            bdflt = 4.0 * dflt if dflt else 0.0
        self._deadline_default_ms = {"interactive": dflt, "batch": bdflt}
        self._shed_rows = 2 * self.max_batch
        self._stager = RequestStager(place=place)
        self._q: _queue.Queue = _queue.Queue()
        self._pending: List[Request] = []
        self._pending_rows = 0
        self._dispatch_reason = ""
        self._stop = threading.Event()
        self._closed = False
        self._started = False
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._lat_cap = int(slo_window)
        # controller feedback window: (t_done, latency_ms), time-bounded
        # in recent_quantile so a transient ages out by wall clock, not
        # by waiting for enough new samples to push it off the end
        self._recent: collections.deque = collections.deque(maxlen=64)
        self._warmed: set = set()
        self._served = 0
        self._batches = 0
        self._occ_sum = 0.0
        self._in_flight = 0
        self._slo_breaches = 0
        # per-scheduler latency histogram: a standalone (non-registry)
        # instance so two in-process replicas never share one series —
        # this is the payload an obswatch InProc scrape federates
        self._lat_hist = _tel.Histogram("serve.request_ms")
        self._lane = {lane: {"served": 0, "shed": 0} for lane in LANES}
        self._depth_samples: collections.deque = collections.deque(
            maxlen=4096)
        self._traj: collections.deque = collections.deque(maxlen=512)
        self._t0 = self._clock()
        # retry-safety: request-id -> Request. In-flight dedup is always
        # safe (same object); completed-result reuse additionally needs
        # the infer fn tagged idempotent (FusedInfer is: nothing
        # donated, no state mutated).
        self._idempotent = bool(getattr(infer_fn, "idempotent", False))
        self._inflight_ids: dict = {}
        self._done_ids: collections.OrderedDict = collections.OrderedDict()
        self._done_cap = 1024
        # the last SLO-breaching traced request: the slo_probe attaches
        # it so a degraded /healthz names a concrete reproducible trace
        self._last_breach_trace: Optional[str] = None
        self._worker: Optional[threading.Thread] = None
        if autostart:
            self.start()

    def start(self):
        """Start the worker loop (called by ``__init__`` unless
        ``autostart=False``). A second call is a programming error —
        the double-start guard keeps two batcher threads from racing
        on one queue."""
        with self._lock:
            if self._closed:
                raise MXNetError("BatchScheduler is closed; build a "
                                 "new one instead of restarting it")
            if self._started:
                raise MXNetError("BatchScheduler already started "
                                 "(double start)")
            self._started = True
        self._worker = threading.Thread(target=self._run,
                                        name="mxtpu-serve-batcher",
                                        daemon=True)
        self._worker.start()

    def rebind_infer(self, infer_fn, place=None):
        """Atomically re-point dispatching at a new infer callable (and
        the stager at its placement fn): the server rebuilt its
        FusedInfer after a re-bind across mesh factorings. Taken under
        the scheduler lock so a concurrently-running ``_dispatch``
        finishes whole on whichever executable it already read."""
        with self._lock:
            self._infer = infer_fn
            if place is not None:
                self._stager.rebind_place(place)

    # -- intake ------------------------------------------------------------
    def submit(self, arrays: Sequence[np.ndarray],
               request_id: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[str] = None,
               trace_ctx: Optional[dict] = None) -> Request:
        """Enqueue one request (arrays follow the server's data names;
        leading axis = rows). Returns immediately; block on
        ``Request.get()``. ``deadline_ms`` is the remaining latency
        budget (defaults to the lane's configured deadline, then the
        SLO); ``priority`` picks the lane (``interactive`` default);
        ``trace_ctx`` is the distributed-trace context propagated from
        the fleet router (the dispatch decomposition lands under it as
        child spans). Re-submitting a ``request_id`` that is already
        in flight (or recently served, when the infer fn is
        idempotent) returns the original request instead of
        dispatching the work twice and counts
        ``serve.duplicate_requests``."""
        priority = priority or "interactive"
        if priority not in LANES:
            raise MXNetError("unknown priority lane %r (expected one "
                             "of %s)" % (priority, ", ".join(LANES)))
        if deadline_ms is None:
            deadline_ms = self._deadline_default_ms[priority] or None
        req = Request(arrays, request_id, deadline_ms=deadline_ms,
                      priority=priority, trace_ctx=trace_ctx)
        req.t_enq = self._clock()
        req.t_adm = req.t_enq
        if req.deadline_ms:
            req.t_deadline = req.t_enq + req.deadline_ms / 1e3
        if len(req.arrays) != len(self._data_shapes):
            raise MXNetError("expected %d input arrays, got %d"
                             % (len(self._data_shapes), len(req.arrays)))
        for a, shape in zip(req.arrays, self._data_shapes):
            if tuple(a.shape[1:]) != tuple(shape[1:]):
                raise MXNetError(
                    "request row shape %r does not match the served "
                    "model's %r (batch ladder only pads the batch "
                    "axis; other dims would retrace)"
                    % (tuple(a.shape[1:]), tuple(shape[1:])))
        if req.rows > self.max_batch:
            raise MXNetError("request of %d rows exceeds max_batch=%d"
                             % (req.rows, self.max_batch))
        if self._closed:
            raise MXNetError("BatchScheduler is closed")
        with self._lock:
            dup = self._inflight_ids.get(req.request_id)
            if dup is None and self._idempotent:
                dup = self._done_ids.get(req.request_id)
            if dup is not None:
                _tel.inc("serve.duplicate_requests")
                return dup
            self._inflight_ids[req.request_id] = req
            self._in_flight += 1
        self._arrival.observe()
        _tel.inc("serve.requests")
        _tel.set_gauge("serve.in_flight", self.in_flight())
        self._q.put(req)
        return req

    def in_flight(self) -> int:
        """Requests accepted but not yet completed (the /healthz
        identity payload reads this)."""
        with self._lock:
            return self._in_flight

    def _finish(self, req: Request, served: bool):
        """Completion bookkeeping: retire the request id (into the
        dedup cache when served and the infer fn is idempotent) and
        drop it from the in-flight count."""
        with self._lock:
            if self._inflight_ids.pop(req.request_id, None) is not None:
                self._in_flight -= 1
            if served and self._idempotent:
                self._done_ids[req.request_id] = req
                while len(self._done_ids) > self._done_cap:
                    self._done_ids.popitem(last=False)

    def infer(self, arrays: Sequence[np.ndarray],
              timeout: Optional[float] = 60.0,
              deadline_ms: Optional[float] = None,
              priority: Optional[str] = None) -> List[np.ndarray]:
        """Synchronous convenience: submit + wait."""
        return self.submit(arrays, deadline_ms=deadline_ms,
                           priority=priority).get(timeout)

    # -- scheduling loop ---------------------------------------------------
    def _admit_intake(self, block_s: float = 0.0):
        """Move queued requests into the pending set, blocking at most
        ``block_s`` for the first one."""
        try:
            if block_s > 0:
                self._admit(self._q.get(timeout=block_s))
            while True:
                self._admit(self._q.get_nowait())
        except _queue.Empty:
            pass

    def _admit(self, req: Request):
        now = self._clock()
        req.t_adm = now
        req.queue_ms = (now - req.t_enq) * 1e3
        self._pending.append(req)
        self._pending_rows += req.rows
        depth = self._pending_rows + self._q.qsize()
        self._depth_samples.append(depth)
        _tel.set_gauge("serve.queue_depth", depth)

    def _bucket_for(self, rows: int) -> int:
        return next(b for b in self.buckets if b >= min(rows,
                                                        self.buckets[-1]))

    def _maybe_shed(self, now: float):
        """Overload shedding: when the backlog exceeds twice
        ``max_batch`` rows, convoying everyone past the SLO serves
        nobody — fail the lowest-priority, most-expired requests with
        :class:`RequestShed` until one dispatch can clear the rest.
        Never sheds while draining on close (those are served)."""
        if self._stop.is_set() or self._pending_rows <= self._shed_rows:
            return
        victims = [r for r in self._pending
                   if r.t_deadline is not None and now > r.t_deadline]
        if not victims:
            return
        victims.sort(key=lambda r: (0 if r.priority == "batch" else 1,
                                    r.t_deadline))
        shed, rows = [], self._pending_rows
        for r in victims:
            if rows <= self.max_batch:
                break
            shed.append(r)
            rows -= r.rows
        if not shed:
            return
        shed_ids = {id(r) for r in shed}
        self._pending = [r for r in self._pending
                         if id(r) not in shed_ids]
        self._pending_rows = rows
        trc = _dtrace._TRACER   # disabled cost: this one None check
        for r in shed:
            _tel.inc("serve.shed_requests")
            _tel.inc("serve.shed.%s" % r.priority)
            with self._lock:
                self._lane[r.priority]["shed"] += 1
            if trc is not None and r.trace_ctx is not None:
                trc.emit("serve.shed", r.trace_ctx, r.t_enq, now,
                         tags={"shed": True, "priority": r.priority,
                               "request_id": r.request_id})
            r.error = RequestShed(
                "request %s (%s lane) shed under overload: deadline "
                "%.1fms expired %.1fms ago with %d rows queued"
                % (r.request_id, r.priority, r.deadline_ms or 0.0,
                   (now - r.t_deadline) * 1e3, self._pending_rows))
            self._finish(r, served=False)
            r._done.set()
        _log.warning("shed %d request(s) under overload: %s",
                     len(shed), _corr_ids(shed))

    def _decide(self, now: float) -> Optional[float]:
        """The dispatch decision over the pending set: ``None`` means
        dispatch now (``_dispatch_reason`` says why), a positive float
        is how long coalescing may continue before re-evaluating."""
        rows = self._pending_rows
        if rows >= self.max_batch:
            self._dispatch_reason = "full"
            return None
        hold0 = min(r.t_adm for r in self._pending)
        window_ms = self._ctl.wait_ms if self.adaptive else self.max_wait_ms
        window_s = window_ms / 1e3
        window_end = hold0 + window_s
        bucket = self._bucket_for(rows)
        est_s = (self._svc.estimate_ms(bucket) * self.SVC_SAFETY
                 + self.SLACK_MARGIN_MS) / 1e3
        slack_end = None
        for r in self._pending:
            if r.t_deadline is not None:
                e = r.t_deadline - est_s
                if slack_end is None or e < slack_end:
                    slack_end = e
        if slack_end is not None and now >= slack_end:
            # the earliest deadline is about to run out of slack:
            # dispatch immediately, whatever the fill looks like
            self._dispatch_reason = "deadline"
            return None
        end = window_end if slack_end is None else min(window_end,
                                                       slack_end)
        if self.adaptive:
            rate = self._arrival.rate()
            nxt = next((b for b in self.buckets if b > rows), None)
            fill_s = ((nxt - rows) / rate
                      if nxt is not None and rate > 0 else None)
            if fill_s is not None:
                # coalescing would cross the next bucket rung within
                # the remaining slack (and a bounded stretch of the
                # window, never past the controller's ceiling — the
                # total hold must stay within the wait the control
                # loop is accountable for): wait for the fill
                ext_end = hold0 + min(self.FILL_STRETCH * window_s,
                                      self._ctl.ceil_ms / 1e3)
                if slack_end is not None:
                    ext_end = min(ext_end, slack_end)
                if now + fill_s <= ext_end:
                    end = max(end, now + fill_s)
            if rows in self._rung_set and (fill_s is None
                                           or now + fill_s > end):
                # sitting exactly on a rung with the next one out of
                # reach: ship a perfectly full bucket now
                self._dispatch_reason = "rung_fill"
                return None
            if rate * max(end - now, 0.0) < 1.0:
                # light load: nothing else is plausibly arriving inside
                # the window — dispatch now instead of holding a
                # nearly-empty bucket open for nobody
                self._dispatch_reason = "idle"
                return None
        if now >= end:
            self._dispatch_reason = "window"
            return None
        return end - now

    def _pack(self, now: float) -> List[Request]:
        """Earliest-deadline-first packing: take pending requests in
        EDF order (no deadline sorts last, FIFO within ties) up to
        ``max_batch`` rows, never splitting a request. Whatever the
        urgent lane leaves free is filled by the batch lane — that
        ride-along is both the occupancy win and the
        starvation-freedom guarantee."""
        self._pending.sort(key=lambda r: (
            r.t_deadline if r.t_deadline is not None else float("inf"),
            r.t_adm))
        batch: List[Request] = []
        rest: List[Request] = []
        rows = 0
        for r in self._pending:
            if rows + r.rows <= self.max_batch:
                batch.append(r)
                rows += r.rows
            else:
                rest.append(r)
        self._pending = rest
        self._pending_rows = sum(r.rows for r in rest)
        return batch

    def step(self) -> Optional[str]:
        """One manual scheduling step (fake-clock tests drive this
        with ``autostart=False``): admit intake, shed under overload,
        evaluate the dispatch decision, dispatch at most one batch.
        Returns the dispatch reason, ``"shed"`` when shedding emptied
        the pending set, ``"wait"`` while coalescing continues, or
        ``None`` when idle."""
        self._admit_intake(0.0)
        if not self._pending:
            return None
        now = self._clock()
        self._maybe_shed(now)
        if not self._pending:
            return "shed"
        if self._decide(now) is not None:
            return "wait"
        reason = self._dispatch_reason
        self._dispatch(self._pack(now))
        return reason

    def _run(self):
        while True:
            if self._stop.is_set():
                self._admit_intake(0.0)
                if not self._pending:
                    break
                batch = self._pack(self._clock())
            else:
                self._admit_intake(0.0 if self._pending else 0.05)
                if not self._pending:
                    continue
                now = self._clock()
                self._maybe_shed(now)
                if not self._pending:
                    continue
                wait_s = self._decide(now)
                if wait_s is not None:
                    # sleep on the intake queue so a new arrival
                    # re-evaluates the decision immediately
                    self._admit_intake(min(wait_s, 0.05))
                    continue
                batch = self._pack(now)
            if not batch:
                continue
            try:
                self._dispatch(batch)
            except BaseException as e:   # noqa: BLE001 (fail the batch,
                _tel.inc("serve.errors")  # not the serving loop)
                for req in batch:
                    req.error = e
                    self._finish(req, served=False)
                    req._done.set()
                _log.exception("serve batch failed (%d requests: %s)",
                               len(batch), _corr_ids(batch))

    def _dispatch(self, batch: List[Request]):
        import jax

        if _faults.fires("drop_response"):
            # the response is lost on the wire: the work is abandoned,
            # callers see a timeout, and the router's deadline-budgeted
            # retry path has to recover the request elsewhere
            _tel.inc("serve.dropped_responses")
            _log.warning("response dropped (injected fault) for %d "
                         "request(s): %s", len(batch),
                         _corr_ids(batch))
            for req in batch:
                self._finish(req, served=False)
            return
        if _faults.fires("slow_replica"):
            time.sleep(_faults.slow_ms() / 1e3)

        t0 = self._clock()
        rows = sum(r.rows for r in batch)
        bucket = next(b for b in self.buckets if b >= rows)
        for req in batch:
            req.sched_idle_ms = (t0 - req.t_adm) * 1e3
        placed, pad = self._stager.stage([r.arrays for r in batch],
                                         bucket)
        t1 = self._clock()
        outs, post = self._infer(placed)
        results = list(post) if post else list(outs)
        jax.block_until_ready(results)   # graft: host-sync
        t2 = self._clock()
        host = [np.asarray(a) for a in results]   # graft: host-sync
        t3 = self._clock()

        h2d_ms = (t1 - t0) * 1e3
        dispatch_ms = (t2 - t1) * 1e3
        d2h_ms = (t3 - t2) * 1e3
        occupancy = rows / float(bucket)
        self._svc.observe(bucket, (t3 - t0) * 1e3)
        _tel.observe("serve.batch_occupancy", occupancy)
        _tel.inc("serve.batches")

        worst_trace = None
        trc = _dtrace._TRACER   # disabled cost: this one None check
        if trc is not None:
            worst_trace = self._emit_spans(trc, batch, t0, t1, t2, t3,
                                           rows, bucket, occupancy)

        off, worst = 0, 0.0
        for req in batch:
            req.result = [h[off:off + req.rows] for h in host]
            off += req.rows
            req.latency_ms = (t3 - req.t_enq) * 1e3
            # the exact per-request wall-time decomposition: the five
            # components sum to latency_ms by construction (pinned by
            # test); pad_waste stays an overlay, outside the sum
            req.components = {
                "queue_ms": req.queue_ms,
                "sched_idle_ms": req.sched_idle_ms,
                "h2d_ms": h2d_ms, "dispatch_ms": dispatch_ms,
                "d2h_ms": d2h_ms}
            for name, v in req.components.items():
                _tel.observe("serve." + name, v)
            _tel.observe("serve.pad_waste_ms",
                         dispatch_ms * (1 - occupancy))
            _tel.observe("serve.request_ms", req.latency_ms)
            worst = max(worst, req.latency_ms)
            with self._lock:
                self._lane[req.priority]["served"] += 1
            self._finish(req, served=True)
            req._done.set()
        _tel.set_gauge("serve.in_flight", self.in_flight())
        with self._lock:
            self._served += rows
            self._batches += 1
            self._occ_sum += occupancy
            if self.slo_ms:
                self._slo_breaches += sum(
                    1 for r in batch if r.latency_ms > self.slo_ms)
            for r in batch:
                self._lat_hist.observe(r.latency_ms)
            self._lat.extend(r.latency_ms for r in batch)
            if len(self._lat) > self._lat_cap:
                del self._lat[:len(self._lat) - self._lat_cap]
            # a bucket's first dispatch carries its one-time compile:
            # real latency for the SLO probe above, but poison as
            # controller feedback (one 300 ms trace would pin the p99
            # and collapse the wait long after steady state resumed)
            if bucket in self._warmed:
                self._recent.extend((t3, r.latency_ms) for r in batch)
            else:
                self._warmed.add(bucket)
        # close the adaptive loop off the sliding-window p99, and leave
        # an observable trajectory behind
        depth = self._pending_rows + self._q.qsize()
        if self.adaptive:
            # control on the RECENT p99, not the full SLO window: the
            # probe's long memory is right for alerting but a controller
            # fed stale samples re-collapses on a transient long after
            # it healed
            self._ctl.update(self.recent_quantile(0.99))
        _tel.set_gauge("serve.adaptive_wait_ms", self._ctl.wait_ms)
        _tel.set_gauge("serve.arrival_rate", self._arrival.rate())
        _tel.set_gauge("serve.queue_depth", depth)
        self._traj.append({
            "t_s": round(t3 - self._t0, 4),
            "wait_ms": round(self._ctl.wait_ms
                             if self.adaptive else self.max_wait_ms, 3),
            "queue_depth": depth, "rows": rows, "bucket": bucket,
            "occupancy": round(occupancy, 4),
            "reason": self._dispatch_reason,
            "arrival_rps": round(self._arrival.rate(), 2)})
        # the serving step record: the SlowRequestDetector keys off
        # request_ms/slo_ms, and the /healthz anomaly count moves
        extra = {
            "request_ms": round(worst, 3),
            "slo_ms": self.slo_ms,
            "serve_rows": rows, "serve_bucket": bucket,
            "adaptive_wait_ms": round(self._ctl.wait_ms, 3),
            "queue_depth": depth}
        if worst_trace is not None:
            extra["worst_trace_id"] = worst_trace
        _tracing.record_step((t3 - t0) * 1e3, extra=extra)

    def _emit_spans(self, trc, batch, t0, t1, t2, t3, rows, bucket,
                    occupancy):
        """Traced requests' decomposition spans: under each request's
        propagated context, a ``serve.request`` span covering enqueue
        to completion with the five exact components as children
        (their durations sum to request_ms by construction), every
        dispatch span cross-linked (``batch=<id>``) to one shared
        ``serve.batch_dispatch`` span tagged with the bucket,
        occupancy and whether this dispatch carried the bucket's
        one-time compile (the xprof registry's count moves in step).
        Returns the worst traced request's trace id (or None)."""
        batch_sid = None
        worst_ms, worst_trace = -1.0, None
        # _warmed gains the bucket only after this dispatch; compiles
        # is the FusedInfer/xprof-registry counter when present
        compiled = bucket not in self._warmed
        for req in batch:
            ctx = req.trace_ctx
            if ctx is None:
                continue
            req_ms = (t3 - req.t_enq) * 1e3
            breach = bool(self.slo_ms) and req_ms > self.slo_ms
            sid = trc.emit(
                "serve.request", ctx, req.t_enq, t3,
                tags={"request_id": req.request_id,
                      "priority": req.priority, "rows": req.rows,
                      "slo_breach": breach})
            if batch_sid is None:
                # one shared batch-dispatch span (first traced
                # request's tree hosts it; the rest cross-link)
                batch_sid = trc.emit(
                    "serve.batch_dispatch", (ctx["t"], sid), t1, t2,
                    tags={"bucket": bucket, "rows": rows,
                          "occupancy": round(occupancy, 4),
                          "compile": compiled,
                          "compiles": getattr(self._infer, "compiles",
                                              None),
                          "requests": len(batch)})
            parent = (ctx["t"], sid)
            trc.emit("serve.queue", parent, req.t_enq, req.t_adm)
            trc.emit("serve.sched_idle", parent, req.t_adm, t0)
            trc.emit("serve.h2d", parent, t0, t1,
                     tags={"pad_rows": bucket - rows,
                           "fastpath": self._stager.last_fastpath,
                           "h2d_bytes": self._stager.last_bytes})
            trc.emit("serve.dispatch", parent, t1, t2,
                     tags={"batch": batch_sid, "bucket": bucket,
                           "occupancy": round(occupancy, 4),
                           "compile": compiled})
            trc.emit("serve.d2h", parent, t2, t3)
            if breach:
                self._last_breach_trace = ctx["t"]
            if req_ms > worst_ms:
                worst_ms, worst_trace = req_ms, ctx["t"]
        return worst_trace

    # -- SLO / stats -------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._lat)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    def recent_quantile(self, q: float,
                        window_s: float = 0.5) -> Optional[float]:
        """Quantile over recently served requests — the adaptive
        controller's feedback signal (the full ``slo_window`` stays the
        alerting probe). Bounded both ways: at most the last 64 samples
        AND only those finished within ``window_s``, so a latency spike
        stops steering the controller once it is ``window_s`` old even
        if traffic is too slow to displace it. ``None`` (nothing recent)
        reads as full headroom."""
        cutoff = self._clock() - float(window_s)
        with self._lock:
            lat = sorted(ms for (t, ms) in self._recent if t >= cutoff)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    def slo_probe(self) -> Optional[dict]:
        """Health probe for /healthz: failing detail once the sliding
        p99 exceeds the SLO, None while healthy (or SLO unset). The
        failing payload carries the controller state so the operator
        sees where the adaptive wait was when the tail broke."""
        if not self.slo_ms:
            return None
        p99 = self.latency_quantile(0.99)
        if p99 is not None and p99 > self.slo_ms:
            detail = {"p99_ms": round(p99, 3), "slo_ms": self.slo_ms}
            detail.update(self.controller_state())
            if self._last_breach_trace is not None:
                # a concrete reproducible trace for the degradation:
                # `trace_report --view waterfall <id>` renders it
                detail["worst_trace_id"] = self._last_breach_trace
            return detail
        return None

    def controller_state(self) -> dict:
        """The adaptive control plane, as one JSON-able dict (merged
        into /healthz and the bench record)."""
        return {"adaptive": self.adaptive,
                "adaptive_wait_ms": round(
                    self._ctl.wait_ms if self.adaptive
                    else self.max_wait_ms, 3),
                "arrival_rate_rps": round(self._arrival.rate(), 2),
                "queue_depth": self._pending_rows + self._q.qsize()}

    def occupancy_snapshot(self) -> dict:
        """Monotone counters for per-tier occupancy deltas in the
        bench (mean occupancy between two snapshots =
        ``Δocc_sum / Δbatches``)."""
        with self._lock:
            return {"batches": self._batches, "occ_sum": self._occ_sum,
                    "served": self._served}

    def metrics_payload(self) -> dict:
        """This scheduler's metrics as a flat ``name -> export`` dict —
        the /metrics-equivalent payload an InProc fleet scrape reads
        directly (no socket). Counters export ints, gauges floats, the
        latency histogram a bucketed summary dict carrying its exact
        sample ring so the federator's fleet percentiles stay exact at
        smoke scale. Names match the process-global telemetry series so
        a subprocess replica's real /metrics merges with these."""
        with self._lock:
            served = self._served
            batches = self._batches
            occ_sum = self._occ_sum
            breaches = self._slo_breaches
        return {
            "serve.requests_served": served,
            "serve.batches": batches,
            "serve.slo_breaches": breaches,
            "serve.occupancy_sum": float(occ_sum),
            "serve.in_flight": float(self.in_flight()),
            "serve.queue_depth": float(self._pending_rows +
                                       self._q.qsize()),
            "serve.request_ms": self._lat_hist.export(include_sample=True),
        }

    def drain_depth_samples(self) -> List[int]:
        """Pop and return the queue-depth samples recorded since the
        last drain (the bench computes per-tier percentiles from
        these)."""
        out: List[int] = []
        while True:
            try:
                out.append(self._depth_samples.popleft())
            except IndexError:
                return out

    def wait_trajectory(self) -> List[dict]:
        """The adaptive-wait trajectory: one sample per dispatched
        batch (time, wait, queue depth, occupancy, reason)."""
        return list(self._traj)

    def lane_stats(self) -> dict:
        with self._lock:
            return {lane: dict(v) for lane, v in self._lane.items()}

    def stats(self) -> dict:
        with self._lock:
            batches = self._batches
            served = self._served
            occ = self._occ_sum / batches if batches else 0.0
            lanes = {lane: dict(v) for lane, v in self._lane.items()}
        out = {"requests_served": served, "batches": batches,
               "mean_occupancy": round(occ, 4), "lanes": lanes}
        out.update(self.controller_state())
        depth = list(self._depth_samples)
        if depth:
            depth.sort()
            out["queue_depth_p50"] = depth[len(depth) // 2]
            out["queue_depth_p99"] = depth[min(len(depth) - 1,
                                               int(0.99 * len(depth)))]
            out["queue_depth_max"] = depth[-1]
        for name, q in (("p50_ms", 0.50), ("p99_ms", 0.99),
                        ("p999_ms", 0.999)):
            v = self.latency_quantile(q)
            if v is not None:
                out[name] = round(v, 3)
        return out

    # -- shutdown ----------------------------------------------------------
    def close(self, timeout: float = 10.0):
        """Graceful shutdown: stop intake, drain every queued request
        (served, not dropped), join the worker. Idempotent and safe to
        race from several threads (the fleet's monitor, a drain, and a
        context-manager exit may all call it)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                _log.warning("serve batcher still alive after %.1fs "
                             "join; leaking the (daemon) thread",
                             timeout)
        # a dispatch error could strand late submissions; fail them
        # rather than hang their callers
        leftovers = list(self._pending)
        self._pending = []
        self._pending_rows = 0
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except _queue.Empty:
                break
        if leftovers:
            _log.warning("failing %d queued request(s) at close: %s",
                         len(leftovers), _corr_ids(leftovers))
        for req in leftovers:
            req.error = MXNetError("BatchScheduler closed before the "
                                   "request was served")
            self._finish(req, served=False)
            # per-request completion event, not the worker's stop
            # signal — waking the caller after the join is the point
            req._done.set()  # graft: lifecycle-ok

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InferenceServer:
    """A bound Module served behind a continuous batcher.

    Builds the compiled-once :class:`~mxnet_tpu.fused_step.FusedInfer`
    from the module's executor (params packed + replicated across the
    ``dp`` mesh when the module was bound over multiple devices;
    request batches sharded along ``dp``), starts the metrics/health
    server per ``MXNET_TPU_SERVE_PORT``, and registers the SLO health
    probe. ``top_k=0`` returns raw forward outputs, ``top_k=1`` the
    on-device argmax, ``top_k>1`` top-k (values, indices) — all
    computed inside the same single dispatch.
    """

    def __init__(self, module, top_k: int = 0,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 slo_ms: Optional[float] = None,
                 port: Optional[object] = None,
                 adaptive: Optional[bool] = None,
                 default_deadline_ms: Optional[float] = None,
                 batch_deadline_ms: Optional[float] = None,
                 tp: Optional[int] = None):
        from .fused_step import make_fused_infer
        from .parallel.sharding import batch_shard_extent

        if not module.binded or not module.params_initialized:
            raise MXNetError("InferenceServer needs a bound, "
                             "param-initialized module")
        group = module._exec_group
        ex = group.executor
        mesh = getattr(group, "_mesh", None)
        if tp is None:
            tp = int(_env.get("MXNET_TPU_SERVE_TP") or 0)
        self.tp = tp = max(1, int(tp))
        if tp > 1:
            mesh = self._tp_mesh(group, mesh, tp)
        self._module = module
        self._mesh = mesh
        # rungs round to the BATCH-sharding extent, not the device
        # count: on a (dp, tp) mesh only dp splits rows
        dp = batch_shard_extent(mesh) if mesh is not None else 1
        self.dp = dp
        self._fused = make_fused_infer(ex, module._data_names,
                                       top_k=top_k, mesh=mesh)
        self._top_k = top_k
        self._data_shapes = [d.shape for d in group.data_shapes]
        self.scheduler = BatchScheduler(
            self._fused, self._data_shapes, max_batch=max_batch,
            max_wait_ms=max_wait_ms, buckets=buckets, slo_ms=slo_ms,
            dp=dp, place=self._fused.place_batch, adaptive=adaptive,
            default_deadline_ms=default_deadline_ms,
            batch_deadline_ms=batch_deadline_ms)
        self._metrics = None
        self._own_metrics = False
        if port is None:
            port = _env.get("MXNET_TPU_SERVE_PORT")
        if port != "" and port is not None:
            self._metrics = _tracing.MetricsServer(int(port))
            self._own_metrics = True
        elif _tracing.metrics_server() is not None:
            self._metrics = _tracing.metrics_server()
        self._probe_name = "serve_slo:%d" % id(self)
        _tracing.register_health_probe(self._probe_name,
                                       self.scheduler.slo_probe)
        # replica identity on /healthz: the router and a human curl
        # read the same in-flight/served signal (rank, pid, uptime are
        # already in the base payload)
        self._info_name = "serve:%d" % id(self)
        _tracing.register_health_info(self._info_name, self.health_info)
        self._closed = False
        self._close_lock = threading.Lock()
        _log.info("serving: buckets=%s max_wait_ms=%s adaptive=%s dp=%d "
                  "tp=%d slo_ms=%s%s",
                  self.scheduler.buckets, self.scheduler.max_wait_ms,
                  self.scheduler.adaptive, dp, tp,
                  self.scheduler.slo_ms or "off",
                  " metrics on :%d" % self._metrics.port
                  if self._metrics else "")

    @staticmethod
    def _tp_mesh(group, mesh, tp: int):
        """Factor the module's devices into the ``(dp, tp)`` serving
        mesh: the same devices the group bound, reshaped so ``tp`` of
        them split the model and the rest replicate/shard the batch.
        Refuses (naming the knob) when ``tp`` does not divide the
        device count — silently dropping devices would serve a
        different capacity than the operator asked for."""
        import jax

        from .parallel.sharding import make_mesh

        devices = (list(mesh.devices.flat) if mesh is not None
                   else jax.devices()[:1])
        n = len(devices)
        if n % tp != 0:
            raise MXNetError(
                "MXNET_TPU_SERVE_TP=%d does not divide the %d-device "
                "group; pick a tp that factors the device count"
                % (tp, n))
        return make_mesh({"dp": n // tp, "tp": tp}, devices=devices)

    # -- serving API -------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._metrics.port if self._metrics is not None else None

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self.scheduler.buckets

    @property
    def compiles(self) -> int:
        """Executables built so far (bounded by len(buckets))."""
        return self._fused.compiles

    @property
    def closed(self) -> bool:
        return self._closed

    def start(self):
        """The server starts serving at construction; an explicit
        second start is the double-start bug this guard exists for."""
        self.scheduler.start()

    def submit(self, arrays, request_id: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               priority: Optional[str] = None,
               trace_ctx: Optional[dict] = None) -> Request:
        return self.scheduler.submit(arrays, request_id=request_id,
                                     deadline_ms=deadline_ms,
                                     priority=priority,
                                     trace_ctx=trace_ctx)

    def infer(self, arrays, timeout: Optional[float] = 60.0,
              deadline_ms: Optional[float] = None,
              priority: Optional[str] = None):
        return self.scheduler.infer(arrays, timeout,
                                    deadline_ms=deadline_ms,
                                    priority=priority)

    def refresh_params(self, host_params=None, digests=None):
        """Repack after a weight update — full re-pack after
        ``module.set_params`` (no arguments), or the delta-aware
        checkpoint-streamed path when ``host_params`` (name -> host
        ndarray) and optionally ``digests`` (the snapshot manifest's
        per-param sha256) are given: only params whose digest differs
        from the resident pack transfer
        (:meth:`~mxnet_tpu.fused_step.FusedInfer.refresh_params`).

        Either way the serving executable is first re-validated
        against the module's CURRENT executor and mesh factoring — a
        re-bind across meshes rebuilds the FusedInfer (and re-points
        the scheduler + stager at it) instead of serving a stale
        executable compiled for the old placement.

        Under an injected ``torn_swap`` fault the repack becomes
        non-atomic (half the pack, a sleep, the rest), so a dispatch
        inside the window would mix param versions — the fleet's
        drain-then-swap rolling update must mask that window, and the
        chaos tests prove it does."""
        self._ensure_executable()
        kw = {}
        if host_params is not None:
            kw = {"host_params": host_params, "digests": digests}
        if _faults.fires("torn_swap"):
            self._fused.refresh_params(
                torn_ms=max(_faults.slow_ms(), 1.0), **kw)
        else:
            self._fused.refresh_params(**kw)

    def refresh_from_snapshot(self, payload: dict):
        """Delta-refresh from a :func:`mxnet_tpu.checkpoint.snapshot`
        payload (the serve-while-training rollout path: training saves,
        the fleet ships the directory, each drained replica streams the
        changed params only)."""
        self.refresh_params(host_params=payload.get("params") or {},
                            digests=payload.get("param_digests"))

    def _ensure_executable(self):
        """Rebuild the FusedInfer when the module was re-bound onto a
        different executor or mesh factoring since construction. The
        scheduler's infer fn and the stager's place fn are re-pointed
        atomically under the scheduler lock — in-flight dispatches
        finish on the old executable, every later batch rides the new
        one."""
        group = self._module._exec_group
        mesh = self._mesh
        if self.tp <= 1:
            mesh = getattr(group, "_mesh", None)
        elif self._fused.stale_for(group.executor, self._mesh):
            # re-bound under tp: refactor the new device set
            mesh = self._tp_mesh(group, getattr(group, "_mesh", None),
                                 self.tp)
        if not self._fused.stale_for(group.executor, mesh):
            return
        from .fused_step import make_fused_infer

        self._mesh = mesh
        self._fused = make_fused_infer(group.executor,
                                       self._module._data_names,
                                       top_k=self._top_k, mesh=mesh)
        self._data_shapes = [d.shape for d in group.data_shapes]
        self.scheduler.rebind_infer(self._fused,
                                    self._fused.place_batch)
        _tel.inc("serve.executable_rebuilds")

    def health_info(self) -> dict:
        """Identity payload merged into /healthz by the tracing tier —
        replica identity plus the adaptive controller state, so the
        router and a human curl see where the scheduler sits."""
        info = {"in_flight": self.scheduler.in_flight(),
                "requests_served": self.scheduler.occupancy_snapshot()
                                       .get("served", 0)}
        info.update(self.scheduler.controller_state())
        return info

    def metrics_payload(self) -> dict:
        """Scrape payload for fleet federation (obswatch): the
        scheduler's per-replica metric series plus compile count."""
        out = self.scheduler.metrics_payload()
        out["serve.compiles"] = self.compiles
        return out

    def stats(self) -> dict:
        out = self.scheduler.stats()
        out["compiles"] = self.compiles
        out["buckets"] = list(self.buckets)
        out["dp"] = self.dp
        out["tp"] = self.tp
        out["in_flight"] = self.scheduler.in_flight()
        return out

    # -- shutdown ----------------------------------------------------------
    def close(self):
        """Idempotent and race-safe: the first caller wins, everyone
        else returns immediately (the fleet may close a replica from
        its monitor thread while a drain path does the same)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        _tracing.unregister_health_probe(self._probe_name)
        _tracing.unregister_health_info(self._info_name)
        self.scheduler.close()
        if self._own_metrics and self._metrics is not None:
            self._metrics.stop()
        self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
