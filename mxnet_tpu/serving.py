"""Continuous-batching inference serving tier.

The reference framework stopped at a predict-only C ABI (one
synchronous forward per caller); this module is the throughput/latency
path the ROADMAP's "millions of users" north star actually needs. It
composes pieces that already exist — the single-dispatch
:class:`~mxnet_tpu.fused_step.FusedInfer` executable, the ``dp`` device
mesh + NamedSharding batch placement from the executor group, the xprof
compile registry and the Prometheus :class:`~mxnet_tpu.tracing.MetricsServer`
— into three layers:

* :class:`BatchScheduler` — a continuous batcher: in-flight requests
  coalesce up to ``max_batch`` or ``max_wait_ms`` (whichever first),
  and every dispatched batch is padded up to a small ladder of bucket
  sizes (default powers of two), so mixed request rates compile at most
  ``len(buckets)`` executables EVER and steady state runs retrace-free
  at exactly one XLA dispatch per served batch.
* :class:`InferenceServer` — wires a bound Module to a FusedInfer
  (params packed once, replicated across the mesh; request batches
  sharded along ``dp``), owns the scheduler, exports `/metrics` +
  `/healthz`, and registers the SLO health probe: when the sliding-
  window p99 exceeds ``MXNET_TPU_SERVE_SLO_MS``, `/healthz` flips to
  ``degraded`` (HTTP 503) and a ``slow_request`` anomaly fires through
  the step-trace detectors.
* latency decomposition — every request's wall time splits into queue
  wait / H2D+pad / dispatch / D2H histograms (``serve.queue_ms``,
  ``serve.h2d_ms``, ``serve.pad_waste_ms``, ``serve.dispatch_ms``,
  ``serve.d2h_ms``, ``serve.request_ms``) with p50/p99 exported through
  the metrics server and summarized by ``trace_report --view serve``.

Shutdown contract: ``close()`` stops intake, DRAINS every queued
request (each gets a result or an error — nothing hangs a caller), and
joins the worker thread; the tests' thread/process leak gate holds.

``bench.py serve`` drives this with an open-loop Poisson load sweep and
writes ``SERVE_bench.json`` (requests/sec, goodput at SLO, p50/p99/p999
latency, mean batch occupancy).
"""
from __future__ import annotations

import collections
import logging
import queue as _queue
import threading
import time
import uuid
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import env as _env
from . import faults as _faults
from . import telemetry as _tel
from . import tracing as _tracing
from .base import MXNetError
from .io_pipeline import RequestStager

__all__ = ["bucket_ladder", "Request", "BatchScheduler",
           "InferenceServer"]

_log = logging.getLogger(__name__)


def bucket_ladder(max_batch: int, dp: int = 1,
                  spec: Optional[str] = None) -> Tuple[int, ...]:
    """The padded batch-size ladder: every dispatched batch rounds up
    to the next rung, so the serving path compiles at most
    ``len(ladder)`` executables total. Default rungs are powers of two
    from ``dp`` up to ``max_batch``; an explicit ``spec`` (or
    ``MXNET_TPU_SERVE_BUCKETS``) is a comma list. Under a ``dp`` mesh
    every rung is rounded up to a multiple of ``dp`` so the batch axis
    always shards evenly."""
    dp = max(1, int(dp))
    if spec is None:
        spec = _env.get("MXNET_TPU_SERVE_BUCKETS")
    if spec:
        rungs = [int(s) for s in str(spec).split(",") if s.strip()]
    else:
        rungs, b = [], 1
        while b < max_batch:
            rungs.append(b)
            b *= 2
        rungs.append(max_batch)
    ladder = sorted({max(dp, -(-r // dp) * dp) for r in rungs})
    if any(r <= 0 for r in ladder) or not ladder:
        raise MXNetError("invalid bucket ladder %r" % (ladder,))
    if ladder[-1] < max_batch:
        ladder.append(-(-max_batch // dp) * dp)
    return tuple(ladder)


class Request:
    """One in-flight inference request: the payload arrays (one per
    data name, leading axis = rows, normally 1) plus the completion
    event the scheduler signals once results (or an error) land.

    Every request carries a stable ``request_id`` (caller-provided or
    a fresh uuid): a hedged or retried duplicate re-submitted with the
    same id is deduped at the scheduler instead of dispatched twice —
    safe because the ``FusedInfer`` dispatch is idempotent (nothing
    donated, no state mutated)."""

    __slots__ = ("arrays", "rows", "t_enq", "_done", "result", "error",
                 "queue_ms", "latency_ms", "request_id")

    def __init__(self, arrays: Sequence[np.ndarray],
                 request_id: Optional[str] = None):
        self.arrays = [np.asarray(a) for a in arrays]
        self.rows = int(self.arrays[0].shape[0])
        self.t_enq = time.perf_counter()
        self._done = threading.Event()
        self.result: Optional[List[np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.queue_ms = 0.0
        self.latency_ms = 0.0
        self.request_id = request_id or uuid.uuid4().hex

    def get(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until the scheduler served this request; returns the
        per-row result arrays (post-processing outputs when the server
        was built with ``top_k``, else the raw forward outputs)."""
        if not self._done.wait(timeout):
            raise MXNetError("inference request timed out after %ss"
                             % timeout)
        if self.error is not None:
            raise self.error
        return self.result

    def done(self) -> bool:
        return self._done.is_set()


class BatchScheduler:
    """Continuous batcher in front of a compiled-once infer callable.

    ``infer_fn(placed_arrays) -> (outs, post)`` is dispatched once per
    coalesced batch (a :class:`~mxnet_tpu.fused_step.FusedInfer`); the
    scheduler owns request coalescing, the bucket ladder, padding (via
    :class:`~mxnet_tpu.io_pipeline.RequestStager`), per-request result
    slicing, the latency decomposition and the SLO window. One daemon
    worker thread ("mxtpu-serve-batcher") runs the loop; ``close()``
    joins it after draining the queue.
    """

    def __init__(self, infer_fn, data_shapes: Sequence[tuple],
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 slo_ms: Optional[float] = None,
                 dp: int = 1, place=None, slo_window: int = 512):
        self._infer = infer_fn
        self._data_shapes = [tuple(s) for s in data_shapes]
        dp = max(1, int(dp))
        if max_batch is None:
            max_batch = _env.get("MXNET_TPU_SERVE_MAX_BATCH")
        max_batch = max(dp, -(-int(max_batch) // dp) * dp)
        self.max_batch = max_batch
        self.max_wait_ms = float(
            _env.get("MXNET_TPU_SERVE_MAX_WAIT_MS")
            if max_wait_ms is None else max_wait_ms)
        if buckets is None:
            self.buckets = bucket_ladder(max_batch, dp=dp)
        else:
            self.buckets = bucket_ladder(max_batch, dp=dp,
                                         spec=",".join(map(str, buckets)))
        self.slo_ms = float(_env.get("MXNET_TPU_SERVE_SLO_MS")
                            if slo_ms is None else slo_ms)
        self._stager = RequestStager(place=place)
        self._q: _queue.Queue = _queue.Queue()
        self._carry: Optional[Request] = None
        self._stop = threading.Event()
        self._closed = False
        self._started = False
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._lat_cap = int(slo_window)
        self._served = 0
        self._batches = 0
        self._occ_sum = 0.0
        self._in_flight = 0
        # retry-safety: request-id -> Request. In-flight dedup is always
        # safe (same object); completed-result reuse additionally needs
        # the infer fn tagged idempotent (FusedInfer is: nothing
        # donated, no state mutated).
        self._idempotent = bool(getattr(infer_fn, "idempotent", False))
        self._inflight_ids: dict = {}
        self._done_ids: collections.OrderedDict = collections.OrderedDict()
        self._done_cap = 1024
        self._worker: Optional[threading.Thread] = None
        self.start()

    def start(self):
        """Start the worker loop (called by ``__init__``). A second
        call is a programming error — the double-start guard keeps two
        batcher threads from racing on one queue."""
        with self._lock:
            if self._closed:
                raise MXNetError("BatchScheduler is closed; build a "
                                 "new one instead of restarting it")
            if self._started:
                raise MXNetError("BatchScheduler already started "
                                 "(double start)")
            self._started = True
        self._worker = threading.Thread(target=self._run,
                                        name="mxtpu-serve-batcher",
                                        daemon=True)
        self._worker.start()

    # -- intake ------------------------------------------------------------
    def submit(self, arrays: Sequence[np.ndarray],
               request_id: Optional[str] = None) -> Request:
        """Enqueue one request (arrays follow the server's data names;
        leading axis = rows). Returns immediately; block on
        ``Request.get()``. Re-submitting a ``request_id`` that is
        already in flight (or recently served, when the infer fn is
        idempotent) returns the original request instead of dispatching
        the work twice and counts ``serve.duplicate_requests``."""
        req = Request(arrays, request_id)
        if len(req.arrays) != len(self._data_shapes):
            raise MXNetError("expected %d input arrays, got %d"
                             % (len(self._data_shapes), len(req.arrays)))
        for a, shape in zip(req.arrays, self._data_shapes):
            if tuple(a.shape[1:]) != tuple(shape[1:]):
                raise MXNetError(
                    "request row shape %r does not match the served "
                    "model's %r (batch ladder only pads the batch "
                    "axis; other dims would retrace)"
                    % (tuple(a.shape[1:]), tuple(shape[1:])))
        if req.rows > self.max_batch:
            raise MXNetError("request of %d rows exceeds max_batch=%d"
                             % (req.rows, self.max_batch))
        if self._closed:
            raise MXNetError("BatchScheduler is closed")
        with self._lock:
            dup = self._inflight_ids.get(req.request_id)
            if dup is None and self._idempotent:
                dup = self._done_ids.get(req.request_id)
            if dup is not None:
                _tel.inc("serve.duplicate_requests")
                return dup
            self._inflight_ids[req.request_id] = req
            self._in_flight += 1
        _tel.inc("serve.requests")
        _tel.set_gauge("serve.in_flight", self.in_flight())
        self._q.put(req)
        return req

    def in_flight(self) -> int:
        """Requests accepted but not yet completed (the /healthz
        identity payload reads this)."""
        with self._lock:
            return self._in_flight

    def _finish(self, req: Request, served: bool):
        """Completion bookkeeping: retire the request id (into the
        dedup cache when served and the infer fn is idempotent) and
        drop it from the in-flight count."""
        with self._lock:
            if self._inflight_ids.pop(req.request_id, None) is not None:
                self._in_flight -= 1
            if served and self._idempotent:
                self._done_ids[req.request_id] = req
                while len(self._done_ids) > self._done_cap:
                    self._done_ids.popitem(last=False)

    def infer(self, arrays: Sequence[np.ndarray],
              timeout: Optional[float] = 60.0) -> List[np.ndarray]:
        """Synchronous convenience: submit + wait."""
        return self.submit(arrays).get(timeout)

    # -- scheduling loop ---------------------------------------------------
    def _gather(self) -> Optional[List[Request]]:
        """Block for the first request, then hold the batch open for
        more arrivals until max_batch or max_wait_ms. After close() the
        wait is skipped: drain whatever is already queued."""
        first = self._carry
        self._carry = None
        while first is None:
            try:
                first = self._q.get(timeout=0.1)
            except _queue.Empty:
                if self._stop.is_set():
                    return None
        batch, rows = [first], first.rows
        deadline = time.perf_counter() + self.max_wait_ms / 1e3
        while rows < self.max_batch:
            wait = deadline - time.perf_counter()
            if self._stop.is_set():
                wait = 0.0
            try:
                req = (self._q.get_nowait() if wait <= 0
                       else self._q.get(timeout=wait))
            except _queue.Empty:
                break
            if rows + req.rows > self.max_batch:
                self._carry = req   # keeps FIFO order for the next batch
                break
            batch.append(req)
            rows += req.rows
        return batch

    def _run(self):
        while True:
            batch = self._gather()
            if batch is None:
                break
            try:
                self._dispatch(batch)
            except BaseException as e:   # noqa: BLE001 (fail the batch,
                _tel.inc("serve.errors")  # not the serving loop)
                for req in batch:
                    req.error = e
                    self._finish(req, served=False)
                    req._done.set()
                _log.exception("serve batch failed (%d requests)",
                               len(batch))

    def _dispatch(self, batch: List[Request]):
        import jax

        if _faults.fires("drop_response"):
            # the response is lost on the wire: the work is abandoned,
            # callers see a timeout, and the router's deadline-budgeted
            # retry path has to recover the request elsewhere
            _tel.inc("serve.dropped_responses")
            for req in batch:
                self._finish(req, served=False)
            return
        if _faults.fires("slow_replica"):
            time.sleep(_faults.slow_ms() / 1e3)

        t0 = time.perf_counter()
        rows = sum(r.rows for r in batch)
        bucket = next(b for b in self.buckets if b >= rows)
        for req in batch:
            req.queue_ms = (t0 - req.t_enq) * 1e3
            _tel.observe("serve.queue_ms", req.queue_ms)
        placed, pad = self._stager.stage([r.arrays for r in batch],
                                         bucket)
        t1 = time.perf_counter()
        outs, post = self._infer(placed)
        results = list(post) if post else list(outs)
        jax.block_until_ready(results)   # graft: host-sync
        t2 = time.perf_counter()
        host = [np.asarray(a) for a in results]   # graft: host-sync
        t3 = time.perf_counter()

        dispatch_ms = (t2 - t1) * 1e3
        occupancy = rows / float(bucket)
        _tel.observe("serve.dispatch_ms", dispatch_ms)
        _tel.observe("serve.pad_waste_ms", dispatch_ms * (1 - occupancy))
        _tel.observe("serve.d2h_ms", (t3 - t2) * 1e3)
        _tel.observe("serve.batch_occupancy", occupancy)
        _tel.inc("serve.batches")

        off, worst = 0, 0.0
        for req in batch:
            req.result = [h[off:off + req.rows] for h in host]
            off += req.rows
            req.latency_ms = (t3 - req.t_enq) * 1e3
            worst = max(worst, req.latency_ms)
            _tel.observe("serve.request_ms", req.latency_ms)
            self._finish(req, served=True)
            req._done.set()
        _tel.set_gauge("serve.in_flight", self.in_flight())
        with self._lock:
            self._served += rows
            self._batches += 1
            self._occ_sum += occupancy
            self._lat.extend(r.latency_ms for r in batch)
            if len(self._lat) > self._lat_cap:
                del self._lat[:len(self._lat) - self._lat_cap]
        # the serving step record: the SlowRequestDetector keys off
        # request_ms/slo_ms, and the /healthz anomaly count moves
        _tracing.record_step((t3 - t0) * 1e3, extra={
            "request_ms": round(worst, 3),
            "slo_ms": self.slo_ms,
            "serve_rows": rows, "serve_bucket": bucket})

    # -- SLO / stats -------------------------------------------------------
    def latency_quantile(self, q: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self._lat)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    def slo_probe(self) -> Optional[dict]:
        """Health probe for /healthz: failing detail once the sliding
        p99 exceeds the SLO, None while healthy (or SLO unset)."""
        if not self.slo_ms:
            return None
        p99 = self.latency_quantile(0.99)
        if p99 is not None and p99 > self.slo_ms:
            return {"p99_ms": round(p99, 3), "slo_ms": self.slo_ms}
        return None

    def stats(self) -> dict:
        with self._lock:
            batches = self._batches
            served = self._served
            occ = self._occ_sum / batches if batches else 0.0
        out = {"requests_served": served, "batches": batches,
               "mean_occupancy": round(occ, 4)}
        for name, q in (("p50_ms", 0.50), ("p99_ms", 0.99),
                        ("p999_ms", 0.999)):
            v = self.latency_quantile(q)
            if v is not None:
                out[name] = round(v, 3)
        return out

    # -- shutdown ----------------------------------------------------------
    def close(self, timeout: float = 10.0):
        """Graceful shutdown: stop intake, drain every queued request
        (served, not dropped), join the worker. Idempotent and safe to
        race from several threads (the fleet's monitor, a drain, and a
        context-manager exit may all call it)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                _log.warning("serve batcher still alive after %.1fs "
                             "join; leaking the (daemon) thread",
                             timeout)
        # a dispatch error could strand late submissions; fail them
        # rather than hang their callers
        leftovers = [] if self._carry is None else [self._carry]
        self._carry = None
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except _queue.Empty:
                break
        for req in leftovers:
            req.error = MXNetError("BatchScheduler closed before the "
                                   "request was served")
            self._finish(req, served=False)
            # per-request completion event, not the worker's stop
            # signal — waking the caller after the join is the point
            req._done.set()  # graft: lifecycle-ok

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InferenceServer:
    """A bound Module served behind a continuous batcher.

    Builds the compiled-once :class:`~mxnet_tpu.fused_step.FusedInfer`
    from the module's executor (params packed + replicated across the
    ``dp`` mesh when the module was bound over multiple devices;
    request batches sharded along ``dp``), starts the metrics/health
    server per ``MXNET_TPU_SERVE_PORT``, and registers the SLO health
    probe. ``top_k=0`` returns raw forward outputs, ``top_k=1`` the
    on-device argmax, ``top_k>1`` top-k (values, indices) — all
    computed inside the same single dispatch.
    """

    def __init__(self, module, top_k: int = 0,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 buckets: Optional[Sequence[int]] = None,
                 slo_ms: Optional[float] = None,
                 port: Optional[object] = None):
        from .fused_step import make_fused_infer

        if not module.binded or not module.params_initialized:
            raise MXNetError("InferenceServer needs a bound, "
                             "param-initialized module")
        group = module._exec_group
        ex = group.executor
        mesh = getattr(group, "_mesh", None)
        dp = int(mesh.size) if mesh is not None else 1
        self.dp = dp
        self._fused = make_fused_infer(ex, module._data_names,
                                       top_k=top_k, mesh=mesh)
        self._data_shapes = [d.shape for d in group.data_shapes]
        self.scheduler = BatchScheduler(
            self._fused, self._data_shapes, max_batch=max_batch,
            max_wait_ms=max_wait_ms, buckets=buckets, slo_ms=slo_ms,
            dp=dp, place=self._fused.place_batch)
        self._metrics = None
        self._own_metrics = False
        if port is None:
            port = _env.get("MXNET_TPU_SERVE_PORT")
        if port != "" and port is not None:
            self._metrics = _tracing.MetricsServer(int(port))
            self._own_metrics = True
        elif _tracing.metrics_server() is not None:
            self._metrics = _tracing.metrics_server()
        self._probe_name = "serve_slo:%d" % id(self)
        _tracing.register_health_probe(self._probe_name,
                                       self.scheduler.slo_probe)
        # replica identity on /healthz: the router and a human curl
        # read the same in-flight/served signal (rank, pid, uptime are
        # already in the base payload)
        self._info_name = "serve:%d" % id(self)
        _tracing.register_health_info(self._info_name, self.health_info)
        self._closed = False
        self._close_lock = threading.Lock()
        _log.info("serving: buckets=%s max_wait_ms=%s dp=%d slo_ms=%s%s",
                  self.scheduler.buckets, self.scheduler.max_wait_ms,
                  dp, self.scheduler.slo_ms or "off",
                  " metrics on :%d" % self._metrics.port
                  if self._metrics else "")

    # -- serving API -------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._metrics.port if self._metrics is not None else None

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self.scheduler.buckets

    @property
    def compiles(self) -> int:
        """Executables built so far (bounded by len(buckets))."""
        return self._fused.compiles

    @property
    def closed(self) -> bool:
        return self._closed

    def start(self):
        """The server starts serving at construction; an explicit
        second start is the double-start bug this guard exists for."""
        self.scheduler.start()

    def submit(self, arrays, request_id: Optional[str] = None) -> Request:
        return self.scheduler.submit(arrays, request_id=request_id)

    def infer(self, arrays, timeout: Optional[float] = 60.0):
        return self.scheduler.infer(arrays, timeout)

    def refresh_params(self):
        """Repack after a weight update (e.g. module.set_params).

        Under an injected ``torn_swap`` fault the repack becomes
        non-atomic (half the pack, a sleep, the rest), so a dispatch
        inside the window would mix param versions — the fleet's
        drain-then-swap rolling update must mask that window, and the
        chaos tests prove it does."""
        if _faults.fires("torn_swap"):
            self._fused.refresh_params(
                torn_ms=max(_faults.slow_ms(), 1.0))
        else:
            self._fused.refresh_params()

    def health_info(self) -> dict:
        """Identity payload merged into /healthz by the tracing tier."""
        return {"in_flight": self.scheduler.in_flight(),
                "requests_served": self.scheduler.stats()
                                       .get("requests_served", 0)}

    def stats(self) -> dict:
        out = self.scheduler.stats()
        out["compiles"] = self.compiles
        out["buckets"] = list(self.buckets)
        out["dp"] = self.dp
        out["in_flight"] = self.scheduler.in_flight()
        return out

    # -- shutdown ----------------------------------------------------------
    def close(self):
        """Idempotent and race-safe: the first caller wins, everyone
        else returns immediately (the fleet may close a replica from
        its monitor thread while a drain path does the same)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        _tracing.unregister_health_probe(self._probe_name)
        _tracing.unregister_health_info(self._info_name)
        self.scheduler.close()
        if self._own_metrics and self._metrics is not None:
            self._metrics.stop()
        self._metrics = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
