"""Inception-BN (reference example/image-classification/symbol_inception-bn.py
and the CIFAR 28-small variant behind the 842 img/s baseline,
README.md:202-206)."""
from .. import symbol as sym

__all__ = ["get_inception_bn", "get_inception_bn_28_small"]


def _conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                  name=None):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name="conv_%s" % name,
                           no_bias=True)
    bn = sym.BatchNorm(data=conv, fix_gamma=False, name="bn_%s" % name)
    return sym.Activation(data=bn, act_type="relu", name="relu_%s" % name)


def _inception_a(data, num_1x1, num_3x3red, num_3x3, num_d3x3red, num_d3x3,
                 pool, proj, name):
    c1 = _conv_factory(data, num_1x1, (1, 1), name="%s_1x1" % name)
    c3r = _conv_factory(data, num_3x3red, (1, 1), name="%s_3x3r" % name)
    c3 = _conv_factory(c3r, num_3x3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    cd3r = _conv_factory(data, num_d3x3red, (1, 1), name="%s_d3x3r" % name)
    cd3a = _conv_factory(cd3r, num_d3x3, (3, 3), pad=(1, 1),
                         name="%s_d3x3a" % name)
    cd3b = _conv_factory(cd3a, num_d3x3, (3, 3), pad=(1, 1),
                         name="%s_d3x3b" % name)
    pooling = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1),
                          pad=(1, 1), pool_type=pool)
    cproj = _conv_factory(pooling, proj, (1, 1), name="%s_proj" % name)
    return sym.Concat(c1, c3, cd3b, cproj, num_args=4, name="ch_concat_%s" % name)


def _inception_b(data, num_3x3red, num_3x3, num_d3x3red, num_d3x3, name):
    c3r = _conv_factory(data, num_3x3red, (1, 1), name="%s_3x3r" % name)
    c3 = _conv_factory(c3r, num_3x3, (3, 3), stride=(2, 2), pad=(1, 1),
                       name="%s_3x3" % name)
    cd3r = _conv_factory(data, num_d3x3red, (1, 1), name="%s_d3x3r" % name)
    cd3a = _conv_factory(cd3r, num_d3x3, (3, 3), pad=(1, 1),
                         name="%s_d3x3a" % name)
    cd3b = _conv_factory(cd3a, num_d3x3, (3, 3), stride=(2, 2), pad=(1, 1),
                         name="%s_d3x3b" % name)
    pooling = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type="max")
    return sym.Concat(c3, cd3b, pooling, num_args=3, name="ch_concat_%s" % name)


def get_inception_bn_28_small(num_classes: int = 10):
    """The CIFAR-10 28x28..32x32 small network of the published baseline."""
    data = sym.Variable("data")
    conv1 = _conv_factory(data, 96, (3, 3), pad=(1, 1), name="1")
    in3a = _inception_a(conv1, 64, 64, 64, 64, 96, "avg", 32, "3a")
    in3b = _inception_a(in3a, 64, 64, 96, 64, 96, "avg", 64, "3b")
    in3c = _inception_b(in3b, 128, 160, 64, 96, "3c")
    in4a = _inception_a(in3c, 224, 64, 96, 96, 128, "avg", 128, "4a")
    in4b = _inception_a(in4a, 192, 96, 128, 96, 128, "avg", 128, "4b")
    in4c = _inception_a(in4b, 160, 128, 160, 128, 160, "avg", 128, "4c")
    in4d = _inception_a(in4c, 96, 128, 192, 160, 192, "avg", 128, "4d")
    in4e = _inception_b(in4d, 128, 192, 192, 256, "4e")
    in5a = _inception_a(in4e, 352, 192, 320, 160, 224, "avg", 128, "5a")
    in5b = _inception_a(in5a, 352, 192, 320, 192, 224, "max", 128, "5b")
    pool = sym.Pooling(data=in5b, kernel=(7, 7), global_pool=True,
                       pool_type="avg", name="global_pool")
    flatten = sym.Flatten(data=pool)
    fc1 = sym.FullyConnected(data=flatten, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")


def get_inception_bn(num_classes: int = 1000):
    """ImageNet Inception-BN (the epoch-time baseline model)."""
    data = sym.Variable("data")
    conv1 = _conv_factory(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                          name="1")
    pool1 = sym.Pooling(data=conv1, kernel=(3, 3), stride=(2, 2),
                        pool_type="max")
    conv2r = _conv_factory(pool1, 64, (1, 1), name="2r")
    conv2 = _conv_factory(conv2r, 192, (3, 3), pad=(1, 1), name="2")
    pool2 = sym.Pooling(data=conv2, kernel=(3, 3), stride=(2, 2),
                        pool_type="max")
    in3a = _inception_a(pool2, 64, 64, 64, 64, 96, "avg", 32, "3a")
    in3b = _inception_a(in3a, 64, 64, 96, 64, 96, "avg", 64, "3b")
    in3c = _inception_b(in3b, 128, 160, 64, 96, "3c")
    in4a = _inception_a(in3c, 224, 64, 96, 96, 128, "avg", 128, "4a")
    in4b = _inception_a(in4a, 192, 96, 128, 96, 128, "avg", 128, "4b")
    in4c = _inception_a(in4b, 160, 128, 160, 128, 160, "avg", 128, "4c")
    in4d = _inception_a(in4c, 96, 128, 192, 160, 192, "avg", 128, "4d")
    in4e = _inception_b(in4d, 128, 192, 192, 256, "4e")
    in5a = _inception_a(in4e, 352, 192, 320, 160, 224, "avg", 128, "5a")
    in5b = _inception_a(in5a, 352, 192, 320, 192, 224, "max", 128, "5b")
    pool = sym.Pooling(data=in5b, kernel=(7, 7), global_pool=True,
                       pool_type="avg", name="global_pool")
    flatten = sym.Flatten(data=pool)
    fc1 = sym.FullyConnected(data=flatten, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc1, name="softmax")
