"""ResNet (the north-star benchmark model: ResNet-50 ImageNet images/sec,
BASELINE.md targets).

Bottleneck-v1 architecture; convs lower to XLA ``conv_general_dilated``
which the TPU backend tiles onto the MXU. BatchNorm keeps the reference's
aux moving-stat semantics.
"""
from .. import symbol as sym

__all__ = ["get_resnet", "get_resnet50"]


def _conv_bn_relu(data, num_filter, kernel, stride, pad, name, relu=True,
                  layout="NCHW"):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True,
                           layout=layout, name=name + "_conv")
    bn = sym.BatchNorm(data=conv, fix_gamma=False, eps=2e-5, momentum=0.9,
                       axis=-1 if layout == "NHWC" else 1,
                       name=name + "_bn")
    if relu:
        return sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return bn


def _bottleneck(data, num_filter, stride, dim_match, name, layout="NCHW"):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""
    b1 = _conv_bn_relu(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                       name + "_b1", layout=layout)
    b2 = _conv_bn_relu(b1, num_filter // 4, (3, 3), stride, (1, 1),
                       name + "_b2", layout=layout)
    b3 = _conv_bn_relu(b2, num_filter, (1, 1), (1, 1), (0, 0),
                       name + "_b3", relu=False, layout=layout)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_relu(data, num_filter, (1, 1), stride, (0, 0),
                                 name + "_sc", relu=False, layout=layout)
    fused = b3 + shortcut
    return sym.Activation(data=fused, act_type="relu", name=name + "_out")


def get_resnet(units, filter_list, num_classes=1000, small_input=False,
               layout="NCHW", stem_s2d=False):
    """Build a bottleneck ResNet.

    ``small_input`` (CIFAR-style) swaps the 7x7/2+maxpool stem for 3x3/1,
    letting the same code run 32x32 tests and 224x224 benchmarks.

    ``layout="NHWC"`` builds the whole tower channels-last (data shape
    (N, H, W, C), BatchNorm axis -1) — the TPU-native layout candidate
    measured by tools/mfu_experiments.py. Weights stay OIHW either way,
    so checkpoints are layout-portable.
    """
    data = sym.Variable("data")
    if stem_s2d:
        # space-to-depth stem (MLPerf-style): the caller feeds data
        # already 2x2 depth-stacked — (N, 12, H/2, W/2) — and a 5x5/1
        # conv replaces the 7x7/2; structurally equivalent FLOPs/output
        # resolution for the throughput experiment
        # (tools/mfu_experiments.py), not weight-exact with 7x7
        body = _conv_bn_relu(data, filter_list[0], (5, 5), (1, 1), (2, 2),
                             "stem", layout=layout)
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max", layout=layout)
    elif small_input:
        body = _conv_bn_relu(data, filter_list[0], (3, 3), (1, 1), (1, 1),
                             "stem", layout=layout)
    else:
        body = _conv_bn_relu(data, filter_list[0], (7, 7), (2, 2), (3, 3),
                             "stem", layout=layout)
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max", layout=layout)
    for stage, (n_units, num_filter) in enumerate(zip(units, filter_list[1:])):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _bottleneck(body, num_filter, stride, False,
                           "stage%d_unit0" % stage, layout=layout)
        for unit in range(1, n_units):
            body = _bottleneck(body, num_filter, (1, 1), True,
                               "stage%d_unit%d" % (stage, unit),
                               layout=layout)
    pool = sym.Pooling(data=body, kernel=(7, 7), global_pool=True,
                       pool_type="avg", layout=layout, name="global_pool")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def get_resnet50(num_classes=1000, small_input=False, layout="NCHW"):
    return get_resnet([3, 4, 6, 3], [64, 256, 512, 1024, 2048],
                      num_classes=num_classes, small_input=small_input,
                      layout=layout)
