"""ResNet (the north-star benchmark model: ResNet-50 ImageNet images/sec,
BASELINE.md targets).

Bottleneck-v1 architecture; convs lower to XLA ``conv_general_dilated``
which the TPU backend tiles onto the MXU. BatchNorm keeps the reference's
aux moving-stat semantics.
"""
from .. import symbol as sym

__all__ = ["get_resnet", "get_resnet50"]


def _conv_bn_relu(data, num_filter, kernel, stride, pad, name, relu=True):
    conv = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True,
                           name=name + "_conv")
    bn = sym.BatchNorm(data=conv, fix_gamma=False, eps=2e-5, momentum=0.9,
                       name=name + "_bn")
    if relu:
        return sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return bn


def _bottleneck(data, num_filter, stride, dim_match, name):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""
    b1 = _conv_bn_relu(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                       name + "_b1")
    b2 = _conv_bn_relu(b1, num_filter // 4, (3, 3), stride, (1, 1),
                       name + "_b2")
    b3 = _conv_bn_relu(b2, num_filter, (1, 1), (1, 1), (0, 0),
                       name + "_b3", relu=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_relu(data, num_filter, (1, 1), stride, (0, 0),
                                 name + "_sc", relu=False)
    fused = b3 + shortcut
    return sym.Activation(data=fused, act_type="relu", name=name + "_out")


def get_resnet(units, filter_list, num_classes=1000, small_input=False):
    """Build a bottleneck ResNet.

    ``small_input`` (CIFAR-style) swaps the 7x7/2+maxpool stem for 3x3/1,
    letting the same code run 32x32 tests and 224x224 benchmarks.
    """
    data = sym.Variable("data")
    if small_input:
        body = _conv_bn_relu(data, filter_list[0], (3, 3), (1, 1), (1, 1),
                             "stem")
    else:
        body = _conv_bn_relu(data, filter_list[0], (7, 7), (2, 2), (3, 3),
                             "stem")
        body = sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                           pad=(1, 1), pool_type="max")
    for stage, (n_units, num_filter) in enumerate(zip(units, filter_list[1:])):
        stride = (1, 1) if stage == 0 else (2, 2)
        body = _bottleneck(body, num_filter, stride, False,
                           "stage%d_unit0" % stage)
        for unit in range(1, n_units):
            body = _bottleneck(body, num_filter, (1, 1), True,
                               "stage%d_unit%d" % (stage, unit))
    pool = sym.Pooling(data=body, kernel=(7, 7), global_pool=True,
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def get_resnet50(num_classes=1000, small_input=False):
    return get_resnet([3, 4, 6, 3], [64, 256, 512, 1024, 2048],
                      num_classes=num_classes, small_input=small_input)
