"""Classic ImageNet classifiers: AlexNet, VGG, GoogLeNet, Inception-v3.

Capability parity with the reference's symbol builders
(``example/image-classification/symbol_{alexnet,vgg,googlenet,
inception-v3}.py``), written config-driven: each architecture is a
table of stages expanded by small helpers, so depth variants share one
code path (the reference unrolled every layer by hand).
"""
from __future__ import annotations

from .. import symbol as sym

__all__ = ["get_alexnet", "get_vgg", "get_googlenet", "get_inception_v3"]


def _conv_relu(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
               name=None):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name=name)
    return sym.Activation(data=c, act_type="relu")


def _classifier_head(data, num_classes, hidden=4096, dropout=0.5):
    net = sym.Flatten(data=data)
    for i in range(2):
        net = sym.FullyConnected(data=net, num_hidden=hidden,
                                 name="fc%d" % (i + 6))
        net = sym.Activation(data=net, act_type="relu")
        net = sym.Dropout(data=net, p=dropout)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc8")
    return sym.SoftmaxOutput(data=net, name="softmax")


def get_alexnet(num_classes: int = 1000):
    """AlexNet (Krizhevsky et al. 2012): 5 conv stages with LRN after the
    first two, then the 4096-4096 dropout head."""
    data = sym.Variable("data")
    net = _conv_relu(data, 96, (11, 11), stride=(4, 4), name="conv1")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = sym.LRN(data=net, alpha=1e-4, beta=0.75, knorm=1, nsize=5)
    net = _conv_relu(net, 256, (5, 5), pad=(2, 2), name="conv2")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = sym.LRN(data=net, alpha=1e-4, beta=0.75, knorm=1, nsize=5)
    for i, nf in enumerate((384, 384, 256)):
        net = _conv_relu(net, nf, (3, 3), pad=(1, 1), name="conv%d" % (i + 3))
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    return _classifier_head(net, num_classes)


_VGG_CFG = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}
_VGG_FILTERS = (64, 128, 256, 512, 512)


def get_vgg(num_classes: int = 1000, num_layers: int = 16):
    """VGG-{11,13,16,19} (Simonyan & Zisserman 2014). The reference built
    VGG-16 layer by layer; here the depth table generates all variants."""
    if num_layers not in _VGG_CFG:
        raise ValueError("vgg: num_layers must be one of %s"
                         % sorted(_VGG_CFG))
    net = sym.Variable("data")
    for stage, (reps, nf) in enumerate(zip(_VGG_CFG[num_layers],
                                           _VGG_FILTERS)):
        for i in range(reps):
            net = _conv_relu(net, nf, (3, 3), pad=(1, 1),
                             name="conv%d_%d" % (stage + 1, i + 1))
        net = sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                          pool_type="max")
    return _classifier_head(net, num_classes)


def _inception_v1(data, n1, n3r, n3, n5r, n5, npool, name):
    """GoogLeNet inception block: 1x1 / 3x3 / 5x5 / pool-proj branches."""
    b1 = _conv_relu(data, n1, (1, 1), name=name + "_1x1")
    b3 = _conv_relu(data, n3r, (1, 1), name=name + "_3x3r")
    b3 = _conv_relu(b3, n3, (3, 3), pad=(1, 1), name=name + "_3x3")
    b5 = _conv_relu(data, n5r, (1, 1), name=name + "_5x5r")
    b5 = _conv_relu(b5, n5, (5, 5), pad=(2, 2), name=name + "_5x5")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="max")
    bp = _conv_relu(bp, npool, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b3, b5, bp, name=name + "_concat")


_GOOGLENET_BLOCKS = [
    # (name, 1x1, 3x3red, 3x3, 5x5red, 5x5, pool_proj, pool_before)
    ("3a", 64, 96, 128, 16, 32, 32, False),
    ("3b", 128, 128, 192, 32, 96, 64, False),
    ("4a", 192, 96, 208, 16, 48, 64, True),
    ("4b", 160, 112, 224, 24, 64, 64, False),
    ("4c", 128, 128, 256, 24, 64, 64, False),
    ("4d", 112, 144, 288, 32, 64, 64, False),
    ("4e", 256, 160, 320, 32, 128, 128, False),
    ("5a", 256, 160, 320, 32, 128, 128, True),
    ("5b", 384, 192, 384, 48, 128, 128, False),
]


def get_googlenet(num_classes: int = 1000):
    """GoogLeNet / Inception-v1 (Szegedy et al. 2015), 9 inception
    blocks driven by the block table."""
    data = sym.Variable("data")
    net = _conv_relu(data, 64, (7, 7), stride=(2, 2), pad=(3, 3),
                     name="conv1")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = _conv_relu(net, 64, (1, 1), name="conv2r")
    net = _conv_relu(net, 192, (3, 3), pad=(1, 1), name="conv2")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    for name, n1, n3r, n3, n5r, n5, npool, pool_before in _GOOGLENET_BLOCKS:
        if pool_before:
            net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                              pool_type="max")
        net = _inception_v1(net, n1, n3r, n3, n5r, n5, npool,
                            "inception_" + name)
    net = sym.Pooling(data=net, kernel=(7, 7), pool_type="avg",
                      global_pool=True)
    net = sym.Flatten(data=net)
    net = sym.Dropout(data=net, p=0.4)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _conv_bn_relu(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                  name=None):
    c = sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, no_bias=True, name=name)
    # fix_gamma=True matches the reference's Inception-v3 conv factory
    bn = sym.BatchNorm(data=c, fix_gamma=True, eps=1e-3,
                       name=(name or "conv") + "_bn")
    return sym.Activation(data=bn, act_type="relu")


def _inc3_a(data, npool, name):
    """35x35 block: 1x1 / 5x5 / double-3x3 / avgpool-proj."""
    b1 = _conv_bn_relu(data, 64, (1, 1), name=name + "_1x1")
    b5 = _conv_bn_relu(data, 48, (1, 1), name=name + "_5x5r")
    b5 = _conv_bn_relu(b5, 64, (5, 5), pad=(2, 2), name=name + "_5x5")
    b3 = _conv_bn_relu(data, 64, (1, 1), name=name + "_d3r")
    b3 = _conv_bn_relu(b3, 96, (3, 3), pad=(1, 1), name=name + "_d3a")
    b3 = _conv_bn_relu(b3, 96, (3, 3), pad=(1, 1), name=name + "_d3b")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg")
    bp = _conv_bn_relu(bp, npool, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b5, b3, bp, name=name + "_concat")


def _inc3_b(data, name):
    """17x17 grid reduction."""
    b3 = _conv_bn_relu(data, 384, (3, 3), stride=(2, 2), name=name + "_3x3")
    bd = _conv_bn_relu(data, 64, (1, 1), name=name + "_d3r")
    bd = _conv_bn_relu(bd, 96, (3, 3), pad=(1, 1), name=name + "_d3a")
    bd = _conv_bn_relu(bd, 96, (3, 3), stride=(2, 2), name=name + "_d3b")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                     pool_type="max")
    return sym.Concat(b3, bd, bp, name=name + "_concat")


def _inc3_c(data, n7, name):
    """17x17 block with factorized 7x7 (1x7 + 7x1) branches."""
    b1 = _conv_bn_relu(data, 192, (1, 1), name=name + "_1x1")
    b7 = _conv_bn_relu(data, n7, (1, 1), name=name + "_7r")
    b7 = _conv_bn_relu(b7, n7, (1, 7), pad=(0, 3), name=name + "_7a")
    b7 = _conv_bn_relu(b7, 192, (7, 1), pad=(3, 0), name=name + "_7b")
    bd = _conv_bn_relu(data, n7, (1, 1), name=name + "_d7r")
    bd = _conv_bn_relu(bd, n7, (7, 1), pad=(3, 0), name=name + "_d7a")
    bd = _conv_bn_relu(bd, n7, (1, 7), pad=(0, 3), name=name + "_d7b")
    bd = _conv_bn_relu(bd, n7, (7, 1), pad=(3, 0), name=name + "_d7c")
    bd = _conv_bn_relu(bd, 192, (1, 7), pad=(0, 3), name=name + "_d7d")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type="avg")
    bp = _conv_bn_relu(bp, 192, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b7, bd, bp, name=name + "_concat")


def _inc3_d(data, name):
    """8x8 grid reduction."""
    b3 = _conv_bn_relu(data, 192, (1, 1), name=name + "_3r")
    b3 = _conv_bn_relu(b3, 320, (3, 3), stride=(2, 2), name=name + "_3x3")
    b7 = _conv_bn_relu(data, 192, (1, 1), name=name + "_7r")
    b7 = _conv_bn_relu(b7, 192, (1, 7), pad=(0, 3), name=name + "_7a")
    b7 = _conv_bn_relu(b7, 192, (7, 1), pad=(3, 0), name=name + "_7b")
    b7 = _conv_bn_relu(b7, 192, (3, 3), stride=(2, 2), name=name + "_7c")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(2, 2),
                     pool_type="max")
    return sym.Concat(b3, b7, bp, name=name + "_concat")


def _inc3_e(data, name, pool="avg"):
    """8x8 block with expanded 3x3 (1x3 | 3x1) fan-outs. The reference
    uses an avg-pool branch in the first E block and max in the second."""
    b1 = _conv_bn_relu(data, 320, (1, 1), name=name + "_1x1")
    b3 = _conv_bn_relu(data, 384, (1, 1), name=name + "_3r")
    b3a = _conv_bn_relu(b3, 384, (1, 3), pad=(0, 1), name=name + "_3a")
    b3b = _conv_bn_relu(b3, 384, (3, 1), pad=(1, 0), name=name + "_3b")
    bd = _conv_bn_relu(data, 448, (1, 1), name=name + "_d3r")
    bd = _conv_bn_relu(bd, 384, (3, 3), pad=(1, 1), name=name + "_d3")
    bda = _conv_bn_relu(bd, 384, (1, 3), pad=(0, 1), name=name + "_d3a")
    bdb = _conv_bn_relu(bd, 384, (3, 1), pad=(1, 0), name=name + "_d3b")
    bp = sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                     pool_type=pool)
    bp = _conv_bn_relu(bp, 192, (1, 1), name=name + "_proj")
    return sym.Concat(b1, b3a, b3b, bda, bdb, bp, name=name + "_concat")


def get_inception_v3(num_classes: int = 1000):
    """Inception-v3 (Szegedy et al. 2016) for 299x299 inputs."""
    data = sym.Variable("data")
    net = _conv_bn_relu(data, 32, (3, 3), stride=(2, 2), name="conv1")
    net = _conv_bn_relu(net, 32, (3, 3), name="conv2")
    net = _conv_bn_relu(net, 64, (3, 3), pad=(1, 1), name="conv3")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    net = _conv_bn_relu(net, 80, (1, 1), name="conv4")
    net = _conv_bn_relu(net, 192, (3, 3), name="conv5")
    net = sym.Pooling(data=net, kernel=(3, 3), stride=(2, 2),
                      pool_type="max")
    for i, npool in enumerate((32, 64, 64)):
        net = _inc3_a(net, npool, "mixed_a%d" % (i + 1))
    net = _inc3_b(net, "mixed_b1")
    for i, n7 in enumerate((128, 160, 160, 192)):
        net = _inc3_c(net, n7, "mixed_c%d" % (i + 1))
    net = _inc3_d(net, "mixed_d1")
    for i, pool in enumerate(("avg", "max")):
        net = _inc3_e(net, "mixed_e%d" % (i + 1), pool=pool)
    net = sym.Pooling(data=net, kernel=(8, 8), pool_type="avg",
                      global_pool=True)
    net = sym.Dropout(data=net, p=0.5)
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=num_classes, name="fc")
    return sym.SoftmaxOutput(data=net, name="softmax")
