"""LSTM language models.

Two formulations matching the reference:

* :func:`lstm_unroll` — explicit symbol-per-timestep unrolling with shared
  weight variables (reference ``example/rnn/lstm.py``), used with
  BucketingModule for variable-length training.
* :func:`lstm_fused` — the fused ``sym.RNN`` op (reference cuDNN RNN path,
  ``cudnn_rnn-inl.h``): one ``lax.scan`` whose per-step cell matmul hits
  the MXU with weights resident across iterations.
"""
from .. import symbol as sym

__all__ = ["lstm_unroll", "lstm_fused"]


def _lstm_cell(num_hidden, indata, prev_h, prev_c, param, seqidx, layeridx):
    """One LSTM step from shared weights (reference lstm.py ``lstm()``)."""
    i2h = sym.FullyConnected(data=indata, weight=param["i2h_weight"],
                             bias=param["i2h_bias"],
                             num_hidden=num_hidden * 4,
                             name="t%d_l%d_i2h" % (seqidx, layeridx))
    h2h = sym.FullyConnected(data=prev_h, weight=param["h2h_weight"],
                             bias=param["h2h_bias"],
                             num_hidden=num_hidden * 4,
                             name="t%d_l%d_h2h" % (seqidx, layeridx))
    gates = i2h + h2h
    slices = sym.SliceChannel(data=gates, num_outputs=4, axis=1,
                              name="t%d_l%d_slice" % (seqidx, layeridx))
    in_gate = sym.Activation(slices[0], act_type="sigmoid")
    forget_gate = sym.Activation(slices[1], act_type="sigmoid")
    in_transform = sym.Activation(slices[2], act_type="tanh")
    out_gate = sym.Activation(slices[3], act_type="sigmoid")
    next_c = (forget_gate * prev_c) + (in_gate * in_transform)
    next_h = out_gate * sym.Activation(next_c, act_type="tanh")
    return next_h, next_c


def lstm_unroll(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0):
    """Explicitly unrolled LSTM LM over a (batch, seq_len) int sequence
    (reference example/rnn/lstm.py ``lstm_unroll``)."""
    embed_weight = sym.Variable("embed_weight")
    cls_weight = sym.Variable("cls_weight")
    cls_bias = sym.Variable("cls_bias")
    params = []
    init_states = []
    for i in range(num_lstm_layer):
        params.append({
            "i2h_weight": sym.Variable("l%d_i2h_weight" % i),
            "i2h_bias": sym.Variable("l%d_i2h_bias" % i),
            "h2h_weight": sym.Variable("l%d_h2h_weight" % i),
            "h2h_bias": sym.Variable("l%d_h2h_bias" % i),
        })
        init_states.append((sym.Variable("l%d_init_h" % i),
                            sym.Variable("l%d_init_c" % i)))

    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=input_size,
                          weight=embed_weight, output_dim=num_embed,
                          name="embed")
    wordvec = sym.SliceChannel(data=embed, num_outputs=seq_len, axis=1,
                               squeeze_axis=True, name="wordvec_slice")

    hidden_all = []
    states = [(h, c) for h, c in init_states]
    for seqidx in range(seq_len):
        hidden = wordvec[seqidx]
        for i in range(num_lstm_layer):
            next_h, next_c = _lstm_cell(num_hidden, hidden, states[i][0],
                                        states[i][1], params[i], seqidx, i)
            states[i] = (next_h, next_c)
            hidden = next_h
        if dropout > 0:
            hidden = sym.Dropout(data=hidden, p=dropout)
        hidden_all.append(hidden)

    hidden_concat = sym.Concat(*hidden_all, num_args=seq_len, dim=0)
    pred = sym.FullyConnected(data=hidden_concat, num_hidden=num_label,
                              weight=cls_weight, bias=cls_bias, name="pred")
    # labels (batch, seq) -> time-major flat to match concat order
    label_t = sym.transpose(data=label)
    label_flat = sym.Reshape(data=label_t, target_shape=(-1,))
    return sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")


def lstm_fused(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
               num_label, dropout=0.0):
    """Same LM via the fused RNN op — the TPU-native fast path."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data=data, input_dim=input_size,
                          output_dim=num_embed, name="embed")
    # (batch, seq, embed) -> time-major (seq, batch, embed)
    tnc = sym.SwapAxis(data=embed, dim1=0, dim2=1)
    rnn = sym.RNN(data=tnc, state_size=num_hidden,
                  num_layers=num_lstm_layer, mode="lstm", p=dropout,
                  name="lstm")
    flat = sym.Reshape(data=rnn, target_shape=(-1, num_hidden))
    pred = sym.FullyConnected(data=flat, num_hidden=num_label, name="pred")
    label_t = sym.transpose(data=label)
    label_flat = sym.Reshape(data=label_t, target_shape=(-1,))
    return sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")
