"""Model zoo: symbol builders for the reference's example networks
(reference ``example/image-classification/symbol_*.py``, ``example/rnn``)."""
from .mlp import get_mlp
from .lenet import get_lenet
from .resnet import get_resnet, get_resnet50
from .inception_bn import get_inception_bn, get_inception_bn_28_small
from .lstm import lstm_unroll, lstm_fused

__all__ = ["get_mlp", "get_lenet", "get_resnet", "get_resnet50",
           "get_inception_bn", "get_inception_bn_28_small",
           "lstm_unroll", "lstm_fused"]
