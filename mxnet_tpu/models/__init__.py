"""Model zoo: symbol builders for the reference's example networks
(reference ``example/image-classification/symbol_*.py``, ``example/rnn``)."""
from .mlp import get_mlp
from .lenet import get_lenet
from .resnet import get_resnet, get_resnet50
from .inception_bn import get_inception_bn, get_inception_bn_28_small
from .lstm import lstm_unroll, lstm_fused
from .vision import (get_alexnet, get_vgg, get_googlenet,
                     get_inception_v3)

__all__ = ["get_mlp", "get_lenet", "get_resnet", "get_resnet50",
           "get_inception_bn", "get_inception_bn_28_small",
           "lstm_unroll", "lstm_fused", "get_alexnet", "get_vgg",
           "get_googlenet", "get_inception_v3"]
