"""Dependency engine.

TPU-native re-design of the reference's async scheduler
(``include/mxnet/engine.h:58-223``, ``src/engine/threaded_engine.h:42-373``):
ops are pushed with read/write variable sets; the engine serializes
conflicting ops and parallelizes the rest.

On TPU the device-side scheduling is done by XLA's async dispatch queue, so
the default engine (:class:`XLAEngine`) executes host closures inline — the
returned ``jax.Array`` futures give the same async overlap the reference got
from per-GPU worker streams. Two more engines mirror the reference:

* :class:`NaiveEngine` — synchronous debugging engine, blocks after every op
  (reference ``src/engine/naive_engine.cc``; selected with
  ``MXNET_ENGINE_TYPE=NaiveEngine``).
* :class:`ThreadedEngine` — a real host-side thread-pool engine with the
  ThreadedVar read/write queue design (reference
  ``src/engine/threaded_engine.cc:26-180``); used for host tasks (IO
  prefetch, callbacks) and validated by the randomized stress test
  (reference ``tests/cpp/threaded_engine_test.cc``).
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Callable, Iterable, List, Optional, Sequence

from . import telemetry as _tel
from . import env as _env
from .base import MXNetError, getenv

__all__ = ["Engine", "Var", "get_engine", "set_engine", "NaiveEngine",
           "XLAEngine", "ThreadedEngine", "ThreadedEnginePooled"]

_var_counter = itertools.count()


class Var:
    """Engine variable: a unit of read/write dependency tracking
    (reference ``ThreadedVar``, ``src/engine/threaded_engine.h:42-160``)."""

    __slots__ = ("vid", "version", "_lock", "_queue", "_num_pending_reads",
                 "_pending_write")

    def __init__(self):
        self.vid = next(_var_counter)
        self.version = 0          # bumped on every completed write
        self._lock = threading.Lock()
        # queue of (is_write, opr) blocks waiting on this var
        self._queue: deque = deque()
        self._num_pending_reads = 0
        self._pending_write = None

    def __repr__(self):
        return "Var(%d, v%d)" % (self.vid, self.version)


class _OprBlock:
    __slots__ = ("fn", "const_vars", "mutable_vars", "priority", "wait",
                 "lock", "seq", "prop", "enq_t")

    def __init__(self, fn, const_vars, mutable_vars, priority, seq,
                 prop="normal"):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.priority = priority
        self.seq = seq
        self.wait = 0
        self.lock = threading.Lock()
        self.prop = prop
        self.enq_t = 0.0  # ready-heap entry time (telemetry queue-wait)

    def dec_wait(self) -> bool:
        with self.lock:
            self.wait -= 1
            return self.wait == 0


def _check_duplicates(const_vars, mutable_vars):
    """Reference ``ThreadedEngine::CheckDuplicate``
    (``src/engine/threaded_engine.cc:205``)."""
    cset = set(id(v) for v in const_vars)
    mset = set(id(v) for v in mutable_vars)
    if len(mset) != len(mutable_vars):
        raise MXNetError("duplicate variable in mutable_vars")
    if cset & mset:
        raise MXNetError("variable appears in both const_vars and mutable_vars")


class Engine:
    """Engine interface (reference ``include/mxnet/engine.h:74-223``)."""

    def new_variable(self) -> Var:
        return Var()

    def push(self, fn: Callable[[], object], const_vars: Sequence[Var] = (),
             mutable_vars: Sequence[Var] = (), priority: int = 0,
             prop: str = "normal") -> None:
        """``prop`` mirrors the reference's ``FnProperty`` (engine.h:
        Normal / CopyFromGPU / CopyToGPU / kAsync): engines with a
        dedicated I/O pool route ``"io"``/``"copy"`` ops there."""
        raise NotImplementedError

    def wait_for_var(self, var: Var) -> None:
        raise NotImplementedError

    def wait_for_all(self) -> None:
        raise NotImplementedError

    def delete_variable(self, var: Var) -> None:
        # Python GC owns lifetime; kept for API parity with
        # Engine::DeleteVariable.
        pass


def _bump_versions(mutable_vars: Iterable[Var]):
    for v in mutable_vars:
        v.version += 1


_ENGINE_INFO = None


def _engine_info_enabled():
    global _ENGINE_INFO
    if _ENGINE_INFO is None:   # read once like the reference's dmlc::GetEnv
        from .base import getenv

        _ENGINE_INFO = bool(getenv("MXNET_ENGINE_INFO", False))
    return _ENGINE_INFO


def _log_push(engine, fn, const_vars, mutable_vars, priority, prop):
    """Per-op engine logging (reference MXNET_ENGINE_INFO,
    src/engine/threaded_engine.h:253,288-301): one line per pushed op
    with its dependency sets — the first tool the reference docs
    recommended for debugging engine-ordering problems."""
    import logging

    logging.getLogger("mxnet_tpu.engine").info(
        "%s push %s const=%s mutable=%s priority=%d prop=%s",
        type(engine).__name__,
        getattr(fn, "__qualname__", getattr(fn, "__name__", repr(fn))),
        [id(v) % 100000 for v in const_vars],
        [id(v) % 100000 for v in mutable_vars], priority, prop)


class XLAEngine(Engine):
    """Default engine: run host closures inline; XLA's async dispatch queue
    provides device-side overlap (the reference's per-device worker streams,
    ``src/engine/threaded_engine_perdevice.cc:26-187``, map onto it)."""

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop="normal"):
        _check_duplicates(const_vars, mutable_vars)
        if _engine_info_enabled():
            _log_push(self, fn, const_vars, mutable_vars, priority, prop)
        _tel.inc("engine.push")
        fn()
        _tel.inc("engine.dispatch")
        _bump_versions(mutable_vars)

    def wait_for_var(self, var):
        pass  # data-level waiting is done by NDArray.wait_to_read

    def wait_for_all(self):
        try:
            import jax

            jax.effects_barrier()
        except Exception:
            pass


class NaiveEngine(Engine):
    """Synchronous debugging engine (reference ``src/engine/naive_engine.cc``).
    If the closure returns jax arrays they are blocked on immediately."""

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop="normal"):
        _check_duplicates(const_vars, mutable_vars)
        if _engine_info_enabled():
            _log_push(self, fn, const_vars, mutable_vars, priority, prop)
        _tel.inc("engine.push")
        ret = fn()
        _tel.inc("engine.dispatch")
        _bump_versions(mutable_vars)
        if prop == "fused_step" \
                and not _env.get("MXNET_TPU_ENGINE_SYNC"):
            # the fused train step returns freshly-donated outputs; an
            # unconditional block here would serialize every batch on
            # the device instead of letting the next dispatch queue.
            # MXNET_TPU_ENGINE_SYNC=1 restores blocking for debugging.
            return
        _block_on(ret)

    def wait_for_var(self, var):
        pass

    def wait_for_all(self):
        pass


def _block_on(ret):
    if ret is None:
        return
    if isinstance(ret, (tuple, list)):
        for r in ret:
            _block_on(r)
        return
    if hasattr(ret, "block_until_ready"):
        # the engine's one sanctioned device sync (ENGINE_SYNC debug
        # path and non-fused result barriers)
        ret.block_until_ready()  # graft: host-sync


class ThreadedEngine(Engine):
    """Host-side threaded dependency engine.

    Implements the reference's ThreadedVar algorithm
    (``src/engine/threaded_engine.cc:26-180``): each var keeps a FIFO of
    pending blocks; reads run concurrently, writes serialize; an op
    dispatches when its wait counter reaches zero. Workers pop a priority
    queue (priority semantics as in ``Engine::Push(priority=)``).
    """

    def __init__(self, num_workers: Optional[int] = None):
        from .analysis import sanitizers as _san
        self._num_workers = num_workers or getenv("MXNET_CPU_WORKER_NTHREADS", 4)
        self._heap: List = []
        self._heap_lock = _san.maybe_instrument(threading.Condition(),
                                                "engine-heap")
        self._pending = 0
        self._pending_lock = _san.maybe_instrument(threading.Condition(),
                                                   "engine-pending")
        self._seq = itertools.count()
        self._shutdown = False
        self._workers = []
        for i in range(self._num_workers):
            t = threading.Thread(target=self._worker_loop,
                                 name="mxtpu-engine-%d" % i, daemon=True)
            t.start()
            self._workers.append(t)

    # -- dependency bookkeeping (ThreadedVar) ------------------------------
    @staticmethod
    def _append_read(var: Var, opr: _OprBlock) -> bool:
        """True if the read is immediately ready."""
        with var._lock:
            if var._pending_write is None and not var._queue:
                var._num_pending_reads += 1
                return True
            var._queue.append((False, opr))
            return False

    @staticmethod
    def _append_write(var: Var, opr: _OprBlock) -> bool:
        with var._lock:
            if (var._pending_write is None and var._num_pending_reads == 0
                    and not var._queue):
                var._pending_write = opr
                return True
            var._queue.append((True, opr))
            return False

    def _complete_read(self, var: Var):
        ready = []
        with var._lock:
            var._num_pending_reads -= 1
            if var._num_pending_reads == 0 and var._queue:
                is_write, opr = var._queue[0]
                if is_write:
                    var._queue.popleft()
                    var._pending_write = opr
                    ready.append(opr)
        self._on_deps_resolved(ready)

    def _complete_write(self, var: Var):
        ready = []
        with var._lock:
            var._pending_write = None
            var.version += 1
            # drain consecutive reads; or a single write if it is first
            while var._queue:
                is_write, opr = var._queue[0]
                if is_write:
                    if var._num_pending_reads == 0 and var._pending_write is None:
                        var._queue.popleft()
                        var._pending_write = opr
                        ready.append(opr)
                    break
                var._queue.popleft()
                var._num_pending_reads += 1
                ready.append(opr)
        self._on_deps_resolved(ready)

    def _on_deps_resolved(self, oprs):
        for opr in oprs:
            if opr.dec_wait():
                self._dispatch(opr)

    # -- scheduling --------------------------------------------------------
    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop="normal"):
        # materialize first: logging must not consume one-shot iterables
        const_vars = list(const_vars)
        mutable_vars = list(mutable_vars)
        _check_duplicates(const_vars, mutable_vars)
        if _engine_info_enabled():
            _log_push(self, fn, const_vars, mutable_vars, priority, prop)
        _tel.inc("engine.push")
        opr = _OprBlock(fn, const_vars, mutable_vars, priority,
                        next(self._seq), prop)
        with self._pending_lock:
            self._pending += 1
        # Guard counter: assume every dep is unready plus one guard unit, so
        # deps completing concurrently during registration can never drop the
        # counter to zero early (reference OprBlock.wait pattern).
        n_deps = len(const_vars) + len(mutable_vars)
        opr.wait = 1 + n_deps
        n_ready = 0
        for v in const_vars:
            if self._append_read(v, opr):
                n_ready += 1
        for v in mutable_vars:
            if self._append_write(v, opr):
                n_ready += 1
        with opr.lock:
            opr.wait -= n_ready + 1
            ready = opr.wait == 0
        if ready:
            self._dispatch(opr)

    def _dispatch(self, opr: _OprBlock):
        if _tel.enabled():
            opr.enq_t = time.perf_counter()
        with self._heap_lock:
            heapq.heappush(self._heap, (-opr.priority, opr.seq, opr))
            self._heap_lock.notify()

    def _worker_loop(self, heap=None, cond=None):
        heap = self._heap if heap is None else heap
        cond = self._heap_lock if cond is None else cond
        while True:
            with cond:
                while not heap and not self._shutdown:
                    cond.wait()
                if self._shutdown and not heap:
                    return
                _, _, opr = heapq.heappop(heap)
            if _tel.enabled():
                _tel.inc("engine.dispatch")
                if opr.enq_t:
                    _tel.observe("engine.queue_wait_ms",
                                 (time.perf_counter() - opr.enq_t) * 1e3)
            try:
                opr.fn()
            finally:
                for v in opr.const_vars:
                    self._complete_read(v)
                for v in opr.mutable_vars:
                    self._complete_write(v)
                with self._pending_lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._pending_lock.notify_all()

    def wait_for_var(self, var: Var):
        done = threading.Event()
        self.push(done.set, const_vars=[var])
        done.wait()

    def wait_for_all(self):
        with self._pending_lock:
            while self._pending:
                self._pending_lock.wait()

    def stop(self):
        self.wait_for_all()
        with self._heap_lock:
            self._shutdown = True
            self._heap_lock.notify_all()


class ThreadedEnginePooled(ThreadedEngine):
    """Global compute pool + dedicated I/O pool (reference
    ``src/engine/threaded_engine_pooled.cc:24-121``: one thread pool for
    compute, a separate single-thread pool for I/O/copy ops so long
    reads never starve compute). Ops pushed with ``prop="io"`` or
    ``prop="copy"`` run on the I/O workers."""

    def __init__(self, num_workers: Optional[int] = None,
                 num_io_workers: Optional[int] = None):
        super().__init__(num_workers)
        from .analysis import sanitizers as _san
        self._io_heap: List = []
        self._io_lock = _san.maybe_instrument(threading.Condition(),
                                              "engine-io")
        n_io = (num_io_workers if num_io_workers is not None
                else getenv("MXNET_CPU_IO_NTHREADS", 1))
        self._io_workers = []
        for i in range(n_io):
            t = threading.Thread(
                target=self._worker_loop, args=(self._io_heap,
                                                self._io_lock),
                name="mxtpu-engine-io-%d" % i, daemon=True)
            t.start()
            self._io_workers.append(t)

    def _dispatch(self, opr: _OprBlock):
        # with no I/O workers (MXNET_CPU_IO_NTHREADS=0), io ops must fall
        # through to the compute pool or they would never run
        if opr.prop in ("io", "copy") and self._io_workers:
            if _tel.enabled():
                opr.enq_t = time.perf_counter()
            with self._io_lock:
                heapq.heappush(self._io_heap, (-opr.priority, opr.seq, opr))
                self._io_lock.notify()
        else:
            super()._dispatch(opr)

    def stop(self):
        super().stop()
        with self._io_lock:
            self._io_lock.notify_all()


class NativeThreadedEngine(Engine):
    """Host dependency engine backed by the C++ scheduler
    (``src/native/engine.cc`` — the native re-design of the reference's
    ``src/engine/threaded_engine.cc``). Python closures run on C++ worker
    threads via a ctypes trampoline; exceptions are captured and re-raised
    at the next wait."""

    def __init__(self, num_workers: Optional[int] = None):
        import ctypes
        import itertools as _it

        from ._native_lib import get_lib

        lib = get_lib()
        if lib is None:
            raise MXNetError("native engine library unavailable "
                             "(build with `make` or install g++)")
        self._lib = lib
        self._handle = lib.mxtpu_engine_create(
            num_workers or getenv("MXNET_CPU_WORKER_NTHREADS", 4))
        self._pending = {}
        self._pending_lock = threading.Lock()
        self._token = _it.count(1)
        self._errors: List[BaseException] = []
        self._ctypes = ctypes

        CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

        def _run(token):
            with self._pending_lock:
                fn = self._pending.pop(token)
            _tel.inc("engine.dispatch")
            try:
                fn()
            except BaseException as e:  # noqa: BLE001
                self._errors.append(e)
        self._trampoline = CB(_run)  # must outlive the engine

    def new_variable(self) -> Var:
        v = Var()
        v_native = self._lib.mxtpu_engine_new_var(self._handle)
        object.__setattr__(v, "version", 0)
        self._native_of(v, v_native)
        return v

    @staticmethod
    def _native_of(var, ptr=None):
        # Var has __slots__; keep the native ptr in a side table
        if ptr is not None:
            NativeThreadedEngine._ptr_table[id(var)] = (var, ptr)
        return NativeThreadedEngine._ptr_table[id(var)][1]

    _ptr_table: dict = {}

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             prop="normal"):
        ctypes = self._ctypes

        _check_duplicates(const_vars, mutable_vars)
        if _engine_info_enabled():
            _log_push(self, fn, const_vars, mutable_vars, priority, prop)
        _tel.inc("engine.push")
        token = next(self._token)
        with self._pending_lock:
            self._pending[token] = fn

        def _wrap(vars_):
            arr = (ctypes.c_void_p * max(len(vars_), 1))()
            for i, v in enumerate(vars_):
                arr[i] = self._native_of(v)
            return arr
        cv = _wrap(const_vars)
        mv = _wrap(mutable_vars)
        self._lib.mxtpu_engine_push(
            self._handle, ctypes.cast(self._trampoline, ctypes.c_void_p),
            ctypes.c_void_p(token), cv, len(const_vars), mv,
            len(mutable_vars), priority)
        for v in mutable_vars:
            v.version += 1  # logical version; native tracks its own

    def wait_for_var(self, var: Var):
        done = threading.Event()
        self.push(done.set, const_vars=[var])
        done.wait()
        self._raise_errors()

    def wait_for_all(self):
        self._lib.mxtpu_engine_wait_all(self._handle)
        self._raise_errors()

    def _raise_errors(self):
        if self._errors:
            err = self._errors[0]
            self._errors = []
            raise err


_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def _create_engine() -> Engine:
    kind = getenv("MXNET_ENGINE_TYPE", "XLAEngine")
    if kind in ("NaiveEngine",):
        return NaiveEngine()
    if kind == "ThreadedEnginePooled":
        return ThreadedEnginePooled()
    if kind == "ThreadedEngine":
        return ThreadedEngine()
    if kind in ("NativeEngine", "NativeThreadedEngine"):
        return NativeThreadedEngine()
    # ThreadedEnginePerDevice (the reference default) == XLA async dispatch
    return XLAEngine()


def get_engine() -> Engine:
    global _engine
    if _engine is None:
        with _engine_lock:
            if _engine is None:
                _engine = _create_engine()
    return _engine


def set_engine(engine: Engine) -> Engine:
    global _engine
    _engine = engine
    return engine
