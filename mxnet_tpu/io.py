"""Data iterators.

TPU-native re-design of the reference's IO layer (``src/io/`` +
``python/mxnet/io.py``): the ``DataIter`` protocol
(``Init/BeforeFirst/Next/Value`` -> ``reset/next``), batching with pad
semantics, background prefetch, and sharding for distributed data parallel
via ``num_parts``/``part_index`` (reference ``iter_image_recordio.cc:223-244``
— this is how distributed workers split data).

Decode/augment runs on host CPU (PIL instead of OpenCV); batches land on
device as jax arrays via NDArray.
"""
from __future__ import annotations

import gzip
import logging
import os
import queue
import struct
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import telemetry as _tel
from . import env as _env
from .base import MXNetError, Registry
from .context import Context
from .ndarray import NDArray, array

__all__ = ["DataBatch", "DataIter", "NDArrayIter", "MNISTIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "DataDesc",
           "RecordDecoder"]

_REG: Registry = Registry.get_registry("data_iter")


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape descriptor; ``layout`` declares the batch axis (reference
    ``LayoutMapper``, io.py:23-80 — 'N' position matters for TNC vs NTC)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Iterator protocol (reference ``include/mxnet/io.h:76-96``)."""

    def __init__(self):
        self.batch_size = 0

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self) -> DataBatch:
        return self.next()

    def next(self) -> DataBatch:
        if self.iter_next():
            _tel.inc("io.batches")
            return DataBatch(self.getdata(), self.getlabel(),
                             self.getpad(), self.getindex())
        raise StopIteration

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0

    @property
    def provide_data(self) -> List[DataDesc]:
        raise NotImplementedError

    @property
    def provide_label(self) -> List[DataDesc]:
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input to list of (name, np.ndarray) (reference io.py)."""
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise MXNetError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {default_name + "_%d" % i: d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise MXNetError("data must be NDArray, numpy array, list or dict")
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, np.asarray(v)))
    return out


@_REG.register("NDArrayIter")
class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:395)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        if shuffle:
            idx = np.random.permutation(self.num_data)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.num_data = new_n
        if self.num_data < batch_size:
            raise MXNetError("batch_size larger than dataset")

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size

    # -- checkpoint support (checkpoint.py) ---------------------------
    def get_checkpoint_state(self) -> dict:
        """Identity of this data stream for the snapshot manifest."""
        return {"kind": type(self).__name__,
                "batch_size": self.batch_size,
                "num_data": self.num_data}

    def set_checkpoint_state(self, state: dict) -> None:
        """Seek to ``state["batches"]`` batches already consumed this
        epoch (0 == freshly reset). A logical-count seek, not a raw
        cursor copy: the saved cursor may include prefetch wrapper
        read-ahead the training loop never saw."""
        k = int(state.get("batches", 0))
        self.cursor = (k - 1) * self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        if self.cursor + self.batch_size <= self.num_data:
            return [array(v[self.cursor:self.cursor + self.batch_size])
                    for _, v in data_source]
        # pad with wrap-around (reference roll-over semantics)
        pad = self.batch_size - (self.num_data - self.cursor)
        return [array(np.concatenate([v[self.cursor:], v[:pad]], axis=0))
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def _read_idx_file(path: str) -> np.ndarray:
    """Read an idx-format (MNIST) file, gzip-transparent."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise MXNetError("invalid idx file %s" % path)
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
                 0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64}[dtype_code]
        data = np.frombuffer(f.read(), dtype=np.dtype(dtype).newbyteorder(">"))
        return data.reshape(shape).astype(dtype)


@_REG.register("MNISTIter")
class MNISTIter(DataIter):
    """MNIST idx-format iterator with worker sharding (reference
    ``src/io/iter_mnist.cc``: ``num_parts``/``part_index``)."""

    def __init__(self, image: str, label: str, batch_size: int = 128,
                 shuffle: bool = True, flat: bool = False, seed: int = 0,
                 silent: bool = False, num_parts: int = 1, part_index: int = 0,
                 input_shape=None, **kwargs):
        super().__init__()
        images = _read_idx_file(image).astype(np.float32) / 255.0
        labels = _read_idx_file(label).astype(np.float32)
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images.reshape(images.shape[0], 1,
                                    images.shape[1], images.shape[2])
            if input_shape is not None:
                images = images.reshape((images.shape[0],) + tuple(input_shape))
        if num_parts > 1:
            images = images[part_index::num_parts]
            labels = labels[part_index::num_parts]
        if shuffle:
            rng = np.random.RandomState(seed)
            idx = rng.permutation(images.shape[0])
            images, labels = images[idx], labels[idx]
        self._inner = NDArrayIter(images, labels, batch_size=batch_size,
                                  last_batch_handle="discard")
        self.batch_size = batch_size

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def iter_next(self):
        return self._inner.iter_next()

    def getdata(self):
        return self._inner.getdata()

    def getlabel(self):
        return self._inner.getlabel()

    def getpad(self):
        return self._inner.getpad()


@_REG.register("CSVIter")
class CSVIter(DataIter):
    """CSV file iterator (reference ``src/io/iter_csv.cc``)."""

    def __init__(self, data_csv: str, data_shape, label_csv: Optional[str] = None,
                 label_shape=(1,), batch_size: int = 1, **kwargs):
        super().__init__()
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label[:, 0]
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        self._inner = NDArrayIter(data, label, batch_size=batch_size,
                                  last_batch_handle="pad")
        self.batch_size = batch_size

    provide_data = property(lambda self: self._inner.provide_data)
    provide_label = property(lambda self: self._inner.provide_label)

    def reset(self):
        self._inner.reset()

    def iter_next(self):
        return self._inner.iter_next()

    def getdata(self):
        return self._inner.getdata()

    def getlabel(self):
        return self._inner.getlabel()

    def getpad(self):
        return self._inner.getpad()


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference io.py:181)."""

    def __init__(self, data_iter: DataIter, size: int, reset_internal: bool = True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch: Optional[DataBatch] = None
        self.batch_size = data_iter.batch_size

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class RecordDecoder:
    """Host-side decode+augment engine shared by ImageRecordIter's
    in-process (thread) path and :mod:`mxnet_tpu.io_pipeline`'s decode
    worker processes.

    Every augmentation draw comes from an RNG keyed by ``(seed, epoch,
    record index)`` (:meth:`derive_rng`), so the execution vehicle —
    thread count, process count, decode order — can never change what a
    record looks like: 1-thread, N-thread and N-process runs are
    bit-identical. The constructor kwargs round-trip through
    :meth:`config` (all picklable), which is how a spawned worker
    rebuilds the exact same decoder."""

    def __init__(self, data_shape, seed: int = 0, rand_crop: bool = False,
                 rand_mirror: bool = False, resize: int = -1,
                 scale: float = 1.0, max_rotate_angle: int = 0,
                 rotate: float = -1.0, rotate_list=(),
                 max_shear_ratio: float = 0.0, pad: int = 0,
                 fill_value: int = 255, random_h: int = 0, random_s: int = 0,
                 random_l: int = 0, mean=None, label_width: int = 1):
        self.data_shape = tuple(data_shape)
        self.seed = int(seed)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.scale = scale
        self.max_rotate_angle = max_rotate_angle
        self.rotate = rotate
        self.rotate_list = list(rotate_list)
        self.max_shear_ratio = max_shear_ratio
        self.pad = pad
        self.fill_value = fill_value
        self.random_h = random_h
        self.random_s = random_s
        self.random_l = random_l
        self.mean = None if mean is None else np.asarray(mean, dtype=np.float32)
        self.label_width = label_width

    def config(self) -> dict:
        """Picklable kwargs that rebuild this decoder bit-identically in
        another process."""
        return {"data_shape": self.data_shape, "seed": self.seed,
                "rand_crop": self.rand_crop, "rand_mirror": self.rand_mirror,
                "resize": self.resize, "scale": self.scale,
                "max_rotate_angle": self.max_rotate_angle,
                "rotate": self.rotate, "rotate_list": self.rotate_list,
                "max_shear_ratio": self.max_shear_ratio, "pad": self.pad,
                "fill_value": self.fill_value, "random_h": self.random_h,
                "random_s": self.random_s, "random_l": self.random_l,
                "mean": self.mean, "label_width": self.label_width}

    def derive_rng(self, epoch: int, idx: int) -> np.random.RandomState:
        """Per-(epoch, record) augmentation RNG: decode order (and pool
        size) cannot change the augmentation a record receives."""
        mixed = (self.seed * 0x9E3779B1 + epoch * 1000003
                 + idx * 2654435761) & 0xFFFFFFFF
        return np.random.RandomState(mixed)

    def _affine_augment(self, img: np.ndarray,
                        rng: np.random.RandomState) -> np.ndarray:
        """Rotation + shear (reference affine path,
        ``image_aug_default.cc:175-220``): forward matrix
        [[a - s*b, b + s*a], [-b, a]] about the image center, constant
        ``fill_value`` border. PIL wants the inverse (output->input) map."""
        angle = 0.0
        if self.max_rotate_angle > 0:
            angle = float(rng.randint(-self.max_rotate_angle,
                                      self.max_rotate_angle + 1))
        if self.rotate > 0:
            angle = float(self.rotate)
        if self.rotate_list:
            angle = float(self.rotate_list[
                rng.randint(len(self.rotate_list))])
        shear = 0.0
        if self.max_shear_ratio > 0:
            shear = (rng.rand() * 2 - 1) * self.max_shear_ratio
        if angle == 0.0 and shear == 0.0:
            return img
        from PIL import Image
        import math

        h, w = img.shape[:2]
        th = math.radians(angle)
        a, b = math.cos(th), math.sin(th)
        fwd = np.array([[a - shear * b, b + shear * a], [-b, a]])
        inv = np.linalg.inv(fwd)
        # PIL's AFFINE applies coefficients in the corner frame (pixel
        # index + 0.5), so the image center there is exactly (w/2, h/2)
        cx, cy = w / 2.0, h / 2.0
        coeffs = (inv[0, 0], inv[0, 1], cx - inv[0, 0] * cx - inv[0, 1] * cy,
                  inv[1, 0], inv[1, 1], cy - inv[1, 0] * cx - inv[1, 1] * cy)
        color = img.shape[2] == 3
        pim = Image.fromarray(np.clip(img, 0, 255).astype(np.uint8).squeeze())
        fill = (self.fill_value,) * 3 if color else self.fill_value
        pim = pim.transform((w, h), Image.AFFINE, coeffs,
                            resample=Image.BILINEAR, fillcolor=fill)
        out = np.asarray(pim).astype(np.float32)
        return out if out.ndim == 3 else out[:, :, None]

    def _hsl_augment(self, img: np.ndarray,
                     rng: np.random.RandomState) -> np.ndarray:
        """HSL color jitter (``image_aug_default.cc:269-300``): uniform
        offsets in [-random_h, random_h] etc.; H clamps to [0, 180] and
        S/L to [0, 255] exactly like the reference's limit[] table
        (OpenCV HLS units)."""
        if not (self.random_h or self.random_s or self.random_l) \
                or img.shape[2] != 3:
            return img
        dh = (rng.rand() * 2 - 1) * self.random_h
        ds = (rng.rand() * 2 - 1) * self.random_s
        dl = (rng.rand() * 2 - 1) * self.random_l
        eps = 1e-12
        rgb = np.clip(img, 0, 255) / 255.0
        r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
        maxc = np.maximum(np.maximum(r, g), b)
        minc = np.minimum(np.minimum(r, g), b)
        l = (maxc + minc) / 2.0
        delta = maxc - minc
        s = np.where(delta < eps, 0.0,
                     np.where(l <= 0.5, delta / (maxc + minc + eps),
                              delta / (2.0 - maxc - minc + eps)))
        rc = (maxc - r) / (delta + eps)
        gc = (maxc - g) / (delta + eps)
        bc = (maxc - b) / (delta + eps)
        hue = np.where(maxc == r, bc - gc,
                       np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
        hue = np.where(delta < eps, 0.0, (hue / 6.0) % 1.0)
        # jitter in OpenCV units, then back to [0, 1]
        hue = np.clip(hue * 180.0 + dh, 0.0, 180.0) / 180.0
        l = np.clip(l * 255.0 + dl, 0.0, 255.0) / 255.0
        s = np.clip(s * 255.0 + ds, 0.0, 255.0) / 255.0
        m2 = np.where(l <= 0.5, l * (1.0 + s), l + s - l * s)
        m1 = 2.0 * l - m2

        def channel(h12):
            h12 = h12 % 1.0
            return np.where(
                h12 < 1 / 6, m1 + (m2 - m1) * h12 * 6.0,
                np.where(h12 < 0.5, m2,
                         np.where(h12 < 2 / 3,
                                  m1 + (m2 - m1) * (2 / 3 - h12) * 6.0, m1)))

        out = np.stack([channel(hue + 1 / 3), channel(hue),
                        channel(hue - 1 / 3)], axis=-1)
        return (out * 255.0).astype(np.float32)

    def decode(self, rec: bytes,
               rng: np.random.RandomState) -> Tuple[np.ndarray, np.ndarray]:
        """One record -> (CHW float32 image in raw-pixel units, label).
        Mean/scale are applied vectorized at batch level
        (:meth:`normalize_inplace`)."""
        from . import recordio as rio

        _tel.inc("io.decoded_records")
        header, img = rio.unpack_img(
            rec, iscolor=1 if self.data_shape[0] == 3 else 0)
        label = np.asarray(header.label, dtype=np.float32)
        img = img.astype(np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        c, h, w = self.data_shape
        if self.resize > 0:
            from PIL import Image

            short = min(img.shape[0], img.shape[1])
            ratio = self.resize / short
            nh, nw = int(round(img.shape[0] * ratio)), \
                int(round(img.shape[1] * ratio))
            img = np.asarray(Image.fromarray(img.astype(np.uint8)).resize(
                (nw, nh))).astype(np.float32)
            if img.ndim == 2:
                img = img[:, :, None]
        img = self._affine_augment(img, rng)
        if self.pad > 0:
            img = np.pad(img, ((self.pad, self.pad), (self.pad, self.pad),
                               (0, 0)), constant_values=float(self.fill_value))
        # crop to (h, w)
        ih, iw = img.shape[0], img.shape[1]
        if ih < h or iw < w:
            from PIL import Image

            img = np.asarray(Image.fromarray(img.astype(np.uint8)).resize(
                (w, h))).astype(np.float32)
            if img.ndim == 2:
                img = img[:, :, None]
            ih, iw = h, w
        if self.rand_crop:
            top = rng.randint(0, ih - h + 1)
            left = rng.randint(0, iw - w + 1)
        else:
            top, left = (ih - h) // 2, (iw - w) // 2
        img = img[top:top + h, left:left + w]
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1]
        img = self._hsl_augment(img, rng)
        return img.transpose(2, 0, 1), label  # HWC -> CHW

    def normalize_inplace(self, imgs: np.ndarray) -> np.ndarray:
        """Mean-subtract + scale a freshly stacked float32 batch in
        place — one vectorized pass beats per-image python-loop
        arithmetic for the bandwidth-bound normalize, and the same
        elementwise float32 ops run in the thread path and in workers,
        keeping both bit-identical."""
        if self.mean is not None:
            imgs -= self.mean
        if self.scale != 1.0:
            imgs *= self.scale
        return imgs


class PrefetchingIter(DataIter):
    """Background-thread pipelining (reference io.py:235 +
    ``src/io/iter_prefetcher.h``): decouples host-side batch prep from
    device compute. Uses the host ThreadedEngine-style worker thread with a
    bounded queue of ready batches.

    Lifecycle: :meth:`close` (or the context-manager form) stops and
    joins the worker thread, so an exception mid-epoch cannot leak a
    live background thread; :meth:`reset` is close + restart."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth: int = 2):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._depth = prefetch_depth
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.current_batch: Optional[DataBatch] = None
        self._start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            descs = []
            for it in self.iters:
                descs.extend(it.provide_data)
            return descs
        descs = []
        for r, it in zip(self.rename_data, self.iters):
            descs.extend(DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                         for d in it.provide_data)
        return descs

    @property
    def provide_label(self):
        if self.rename_label is None:
            descs = []
            for it in self.iters:
                descs.extend(it.provide_label)
            return descs
        descs = []
        for r, it in zip(self.rename_label, self.iters):
            descs.extend(DataDesc(r.get(d.name, d.name), d.shape, d.dtype)
                         for d in it.provide_label)
        return descs

    def _start(self):
        def _run():
            while not self._stop.is_set():
                try:
                    batches = [it.next() for it in self.iters]
                except StopIteration:
                    self._queue.put(None)
                    return
                data, label = [], []
                for b in batches:
                    data.extend(b.data)
                    label.extend(b.label)
                merged = DataBatch(data, label, batches[0].pad,
                                   batches[0].index)
                while not self._stop.is_set():
                    try:
                        self._queue.put(merged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()

    def _drain(self):
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def close(self):
        """Stop and join the producer thread, drain the queue. Safe to
        call repeatedly and from ``__del__``; after close the iterator
        reports exhaustion until :meth:`reset`."""
        th = self._thread
        if th is None:
            return
        self._thread = None
        self._stop.set()
        # a producer blocked on the bounded queue polls _stop every
        # 100ms; draining lets it exit immediately
        self._drain()
        th.join(timeout=5.0)
        if th.is_alive():
            logging.warning("PrefetchingIter.close: producer thread did "
                            "not exit within 5s; leaking the (daemon) "
                            "thread rather than hanging teardown")
        self._drain()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def reset(self):
        self.close()
        for it in self.iters:
            it.reset()
        self._stop = threading.Event()
        self._start()

    def iter_next(self):
        if self._thread is None:
            return False
        if _tel.enabled():
            # time blocked on the queue: nonzero stall means the consumer
            # outran the producer thread — the pipeline, not the device,
            # is the bottleneck
            import time

            t0 = time.perf_counter()
            batch = self._queue.get()
            _tel.observe("io.prefetch_stall_ms",
                         (time.perf_counter() - t0) * 1e3)
        else:
            batch = self._queue.get()
        if batch is None:
            return False
        self.current_batch = batch
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad

    def getindex(self):
        return self.current_batch.index


@_REG.register("ImageRecordIter")
class ImageRecordIter(DataIter):
    """Image recordio iterator with sharding + augmentation (reference
    ``src/io/iter_image_recordio.cc:109-455``). Decode via PIL; augmentation
    covers ``image_aug_default.cc:40-300``: resize, random/center crop,
    random mirror, mean subtraction, scale, rotation/shear (affine with
    ``fill_value`` border), padding, and HSL color jitter
    (``random_h/s/l``, OpenCV units: H in [0,180), S/L in [0,255])."""

    def __init__(self, path_imgrec: str, data_shape, batch_size: int,
                 path_imgidx: Optional[str] = None, label_width: int = 1,
                 shuffle: bool = False, num_parts: int = 1, part_index: int = 0,
                 mean_img: Optional[str] = None, mean_r: float = 0.0,
                 mean_g: float = 0.0, mean_b: float = 0.0, scale: float = 1.0,
                 rand_crop: bool = False, rand_mirror: bool = False,
                 resize: int = -1, round_batch: bool = True, seed: int = 0,
                 preprocess_threads: int = 4, prefetch_buffer: int = 2,
                 preprocess_mode: Optional[str] = None,
                 max_rotate_angle: int = 0, rotate: float = -1.0,
                 rotate_list=(), max_shear_ratio: float = 0.0,
                 pad: int = 0, fill_value: int = 255,
                 random_h: int = 0, random_s: int = 0, random_l: int = 0,
                 **kwargs):
        super().__init__()
        from . import recordio as rio

        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        # decode-pool parameters (reference iter_image_recordio.cc:188-196
        # decodes with an OMP pool sized by preprocess_threads; here a
        # thread pool — PIL's JPEG codec and large-array numpy ufuncs
        # release the GIL — plus futures-based batch read-ahead sized by
        # prefetch_buffer so decode overlaps device compute)
        self.preprocess_threads = max(1, int(preprocess_threads))
        self.prefetch_buffer = max(1, int(prefetch_buffer))
        # preprocess_mode="process" (or MXNET_TPU_DECODE_PROCS=N) swaps
        # the GIL-bound thread pool for io_pipeline's multiprocess decode
        # into a shared-memory batch ring; results stay bit-identical
        # because every augmentation draw is keyed by (epoch, record idx)
        env_procs = _env.get("MXNET_TPU_DECODE_PROCS")
        if preprocess_mode is None:
            preprocess_mode = "process" if env_procs > 0 else "thread"
        if preprocess_mode not in ("thread", "process"):
            raise MXNetError("preprocess_mode must be 'thread' or "
                             "'process', got %r" % (preprocess_mode,))
        self.preprocess_mode = preprocess_mode
        self._num_procs = env_procs if env_procs > 0 \
            else self.preprocess_threads
        self._proc_pipe = None
        self._pool = None
        self._inflight = {}
        self._epoch = 0
        self._aug_seed = int(seed)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.resize = resize
        self.scale = scale
        self.max_rotate_angle = max_rotate_angle
        self.rotate = rotate
        if isinstance(rotate_list, str):
            rotate_list = [v for v in rotate_list.split(",") if v.strip()]
        self.rotate_list = [int(v) for v in rotate_list]
        self.max_shear_ratio = max_shear_ratio
        self.pad = pad
        self.fill_value = fill_value
        self.random_h = random_h
        self.random_s = random_s
        self.random_l = random_l
        self.mean = None
        if mean_img is not None and os.path.isfile(mean_img):
            from . import ndarray as nd
            self.mean = list(nd.load(mean_img).values())[0].asnumpy()
        elif mean_r or mean_g or mean_b:
            self.mean = np.array([mean_r, mean_g, mean_b],
                                 dtype=np.float32).reshape(3, 1, 1)
        self._rng = np.random.RandomState(seed)
        self._path_imgrec = path_imgrec
        # load record offsets; shard by record index (InputSplit semantics)
        self._records: List[bytes] = []
        reader = rio.MXRecordIO(path_imgrec, "r")
        i = 0
        while True:
            rec = reader.read()
            if rec is None:
                break
            if i % num_parts == part_index:
                self._records.append(rec)
            i += 1
        reader.close()
        if shuffle:
            self._rng.shuffle(self._records)
        self.label_width = label_width
        self.cursor = -batch_size
        self.num_data = len(self._records)
        if self.num_data == 0:
            raise MXNetError("no records found in %s" % path_imgrec)
        if mean_img is not None and self.mean is None:
            # first use: compute the dataset mean image and cache it to
            # disk (reference iter_normalize.h computes + saves mean_img
            # the same way before training starts)
            self.mean = self._compute_mean(mean_img)
        # the decoder is the single source of truth for decode+augment;
        # its config() ships to io_pipeline workers in process mode
        self._decoder = RecordDecoder(
            data_shape=self.data_shape, seed=self._aug_seed,
            rand_crop=rand_crop, rand_mirror=rand_mirror, resize=resize,
            scale=scale, max_rotate_angle=max_rotate_angle, rotate=rotate,
            rotate_list=self.rotate_list, max_shear_ratio=max_shear_ratio,
            pad=pad, fill_value=fill_value, random_h=random_h,
            random_s=random_s, random_l=random_l, mean=self.mean,
            label_width=label_width)

    def _compute_mean(self, path: str) -> np.ndarray:
        from concurrent.futures import ThreadPoolExecutor

        from . import ndarray as nd
        from . import recordio as rio

        # deterministic, unscaled, unaugmented pass (mean lives in
        # raw-pixel units) over the FULL dataset — not just this worker's
        # shard — so every worker agrees on the mean. A dedicated clean
        # decoder replaces the old save/mutate/restore dance on self.
        dec = RecordDecoder(data_shape=self.data_shape, resize=self.resize,
                            pad=self.pad, fill_value=self.fill_value)
        workers = self._num_procs if self.preprocess_mode == "process" \
            else self.preprocess_threads
        pool = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="meandec") \
            if workers > 1 else None
        acc = np.zeros(self.data_shape, dtype=np.float64)
        count = 0

        def _decode_one(rec):
            return dec.decode(rec, np.random.RandomState(0))[0]

        reader = rio.MXRecordIO(self._path_imgrec, "r")
        try:
            chunk: List[bytes] = []

            def _flush():
                nonlocal acc, count
                imgs = pool.map(_decode_one, chunk) if pool is not None \
                    else map(_decode_one, chunk)
                # accumulate in submission order: the float64 sum is
                # bit-identical for any pool size
                for img in imgs:
                    acc += img
                    count += 1
                chunk.clear()

            while True:
                rec = reader.read()
                if rec is None:
                    break
                chunk.append(rec)
                if len(chunk) >= max(64, 8 * workers):
                    _flush()
            if chunk:
                _flush()
        finally:
            reader.close()
            if pool is not None:
                pool.shutdown()
        logging.info("computed mean image from %d records -> %s",
                     count, path)
        mean = (acc / max(count, 1)).astype(np.float32)
        # atomic publish: a killed run must not leave a torn cache file
        # that every later construction would crash loading
        tmp = "%s.tmp.%d" % (path, os.getpid())
        nd.save(tmp, {"mean_img": nd.array(mean)})
        os.replace(tmp, path)
        return mean

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self.cursor = -self.batch_size
        # augmentation draws are keyed by (epoch, record index), so each
        # epoch re-augments differently (reference parser RNG keeps
        # drawing across epochs) while staying reproducible and
        # independent of the pool size
        self._epoch += 1
        # cancel read-ahead from the old epoch so the pool doesn't burn
        # prefetch_buffer*batch_size decodes that will be discarded
        for futs in self._inflight.values():
            for f in futs:
                f.cancel()
        self._inflight.clear()
        if self._proc_pipe is not None:
            # parked ring results belong to the finished epoch; drop them
            self._proc_pipe.flush()
        self._cache_cursor = None

    def close(self):
        """Release the decode machinery: shut down worker processes and
        their shared-memory segments (process mode) and the thread pool.
        The iterator stays usable afterwards — the next batch lazily
        rebuilds whatever it needs."""
        pipe, self._proc_pipe = self._proc_pipe, None
        if pipe is not None:
            pipe.shutdown()
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        self._inflight.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    # -- decode pool -------------------------------------------------------
    def _derive_rng(self, epoch: int, idx: int) -> np.random.RandomState:
        return self._decoder.derive_rng(epoch, idx)

    def _ensure_pool(self):
        if self._pool is None and self.preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.preprocess_threads,
                thread_name_prefix="imgdec")
        return self._pool

    def _decode_at(self, epoch: int, idx: int):
        return self._decode(self._records[idx % self.num_data],
                            self._derive_rng(epoch, idx))

    def _submit(self, cursor: int):
        pool = self._pool
        if pool is None or cursor in self._inflight:
            return
        ep = self._epoch
        self._inflight[cursor] = [
            pool.submit(self._decode_at, ep, i)
            for i in range(cursor, cursor + self.batch_size)]

    def _gather(self, cursor: int):
        futs = self._inflight.pop(cursor, None)
        if futs is not None:
            return [f.result() for f in futs]
        pool = self._ensure_pool()
        idxs = range(cursor, cursor + self.batch_size)
        if pool is not None:
            ep = self._epoch
            return list(pool.map(lambda i: self._decode_at(ep, i), idxs))
        return [self._decode_at(self._epoch, i) for i in idxs]

    def _decode(self, rec: bytes,
                rng: np.random.RandomState) -> Tuple[np.ndarray, np.ndarray]:
        return self._decoder.decode(rec, rng)

    # -- multi-process pipeline (io_pipeline) ------------------------------
    def _ensure_pipe(self):
        """Lazily start the shared-memory decode pipeline; any startup
        failure falls back to in-process decode instead of raising."""
        if self.preprocess_mode != "process":
            return None
        if self._proc_pipe is None:
            from . import io_pipeline

            try:
                self._proc_pipe = io_pipeline.ProcessDecodePipeline(
                    self._records, self._decoder.config(), self.batch_size,
                    label_width=self.label_width,
                    num_workers=self._num_procs)
            except Exception as e:
                self._disable_process_mode("pipeline startup failed: %s" % e)
                return None
        return self._proc_pipe

    def _disable_process_mode(self, reason: str):
        """Degrade gracefully: drop the worker pipeline and continue on
        the in-process decode path. Never hangs the training loop."""
        logging.warning(
            "ImageRecordIter: multi-process decode disabled (%s); "
            "falling back to in-process decode", reason)
        _tel.inc("io.pipeline.fallbacks")
        pipe, self._proc_pipe = self._proc_pipe, None
        self.preprocess_mode = "thread"
        if pipe is not None:
            pipe.shutdown()


    def _decode_batch(self):
        if getattr(self, "_cache_cursor", None) == self.cursor:
            _tel.inc("io.decode_cache_hit")
            return self._cache
        pipe = self._ensure_pipe()
        if pipe is not None:
            from .io_pipeline import PipelineError

            try:
                imgs, labels = pipe.get_batch(self.cursor, self._epoch,
                                              limit=self.num_data)
            except PipelineError as e:
                # a dead worker (or wedged ring) must never hang the
                # training loop: count it, fall through to in-process
                _tel.inc("io.pipeline.worker_crashes")
                self._disable_process_mode(str(e))
            else:
                labels = np.ascontiguousarray(
                    labels[:, 0] if self.label_width == 1 else labels)
                self._cache = (imgs, labels)
                self._cache_cursor = self.cursor
                return self._cache
        results = self._gather(self.cursor)
        if self._pool is not None:
            # read-ahead: keep the pool decoding the next batches while
            # the consumer computes on this one (reference PrefetcherIter
            # + OMP parser overlap, iter_prefetcher.h)
            for k in range(1, self.prefetch_buffer + 1):
                nxt = self.cursor + k * self.batch_size
                if nxt < self.num_data:
                    self._submit(nxt)
        imgs = np.stack([r[0] for r in results])
        labels = [r[1] if self.label_width > 1 else float(r[1].ravel()[0])
                  for r in results]
        self._decoder.normalize_inplace(imgs)
        self._cache = (imgs, np.asarray(labels, dtype=np.float32))
        self._cache_cursor = self.cursor
        return self._cache

    def getdata(self):
        return [array(self._decode_batch()[0])]

    def getlabel(self):
        return [array(self._decode_batch()[1])]

    def getpad(self):
        if self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


def MXDataIter(name: str, **kwargs) -> DataIter:
    """Create a registered iterator by name (the reference's C++-backed
    iterators exposed via registry, io.py:506)."""
    cls = _REG.get(name)
    return cls(**kwargs)
