"""RecordIO file format.

TPU-native equivalent of dmlc-core recordio + ``python/mxnet/recordio.py``:
a stream of length-prefixed records with a magic marker, plus an indexed
variant for random access, and the image-record header used by
``ImageRecordIter``/``im2rec`` (label + id packed ahead of the payload).
Binary layout (little-endian): ``magic(u32) lrecord(u32) data pad-to-4``,
with the upper 3 bits of ``lrecord`` reserved for the continuation flag like
the reference.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple
from typing import Optional

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LFLAG_BITS = 29
_LENGTH_MASK = (1 << _LFLAG_BITS) - 1


class MXRecordIO:
    """Sequential record reader/writer (reference dmlc::RecordIOWriter).

    Uses the native C++ codec (``src/native/recordio.cc``) when available,
    falling back to pure Python; the on-disk format is identical.
    ``write`` returns the record's byte offset (used by the indexed
    variant)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        from ._native_lib import get_lib

        self._lib = get_lib()
        self.open()

    def open(self):
        self.writable = self.flag == "w"
        if self.flag not in ("r", "w"):
            raise MXNetError("invalid flag %s" % self.flag)
        from .filesystem import open_uri, scheme_of

        if scheme_of(self.uri) is not None:
            # URI scheme (mem://, registered s3:// etc.): the native
            # codec only reads local files, so take the python path over
            # the filesystem layer (reference: dmlc::Stream dispatch)
            self.fp = open_uri(self.uri, "wb" if self.writable else "rb")
            self._h = None
            return
        if self._lib is not None:
            if self.writable:
                self._h = self._lib.mxtpu_recio_writer_open(
                    self.uri.encode())
            else:
                self._h = self._lib.mxtpu_recio_reader_open(
                    self.uri.encode())
            if not self._h:
                raise MXNetError("cannot open %s" % self.uri)
            self.fp = None
            self._offset = 0
        else:
            self.fp = open(self.uri, "wb" if self.writable else "rb")
            self._h = None

    def close(self):
        if self._h is not None:
            if self.writable:
                self._lib.mxtpu_recio_writer_close(self._h)
            else:
                self._lib.mxtpu_recio_reader_close(self._h)
            self._h = None
        elif self.fp is not None:
            self.fp.close()
            self.fp = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes) -> int:
        if not self.writable:
            raise MXNetError("not opened for writing")
        if self._h is not None:
            import ctypes

            data = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) \
                if buf else (ctypes.c_uint8 * 1)()
            off = self._lib.mxtpu_recio_write(self._h, data, len(buf))
            if off < 0:
                raise MXNetError("write failed on %s" % self.uri)
            # keep tell() working on the native handle: next record starts
            # after the 8-byte header + payload + padding
            self._offset = off + 8 + len(buf) + (4 - len(buf) % 4) % 4
            return off
        off = self.fp.tell()
        self.fp.write(struct.pack("<II", _MAGIC, len(buf) & _LENGTH_MASK))
        self.fp.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fp.write(b"\x00" * pad)
        return off

    def tell(self) -> int:
        if self.fp is not None:
            return self.fp.tell()
        if self.writable:
            return getattr(self, "_offset", 0)
        raise MXNetError("tell() unsupported on the native read handle; "
                         "use record offsets from the writer")

    def seek(self, offset: int):
        if self._h is not None:
            self._lib.mxtpu_recio_reader_seek(self._h, offset)
        else:
            self.fp.seek(offset)

    def read(self) -> Optional[bytes]:
        if self.writable:
            raise MXNetError("not opened for reading")
        if self._h is not None:
            import ctypes

            out = ctypes.POINTER(ctypes.c_uint8)()
            n = self._lib.mxtpu_recio_read(self._h, ctypes.byref(out))
            if n == -1:
                return None
            if n == -2:
                raise MXNetError("invalid record magic in %s" % self.uri)
            return ctypes.string_at(out, n)
        header = self.fp.read(8)
        if len(header) < 8:
            return None
        magic, lrec = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic at %d" % (self.fp.tell() - 8))
        length = lrec & _LENGTH_MASK
        buf = self.fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.fp.read(pad)
        return buf

    def __enter__(self):
        return self

    def __exit__(self, *args):
        self.close()


class MXIndexedRecordIO(MXRecordIO):
    """Indexed record IO: ``.idx`` text file of ``key\\toffset`` lines
    (reference ``python/mxnet/recordio.py`` indexed variant)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        from .filesystem import exists as fs_exists, open_uri

        if not self.writable and fs_exists(idx_path):
            with open_uri(idx_path, "rb") as fin:
                for line in fin.read().decode().splitlines():
                    if not line.strip():
                        continue
                    key, off = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(off)
                    self.keys.append(key)

    def close(self):
        # commit the record stream before the index: a failing idx write
        # must not lose the records
        super().close()
        if self.writable and self.idx:
            from .filesystem import open_uri

            with open_uri(self.idx_path, "wb") as fout:
                for key in self.keys:
                    fout.write(("%s\t%d\n"
                                % (key, self.idx[key])).encode())

    def seek(self, idx):
        MXRecordIO.seek(self, self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf: bytes):
        key = self.key_type(idx)
        offset = self.write(buf)
        self.idx[key] = offset
        self.keys.append(key)


IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack an image-record header + payload (reference pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (list, tuple, np.ndarray)):
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, header.label,
                       header.id, header.id2) + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(payload[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        payload = payload[header.flag * 4:]
    return header, payload


def pack_img(header: IRHeader, img: np.ndarray, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode an image array and pack (requires PIL)."""
    import io as _io

    from PIL import Image

    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
    Image.fromarray(img.astype(np.uint8)).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor: int = -1):
    """Unpack + decode an image record -> (header, HWC uint8 array)."""
    import io as _io

    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    if iscolor == 0:
        img = img.convert("L")
    elif iscolor == 1:
        img = img.convert("RGB")
    return header, np.asarray(img)
