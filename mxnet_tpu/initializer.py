"""Weight initializers (reference ``python/mxnet/initializer.py``)."""
from __future__ import annotations

import re
from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray
from . import random as _random

__all__ = ["Initializer", "Uniform", "Normal", "Xavier", "MSRAPrelu",
           "Orthogonal", "Zero", "One", "Constant", "Load", "Mixed"]

_REG: Registry = Registry.get_registry("initializer")


class Initializer:
    """Base: dispatch by parameter name suffix, like the reference."""

    def __call__(self, name: str, arr: NDArray):
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("parameters"):
            # fused-RNN flat parameter blob (cuDNN-style)
            self._init_weight(name, arr)
        elif name.endswith("moving_mean") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("state") or name.endswith("state_cell") \
                or name.endswith("init_h") or name.endswith("init_c"):
            # RNN initial states default to zero
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        else:
            self._init_default(name, arr)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(np.prod(shape), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        raise MXNetError(
            "unknown parameter name pattern '%s'; use a Mixed initializer" % name)


@_REG.register("uniform")
class Uniform(Initializer):
    def __init__(self, scale: float = 0.07):
        self.scale = scale

    def _init_weight(self, _, arr):
        _random.uniform(-self.scale, self.scale, out=arr)


@_REG.register("normal")
class Normal(Initializer):
    def __init__(self, sigma: float = 0.01):
        self.sigma = sigma

    def _init_weight(self, _, arr):
        _random.normal(0.0, self.sigma, out=arr)


@_REG.register("xavier")
class Xavier(Initializer):
    def __init__(self, rnd_type: str = "uniform", factor_type: str = "avg",
                 magnitude: float = 3.0):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = magnitude

    def _init_weight(self, _, arr):
        shape = arr.shape
        fan_out = shape[0]
        fan_in = int(np.prod(shape[1:])) if len(shape) > 1 else shape[0]
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("invalid factor_type %s" % self.factor_type)
        scale = float(np.sqrt(self.magnitude / factor))
        if self.rnd_type == "uniform":
            _random.uniform(-scale, scale, out=arr)
        elif self.rnd_type == "gaussian":
            _random.normal(0.0, scale, out=arr)
        else:
            raise MXNetError("invalid rnd_type %s" % self.rnd_type)


@_REG.register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type: str = "avg", slope: float = 0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)


@_REG.register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale: float = 1.414, rand_type: str = "uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@_REG.register("zero")
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    def _init_default(self, _, arr):
        arr[:] = 0.0


@_REG.register("one")
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


class Constant(Initializer):
    def __init__(self, value: float):
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


class Load:
    """Initialize from a saved dict, falling back to ``default_init``
    (reference ``mx.init.Load``)."""

    def __init__(self, param, default_init: Optional[Initializer] = None,
                 verbose: bool = False):
        from . import ndarray as nd

        if isinstance(param, str):
            param = nd.load(param)
        self.param = {}
        for name, arr in param.items():
            self.param[name.replace("arg:", "").replace("aux:", "")] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name: str, arr: NDArray):
        if name in self.param:
            if self.param[name].shape != arr.shape:
                raise MXNetError("Load: shape mismatch for '%s'" % name)
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise MXNetError("Load: no init for '%s'" % name)
            self.default_init(name, arr)


class Mixed:
    """Regex-pattern-dispatched initializers (reference ``mx.init.Mixed``)."""

    def __init__(self, patterns: List[str], initializers: List[Initializer]):
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: patterns and initializers must pair up")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name: str, arr: NDArray):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Mixed: no pattern matched '%s'; add '.*'" % name)
