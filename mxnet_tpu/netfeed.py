"""netfeed: the disaggregated input pipeline — decode hosts streaming
ready device-feed batches to training hosts over :mod:`netwire`.

The same-host input plane (:mod:`mxnet_tpu.io_pipeline`) moves decoded
batches through a ``shared_memory`` ring; this module is its cross-host
sibling, the reference's data-plane role for ps-lite: a decode fleet
runs :class:`NetFeedServer` around any ``DataIter`` (typically the
PR 5 device-feed iterator: raw uint8 frames + deferred augmentation
params), and the training host runs :class:`NetFeedIter`, which speaks
the frame protocol and plugs into :class:`~mxnet_tpu.io_pipeline.
FeedScheduler` unchanged — ``io.feed_stall_ms`` stays the one signal
for "the chip starved", now measuring the network feed.

Batches cross bit-identically: every numpy payload (data, labels,
index, the ``tops``/``lefts``/``mirror`` augmentation arrays) rides as
a raw described buffer, scalar augmentation params (``mean``/``scale``/
``layout``/``crop``) ride in frame metadata, and the property test
pins equality against the in-process path array for array.

Flow control is credit-based pipelining: the client keeps
``MXNET_TPU_NETFEED_DEPTH`` ``next`` requests outstanding on ONE
connection (the server answers in arrival order, so the decode host is
always D batches ahead), and every reply carries a sequence number so
an injected ``net_reorder`` cannot shuffle epochs — the client
reassembles by seq, never by arrival. End of epoch is an explicit
``eof`` reply (never a dropped connection), ``reset`` restarts the
underlying iterator, and a decode host that stops answering fails the
epoch with a named :class:`~mxnet_tpu.netwire.WireTimeout` after
``MXNET_TPU_NETFEED_TIMEOUT_S`` instead of wedging the training loop.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import env as _env
from . import netwire as _netwire
from . import telemetry as _tel
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["NetFeedServer", "NetFeedIter", "serve_subprocess",
           "demo_feed_factory"]

_log = logging.getLogger(__name__)

#: augmentation-dict keys that are numpy arrays on the wire; everything
#: else in ``batch.aug`` must be a JSON-representable scalar/list
_AUG_ORDER = ("tops", "lefts", "mirror")


def _np(x) -> np.ndarray:
    asnumpy = getattr(x, "asnumpy", None)
    return asnumpy() if callable(asnumpy) else np.asarray(x)


def _descs_out(descs) -> List[list]:
    return [[d.name, list(d.shape), np.dtype(d.dtype).str,
             getattr(d, "layout", "NCHW")] for d in descs]


def _descs_in(raw) -> List[DataDesc]:
    return [DataDesc(name, tuple(shape), dtype=np.dtype(dt),
                     layout=layout)
            for name, shape, dt, layout in raw]


class NetFeedServer:
    """Serve one ``DataIter``'s batches as netwire frames (the decode
    host role). Ops: ``meta`` (iterator descriptors), ``next`` (one
    batch or an ``eof`` marker, stamped with an epoch sequence
    number), ``reset``, ``stop``. The base iterator is driven under a
    lock — one decode stream per server; parallelism lives inside the
    base iterator (e.g. the decode-pool pipeline), not in racing
    ``next`` calls."""

    def __init__(self, base: DataIter, host: str = "127.0.0.1",
                 port: int = 0):
        self.base = base
        from .analysis import sanitizers as _san
        self._lock = _san.maybe_instrument(threading.Lock(),
                                           "netfeed-iter")
        self._seq = 0
        self.stopped = threading.Event()
        self._wire = _netwire.WireServer(self._handle, host, port,
                                         name="netfeed")
        self.host, self.port = self._wire.host, self._wire.port

    # -- batch codec --------------------------------------------------------
    @staticmethod
    def encode_batch(batch: DataBatch, seq: int) -> Tuple[dict, list]:
        """Split one batch into (frame metadata, wire arrays): data +
        label + optional index + augmentation arrays as raw buffers,
        scalar aug params in metadata."""
        data = [_np(d) for d in (batch.data or [])]
        label = [_np(x) for x in (batch.label or [])]
        arrays = data + label
        meta: Dict[str, object] = {"seq": int(seq),
                                   "pad": int(batch.pad or 0),
                                   "nd": len(data), "nl": len(label)}
        if batch.index is not None:
            arrays.append(np.asarray(batch.index))
            meta["has_index"] = True
        aug = getattr(batch, "aug", None)
        if aug is not None:
            scalars, akeys = {}, []
            for k in _AUG_ORDER:
                if k in aug:
                    akeys.append(k)
                    arrays.append(np.asarray(aug[k]))
            for k, v in aug.items():
                if k in _AUG_ORDER:
                    continue
                if isinstance(v, np.ndarray):
                    akeys.append(k)
                    arrays.append(v)
                elif isinstance(v, tuple):
                    scalars[k] = list(v)
                elif isinstance(v, (np.floating, np.integer)):
                    scalars[k] = v.item()
                else:
                    scalars[k] = v
            meta["aug_arrays"] = akeys
            meta["aug_meta"] = scalars
        return meta, arrays

    @staticmethod
    def decode_batch(frame: "_netwire.Frame") -> DataBatch:
        """Inverse of :meth:`encode_batch`; array payloads stay numpy
        (the consumer — FeedScheduler staging or the fit loop — owns
        device placement)."""
        from . import ndarray as nd

        meta = frame.meta
        arrays = list(frame.arrays)
        noff = int(meta.get("nd", 0))
        loff = noff + int(meta.get("nl", 0))
        data = [nd.array(a) for a in arrays[:noff]]
        label = [nd.array(a) for a in arrays[noff:loff]]
        pos = loff
        index = None
        if meta.get("has_index"):
            index = np.asarray(arrays[pos])
            pos += 1
        batch = DataBatch(data, label, pad=int(meta.get("pad", 0)),
                          index=index)
        akeys = meta.get("aug_arrays")
        if akeys is not None or meta.get("aug_meta"):
            aug: Dict[str, object] = {}
            for k in (akeys or ()):
                aug[k] = np.asarray(arrays[pos])
                pos += 1
            for k, v in (meta.get("aug_meta") or {}).items():
                # crop crossed as a JSON list; the device-feed
                # consumers unpack it positionally so a tuple restores
                # the in-process shape exactly
                aug[k] = tuple(v) if isinstance(v, list) else v
            batch.aug = aug
        return batch

    # -- frame protocol -----------------------------------------------------
    def _handle(self, frame, respond):
        op = frame.op
        if op == "next":
            with self._lock:
                seq = self._seq
                self._seq += 1
                try:
                    batch = self.base.next()
                except StopIteration:
                    batch = None
            if batch is None:
                respond("batch", (), {"seq": seq, "eof": True})
                return
            # encode outside the lock: the batch is this request's own,
            # and host-syncing device arrays must not serialize the
            # next decode
            meta, arrays = self.encode_batch(batch, seq)
            _tel.inc("io.netfeed.batches_served")
            respond("batch", arrays, meta)
        elif op == "meta":
            with self._lock:
                respond("ok", (), {
                    "provide_data": _descs_out(self.base.provide_data),
                    "provide_label": _descs_out(self.base.provide_label),
                    "batch_size": int(getattr(self.base, "batch_size",
                                              0))})
        elif op == "reset":
            with self._lock:
                self.base.reset()
                self._seq = 0
            respond("ok")
        elif op == "stop":
            respond("ok")
            self.stopped.set()
        else:
            respond("err", (), {"error": "unknown netfeed op %r" % (op,)})

    def close(self):
        self._wire.close()
        close = getattr(self.base, "close", None)
        if callable(close):
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NetFeedIter(DataIter):
    """The training-host end: a ``DataIter`` over a remote
    :class:`NetFeedServer`. Keeps ``MXNET_TPU_NETFEED_DEPTH`` batch
    requests in flight on one connection and reassembles replies by
    sequence number, so the decode host's read-ahead hides the wire
    rtt; wrap it in :class:`~mxnet_tpu.io_pipeline.FeedScheduler` and
    ``io.feed_stall_ms`` proves whether the chip ever waited. The time
    ``next()`` itself blocks on the wire lands in
    ``io.netfeed_wait_ms`` — stalls the FeedScheduler's own depth then
    absorbs."""

    def __init__(self, host: str, port: int, depth: Optional[int] = None,
                 timeout_s: Optional[float] = None):
        super().__init__()
        self._client = _netwire.WireClient(host, int(port),
                                           peer="netfeed", pool=1)
        self._depth = max(1, int(_env.get("MXNET_TPU_NETFEED_DEPTH")
                                 if depth is None else depth))
        self._timeout_s = float(_env.get("MXNET_TPU_NETFEED_TIMEOUT_S")
                                if timeout_s is None else timeout_s)
        self._out: deque = deque()          # issued, unresolved waiters
        self._buf: Dict[int, object] = {}   # seq -> reply frame
        self._expected = 0
        self._done = False
        self._closed = False
        frame = self._client.call("meta", timeout_s=self._timeout_s)
        if frame.op != "ok":
            raise MXNetError("netfeed meta failed: %s"
                             % frame.meta.get("error"))
        self._provide_data = _descs_in(frame.meta["provide_data"])
        self._provide_label = _descs_in(frame.meta["provide_label"])
        self.batch_size = int(frame.meta.get("batch_size", 0))

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    # -- pipeline pump ------------------------------------------------------
    def _pump(self):
        while len(self._out) < self._depth:
            self._out.append(self._client.request("next"))

    def _collect(self, deadline: float):
        """Resolve the oldest outstanding waiter into the seq buffer."""
        if not self._out:
            raise MXNetError("netfeed protocol error: expected seq %d "
                             "but nothing is outstanding" % self._expected)
        w = self._out.popleft()
        try:
            frame = w.wait(max(0.0, deadline - time.perf_counter()))
        except _netwire.WireTimeout:
            w.cancel()
            raise _netwire.WireTimeout(
                "netfeed batch %d not served within %.1fs (decode host "
                "wedged or MXNET_TPU_NETFEED_TIMEOUT_S too tight)"
                % (self._expected, self._timeout_s))
        seq = int(frame.meta.get("seq", -1))
        self._buf[seq] = frame

    def next(self) -> DataBatch:
        if self._done:
            raise StopIteration
        self._pump()
        t0 = time.perf_counter() if _tel.enabled() else 0.0
        deadline = time.perf_counter() + self._timeout_s
        while self._expected not in self._buf:
            self._collect(deadline)
        if _tel.enabled():
            _tel.observe("io.netfeed_wait_ms",
                         (time.perf_counter() - t0) * 1e3)
        frame = self._buf.pop(self._expected)
        self._expected += 1
        if frame.meta.get("eof"):
            self._done = True
            self._drain()
            raise StopIteration
        self._pump()
        _tel.inc("io.netfeed.batches")
        return NetFeedServer.decode_batch(frame)

    def _drain(self):
        """Resolve every outstanding request (post-eof they are all
        cheap ``eof`` replies) so reset() starts from a quiet wire."""
        deadline = time.perf_counter() + self._timeout_s
        while self._out:
            try:
                self._collect(deadline)
            except (MXNetError, _netwire.WireError):
                break
        self._buf.clear()

    def reset(self):
        self._drain()
        frame = self._client.call("reset", timeout_s=self._timeout_s)
        if frame.op != "ok":
            raise MXNetError("netfeed reset failed: %s"
                             % frame.meta.get("error"))
        self._expected = 0
        self._done = False

    def iter_next(self) -> bool:
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current.data

    def getlabel(self):
        return self._current.label

    def getpad(self):
        return self._current.pad

    def getindex(self):
        return self._current.index

    def close(self, stop_server: bool = False):
        if self._closed:
            return
        self._closed = True
        self._drain()
        if stop_server:
            try:
                self._client.call("stop", timeout_s=5.0)
            except _netwire.WireError:
                pass
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# two-process plumbing
# ---------------------------------------------------------------------------

def _netfeed_main(port_conn, factory_ref: str):
    """Decode-host entry point (spawn target): build the base iterator
    from a ``"module:attr"`` factory ref, serve it, report the bound
    port, run until a ``stop`` frame."""
    from .fleet import _resolve_factory

    server = NetFeedServer(_resolve_factory(factory_ref)())
    try:
        port_conn.send(server.port)
        port_conn.close()
        while not server.stopped.wait(0.5):
            pass
    finally:
        server.close()


def serve_subprocess(factory_ref: str, start_method: str = "spawn",
                     timeout_s: float = 60.0):
    """Spawn a decode host serving ``factory_ref``'s iterator over
    loopback; returns ``(process, host, port)``. The caller stops it
    with ``NetFeedIter.close(stop_server=True)`` (or kills the
    process)."""
    import multiprocessing

    from .fleet import _resolve_factory

    _resolve_factory(factory_ref)   # fail fast in the parent
    ctx = multiprocessing.get_context(start_method or "spawn")
    port_conn, child_conn = ctx.Pipe()
    proc = ctx.Process(target=_netfeed_main,
                       args=(child_conn, factory_ref),
                       name="mxtpu-netfeed", daemon=True)
    proc.start()
    child_conn.close()
    if not port_conn.poll(timeout_s):
        port_conn.close()
        proc.join(1.0)
        raise MXNetError("netfeed decode host never reported a port")
    try:
        port = int(port_conn.recv())
    except (EOFError, OSError):
        port_conn.close()
        raise MXNetError("netfeed decode host died before reporting "
                         "a port")
    port_conn.close()
    return proc, "127.0.0.1", port


# ---------------------------------------------------------------------------
# deterministic demo feed (tests / bench)
# ---------------------------------------------------------------------------

class _DemoFeed(DataIter):
    """A seeded synthetic device-feed iterator: uint8 NHWC frames plus
    the PR 5 deferred-augmentation ``batch.aug`` contract, bit-exactly
    reproducible — run it locally and through the wire and the batches
    must match byte for byte."""

    def __init__(self, batches: int = 12, batch_size: int = 8,
                 hw: int = 16, seed: int = 7):
        super().__init__()
        self.batch_size = int(batch_size)
        self._n = int(batches)
        self._hw = int(hw)
        self._seed = int(seed)
        self._i = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._hw, self._hw, 3),
                         dtype=np.uint8, layout="NHWC")]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,),
                         dtype=np.float32, layout="N")]

    def next(self) -> DataBatch:
        from . import ndarray as nd

        if self._i >= self._n:
            raise StopIteration
        rng = np.random.RandomState(self._seed * 1000003 + self._i)
        b, s = self.batch_size, self._hw
        crop = s - 2
        data = rng.randint(0, 256, (b, s, s, 3)).astype(np.uint8)
        labels = rng.randint(0, 10, (b,)).astype(np.float32)
        batch = DataBatch([nd.array(data)], [nd.array(labels)], pad=0,
                          index=np.arange(self._i * b, (self._i + 1) * b))
        batch.aug = {"tops": rng.randint(0, 3, (b,)).astype(np.int32),
                     "lefts": rng.randint(0, 3, (b,)).astype(np.int32),
                     "mirror": rng.rand(b) < 0.5,
                     "mean": 127.5, "scale": 1.0 / 128.0,
                     "layout": "NHWC", "crop": (crop, crop)}
        self._i += 1
        return batch

    def reset(self):
        self._i = 0


def demo_feed_factory() -> DataIter:
    """Spawn-resolvable factory (``"mxnet_tpu.netfeed:demo_feed_factory"``)
    for the netfeed tests and the fleet bench's 2-process epoch."""
    return _DemoFeed()
