"""netwire: zero-copy socket transport for the fleet and the input plane.

The reference framework ran every cross-host byte through ps-lite
(``ps::Postoffice``, PAPER.md layers 0/7): one length-prefixed binary
transport under both the parameter plane and the data plane. This
module is that role rebuilt for the reproduction: a single framing
layer under :class:`mxnet_tpu.fleet.SocketReplica` (inference fleets
across hosts) and :mod:`mxnet_tpu.netfeed` (decode hosts streaming
ready batches to training hosts), replacing the same-host-only pickled
``multiprocessing.Pipe`` and ``shared_memory`` ring primitives.

Frame layout (all integers network byte order)::

    offset 0   magic      2s   b"MW"
    offset 2   version    u8   WIRE_VERSION of the sender
    offset 3   flags      u8   reserved (0)
    offset 4   header_len u16  total fixed-header bytes, >= 18
    offset 6   meta_len   u32  JSON metadata length
    offset 10  body_len   u64  concatenated array payload length
    offset 18  ..header_len    appended header fields (skew tail)
    [meta_len bytes]           UTF-8 JSON: op, mid, array descriptors,
                               dtrace context, request envelope
    [body_len bytes]           raw array payloads, back to back

Version skew rides the PR 15 appended-field idiom at both levels: a
newer sender may append trailing fixed-header bytes (``header_len``
tells an old reader how much to skip) and new JSON keys (an old reader
indexes only what it knows); an old sender's shorter frames parse
unchanged. Both directions are pinned by test.

**No pickle on the hot path.** Arrays cross as raw bytes described by
``{"d": dtype.str, "s": shape}`` descriptors in the metadata; the
sender hands ``sendmsg`` one ``memoryview`` per array (zero copies —
scatter/gather out of the numpy buffers) and the receiver rebuilds
views over a single recv buffer with ``np.frombuffer``. Object dtypes
are refused at encode time: anything that would need pickle does not
belong on this wire. Both length fields are checked against
``MXNET_TPU_WIRE_MAX_FRAME_MB`` *before* allocation (a hostile or
corrupt prefix must not OOM the reader), and every short read raises a
named :class:`WireError` saying what was being read and how many bytes
were missing — the ``_read_exact`` hardening idiom from the checkpoint
loader (:func:`mxnet_tpu.ndarray.load_from_stream`).

:class:`WireClient` keeps ``MXNET_TPU_WIRE_POOL`` persistent
connections per peer and multiplexes requests by message id, so one
slow response never head-of-line-blocks the pool. Per-attempt
deadlines come from the caller (the router's remaining-budget envelope,
PR 14) and are enforced on the waiter. TCP backpressure is surfaced
rather than hidden: a send that blocks longer than
``MXNET_TPU_WIRE_BACKPRESSURE_MS`` counts ``wire.backpressure_stalls``
and lands in the ``wire.backpressure_stall_ms`` histogram, and
``wire.pending`` gauges in-flight depth — inflated rtt under
backpressure is exactly what feeds the router's p95 hedge trigger and
breaker failure accounting, so a congested peer sheds load the same
way a slow one does.

:class:`WireServer` is the PR 7 lifecycle discipline applied to a
listener: a 0.2 s-poll accept loop, per-connection reader threads on a
0.5 s idle poll (so ``close()`` joins everything with bounded
timeouts), replies sent on the receiving connection under a per-socket
send lock.

The network fault plane (:mod:`mxnet_tpu.faults`: ``net_drop``,
``net_partition``, ``net_reorder``, ``net_slow``) injects *inside*
``WireConn.send_frame`` — below every consumer — so the fleet bench
proves goodput survives loss, resets, and reordering with the same
seeded, counted machinery as the process-fault drills.

Telemetry (all under ``wire.``): ``bytes_tx``/``bytes_rx``,
``frames_tx``/``frames_rx``, ``rtt_ms``, ``reconnects``,
``backpressure_stalls``/``backpressure_stall_ms``, ``pending``.
``trace_report --view wire`` renders the per-peer rollup the fleet
bench embeds in FLEET_bench.json.
"""
from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtrace as _dtrace
from . import env as _env
from . import faults as _faults
from . import telemetry as _tel
from .base import MXNetError

__all__ = ["WIRE_VERSION", "WireError", "WireTimeout", "WirePeerLost",
           "Frame", "encode_frame", "decode_frame", "read_frame",
           "WireConn", "WireServer", "WireClient"]

_log = logging.getLogger(__name__)

WIRE_VERSION = 1

_MAGIC = b"MW"
#: magic(2s) version(B) flags(B) header_len(H) meta_len(I) body_len(Q)
_PREFIX = struct.Struct("!2sBBHIQ")


class WireError(MXNetError):
    """Framing/transport failure: bad magic, truncated read, refused
    length, or a broken socket mid-frame."""


class WireTimeout(WireError):
    """A waiter's per-attempt deadline expired before the reply."""


class WirePeerLost(WireError):
    """The connection died with the request in flight (reset,
    partition, or peer crash) — the caller cannot know whether the
    peer served it."""


class Frame:
    """One decoded frame: ``op``/``mid`` routing fields, the metadata
    dict (request envelope, array descriptors already consumed), the
    decoded numpy arrays (views over the recv buffer), and the dtrace
    context the sender attached (or None)."""

    __slots__ = ("op", "mid", "meta", "arrays", "tctx")

    def __init__(self, op: str, mid: str, meta: dict,
                 arrays: List[np.ndarray], tctx: Optional[dict]):
        self.op = op
        self.mid = mid
        self.meta = meta
        self.arrays = arrays
        self.tctx = tctx

    def __repr__(self):
        return ("Frame(op=%r, mid=%r, arrays=%d, meta_keys=%s)"
                % (self.op, self.mid, len(self.arrays),
                   sorted(self.meta)))


def _max_frame_bytes() -> int:
    return int(_env.get("MXNET_TPU_WIRE_MAX_FRAME_MB")) << 20


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def encode_frame(op: str, mid: str, arrays: Sequence = (),
                 meta: Optional[dict] = None,
                 trace_ctx: Optional[dict] = None,
                 _header_tail: bytes = b"") -> List[memoryview]:
    """Encode one frame as a buffer list ready for ``sendmsg``: element
    0 is the header+metadata bytes, each following element is one
    array's raw buffer (a zero-copy ``memoryview`` of the numpy data).

    ``_header_tail`` is the skew test hook: bytes appended to the fixed
    header, exactly what a future WIRE_VERSION would do. Readers of
    this version skip them via ``header_len``.
    """
    descs = []
    bufs: List[memoryview] = [memoryview(b"")]   # slot 0 patched below
    body_len = 0
    for a in arrays:
        arr = np.asarray(a)
        if not arr.flags.c_contiguous:
            # 0-d arrays are always contiguous, so this never promotes
            # a scalar to 1-d the way unconditional ascontiguousarray
            # would — shapes round-trip bit-identically
            arr = np.ascontiguousarray(arr)
        if arr.dtype.hasobject:
            raise WireError(
                "refusing to encode dtype %s for op %r: object arrays "
                "would need pickle, which never rides this wire"
                % (arr.dtype, op))
        descs.append({"d": arr.dtype.str, "s": list(arr.shape)})
        mv = memoryview(arr).cast("B") if arr.nbytes else memoryview(b"")
        bufs.append(mv)
        body_len += arr.nbytes
    obj = {"op": str(op), "mid": str(mid), "arrays": descs}
    if meta:
        obj["m"] = meta
    if trace_ctx is not None:
        obj["tctx"] = trace_ctx
    meta_bytes = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    cap = _max_frame_bytes()
    if body_len > cap or len(meta_bytes) > cap:
        raise WireError(
            "frame for op %r exceeds MXNET_TPU_WIRE_MAX_FRAME_MB: "
            "body=%d meta=%d cap=%d bytes" % (op, body_len,
                                              len(meta_bytes), cap))
    header = _PREFIX.pack(_MAGIC, WIRE_VERSION, 0,
                          _PREFIX.size + len(_header_tail),
                          len(meta_bytes), body_len) + _header_tail
    bufs[0] = memoryview(header + meta_bytes)
    return bufs


def read_frame(read_exact: Callable[[int, str], memoryview],
               what: str = "<wire>") -> Frame:
    """Decode one frame from a ``read_exact(n, what) -> buffer``
    callable (socket- or bytes-backed). Raises :class:`WireError` on
    bad magic, refused lengths, truncation, or descriptor/body length
    mismatch. Trailing fixed-header bytes from a newer peer are read
    and ignored; unknown metadata keys are ignored by construction.
    """
    head = bytes(read_exact(_PREFIX.size, what + " frame header"))
    magic, version, _flags, header_len, meta_len, body_len = \
        _PREFIX.unpack(head)
    if magic != _MAGIC:
        raise WireError("bad frame magic %r from %s (expected %r) — "
                        "peer is not speaking the netwire protocol"
                        % (magic, what, _MAGIC))
    if header_len < _PREFIX.size:
        raise WireError("frame header_len %d from %s is shorter than "
                        "the fixed prefix (%d)"
                        % (header_len, what, _PREFIX.size))
    if header_len > _PREFIX.size:
        # appended-field skew: a newer sender's extra header bytes —
        # read and drop, exactly like old routers ignoring envelope
        # tail fields
        read_exact(header_len - _PREFIX.size, what + " header tail")
    cap = _max_frame_bytes()
    for field, n in (("meta", meta_len), ("body", body_len)):
        if n > cap:
            raise WireError(
                "refusing frame from %s: %s length field %d exceeds "
                "MXNET_TPU_WIRE_MAX_FRAME_MB cap of %d bytes (v%d "
                "frame; corrupt or hostile prefix?)"
                % (what, field, n, cap, version))
    try:
        obj = json.loads(bytes(read_exact(meta_len, what + " metadata"))
                         .decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError("frame metadata from %s is not valid JSON: %s"
                        % (what, e))
    body = read_exact(body_len, what + " payload")
    mv = memoryview(body).cast("B") if body_len else memoryview(b"")
    arrays, off = [], 0
    for d in obj.get("arrays", ()):
        dt = np.dtype(d["d"])
        shape = tuple(int(x) for x in d["s"])
        nb = int(dt.itemsize * int(np.prod(shape, dtype=np.int64)))
        if off + nb > body_len:
            raise WireError(
                "frame from %s: array descriptors claim %d+ bytes but "
                "the body holds %d" % (what, off + nb, body_len))
        arrays.append(np.frombuffer(mv[off:off + nb], dtype=dt)
                      .reshape(shape))
        off += nb
    if off != body_len:
        raise WireError("frame from %s: body has %d bytes but the "
                        "descriptors consumed %d" % (what, body_len, off))
    return Frame(obj.get("op", ""), obj.get("mid", ""),
                 obj.get("m") or {}, arrays, obj.get("tctx"))


def decode_frame(data) -> Frame:
    """Decode a frame from a contiguous buffer (tests, property
    checks). The same path sockets use, minus the I/O."""
    mv = memoryview(data)
    pos = [0]

    def read_exact(n: int, what: str) -> memoryview:
        if pos[0] + n > len(mv):
            raise WireError(
                "truncated %s: wanted %d bytes, only %d available"
                % (what, n, len(mv) - pos[0]))
        out = mv[pos[0]:pos[0] + n]
        pos[0] += n
        return out

    return read_frame(read_exact)


def _sock_read_exact(sock: socket.socket, n: int, what: str,
                     first_poll: bool = False) -> memoryview:
    """recv_into a preallocated buffer until ``n`` bytes arrived.
    EOF or a mid-frame stall raises a named :class:`WireError`;
    ``first_poll`` lets an idle-poll timeout on the FIRST byte
    propagate as ``socket.timeout`` (the reader loop's stop-check
    tick) while any later timeout means a peer parked mid-frame."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            k = sock.recv_into(view[got:], min(n - got, 1 << 20))
        except socket.timeout:
            if first_poll and got == 0:
                raise
            raise WireError(
                "wire read of %s stalled mid-frame with %d of %d bytes "
                "(peer wedged or framing mismatch)" % (what, got, n))
        except OSError as e:
            # includes EBADF from a concurrent close() — the reader
            # loop treats any WireError as "connection gone"
            raise WireError("wire read of %s failed after %d of %d "
                            "bytes: %s" % (what, got, n, e))
        if k == 0:
            raise WireError("truncated %s: peer closed after %d of %d "
                            "bytes" % (what, got, n))
        got += k
    return view


# ---------------------------------------------------------------------------
# one connection
# ---------------------------------------------------------------------------

class WireConn:
    """One framed socket: locked scatter/gather sends (with the fault
    hooks and backpressure accounting), unlocked single-reader
    receives, and per-connection byte/frame counters."""

    def __init__(self, sock: socket.socket, peer: str = "?"):
        self.peer = peer
        self._sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        from .analysis import sanitizers as _san
        self._slock = _san.maybe_instrument(threading.Lock(),
                                            "wire-send-%s" % peer)
        self._held: Optional[List[memoryview]] = None   # net_reorder
        self._closed = False
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.frames_tx = 0
        self.frames_rx = 0
        self.stalls = 0

    # -- send ---------------------------------------------------------------
    def send_frame(self, bufs: List[memoryview]) -> int:
        """Write one encoded frame (fault plane applied); returns bytes
        written (0 when the frame was dropped/held by a fault). Raises
        :class:`WireError` on a broken socket."""
        if _faults.fires("net_slow"):
            time.sleep(_faults.slow_ms() / 1e3)
        if _faults.fires("net_partition"):
            _log.warning("net_partition injected: hard-closing %s",
                         self.peer)
            self.close()
            raise WirePeerLost("connection to %s lost (injected "
                               "partition)" % self.peer)
        if _faults.fires("net_drop"):
            return 0
        queue = [bufs]
        with self._slock:
            if _faults.fires("net_reorder") and self._held is None:
                # hold this frame back; it rides behind the NEXT one
                self._held = bufs
                return 0
            if self._held is not None:
                queue.append(self._held)   # swapped order on the wire
                self._held = None
            sent = 0
            t0 = time.perf_counter()
            try:
                for frame_bufs in queue:
                    sent += self._write(frame_bufs)
                    self.frames_tx += 1
            except OSError as e:
                self._closed = True
                raise WireError("send to %s failed: %s" % (self.peer, e))
            self.bytes_tx += sent
        stall_ms = (time.perf_counter() - t0) * 1e3
        if stall_ms >= float(_env.get("MXNET_TPU_WIRE_BACKPRESSURE_MS")):
            self.stalls += 1
            _tel.inc("wire.backpressure_stalls")
            _tel.observe("wire.backpressure_stall_ms", stall_ms)
        _tel.inc("wire.frames_tx")
        _tel.inc("wire.bytes_tx", sent)
        return sent

    def _write(self, bufs: List[memoryview]) -> int:
        total = sum(len(b) for b in bufs)
        sent = self._sock.sendmsg(bufs)
        if sent < total:
            # a short scatter/gather write: flatten the remainder and
            # drain it with plain send() (bounded by SO_SNDTIMEO-free
            # blocking writes; the stall shows up in backpressure)
            rest = b"".join(bytes(b) for b in bufs)[sent:]
            while rest:
                k = self._sock.send(rest)
                rest = rest[k:]
            sent = total
        return sent

    # -- receive ------------------------------------------------------------
    def recv_frame(self, idle_ok: bool = False) -> Optional[Frame]:
        """Read one frame; ``idle_ok`` turns an idle-poll timeout
        before any byte arrived into ``None`` (the reader loop's
        stop-check tick)."""
        try:
            frame = read_frame(
                lambda n, what, _first=[True]: self._read(n, what, _first),
                what="peer %s" % self.peer)
        except socket.timeout:
            if idle_ok:
                return None
            raise WireError("idle read from %s timed out" % self.peer)
        self.frames_rx += 1
        _tel.inc("wire.frames_rx")
        return frame

    def _read(self, n: int, what: str, first: List[bool]) -> memoryview:
        out = _sock_read_exact(self._sock, n, what,
                               first_poll=first[0])
        first[0] = False
        self.bytes_rx += n
        _tel.inc("wire.bytes_rx", n)
        return out

    def close(self):
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class WireServer:
    """Threaded frame server: ``handler(frame, respond)`` runs on the
    per-connection reader thread; ``respond(op, arrays=(), meta=None)``
    replies on the same connection with the request's mid (so a pooled
    client demultiplexes it back to the right waiter). Lifecycle is the
    ps.py discipline: polled accept loop, polled per-conn readers,
    bounded joins in ``close()``."""

    def __init__(self, handler: Callable, host: str = "127.0.0.1",
                 port: int = 0, name: str = "wire"):
        self._handler = handler
        self._name = name
        self._stop = threading.Event()
        self._closed = False
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[WireConn] = []
        from .analysis import sanitizers as _san
        self._lock = _san.maybe_instrument(threading.Lock(),
                                           "wire-server-%s" % name)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name="mxtpu-wire-accept-%s" % name, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.2)
        except OSError:
            return   # close() won the race to the listening socket
        while not self._stop.is_set():
            try:
                raw, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # 0.5s idle poll: a parked reader wakes to check _stop, so
            # close() can join it with a bounded timeout
            raw.settimeout(0.5)
            conn = WireConn(raw, peer="%s:%d" % addr[:2])
            th = threading.Thread(
                target=self._serve, args=(conn,),
                name="mxtpu-wire-conn-%s" % self._name, daemon=True)
            with self._lock:
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()] + [th]
                self._conns = [c for c in self._conns
                               if not c.closed] + [conn]
            th.start()

    def _serve(self, conn: WireConn):
        try:
            while not self._stop.is_set():
                try:
                    frame = conn.recv_frame(idle_ok=True)
                except WireError:
                    return    # peer hung up / garbage framing: drop conn
                if frame is None:
                    continue   # idle poll tick: re-check _stop

                def respond(op: str, arrays: Sequence = (),
                            meta: Optional[dict] = None,
                            _mid=frame.mid):
                    conn.send_frame(encode_frame(op, _mid, arrays, meta))

                try:
                    self._handler(frame, respond)
                except WireError:
                    return    # reply path broke: drop the connection
                except Exception as e:   # noqa: BLE001 (report, don't die)
                    try:
                        respond("err", meta={
                            "error": "%s: %s" % (type(e).__name__, e)})
                    except WireError:
                        return
        finally:
            conn.close()

    def close(self):
        """Signal stop, close the listener, join accept + conn threads
        with bounded timeouts (they poll ``_stop``). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=5.0)
        with self._lock:
            threads = list(self._conn_threads)
            conns = list(self._conns)
            self._conn_threads = []
            self._conns = []
        for c in conns:
            c.close()
        stragglers = 0
        for th in threads:
            th.join(timeout=2.0)
            stragglers += th.is_alive()
        if stragglers or self._accept_thread.is_alive():
            _log.warning("WireServer(%s).close: %d thread(s) alive after "
                         "bounded join; leaking daemon thread(s) rather "
                         "than hanging teardown", self._name, stragglers)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# pooled client
# ---------------------------------------------------------------------------

class _Waiter:
    """Reply waiter for one mid (the fleet ``_PendingWaiter`` shape,
    with wire-taxonomy errors)."""

    __slots__ = ("_done", "_frame", "_error", "t0", "_on_cancel")

    def __init__(self):
        self._done = threading.Event()
        self._frame: Optional[Frame] = None
        self._error: Optional[BaseException] = None
        self.t0 = time.perf_counter()
        self._on_cancel: Optional[Callable[[], None]] = None

    def resolve(self, frame: Frame):
        self._frame = frame
        self._done.set()

    def fail(self, err: BaseException):
        self._error = err
        self._done.set()

    def wait(self, timeout_s: float) -> Frame:
        if not self._done.wait(timeout_s):
            raise WireTimeout("wire reply still pending after %.3fs"
                              % timeout_s)
        if self._error is not None:
            raise self._error
        return self._frame

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self):
        """Forget the pending mid (a timed-out attempt the router
        abandoned, or a fault-dropped frame whose reply will never
        come) so the pending table cannot grow under chaos."""
        cb, self._on_cancel = self._on_cancel, None
        if cb is not None:
            cb()


class _PooledConn:
    """One pool slot: a lazily-(re)connected WireConn plus its reader
    thread and pending-mid table."""

    def __init__(self, client: "WireClient", idx: int):
        self._client = client
        self._idx = idx
        from .analysis import sanitizers as _san
        self._lock = _san.maybe_instrument(
            threading.Lock(), "wire-client-%s-%d" % (client.peer, idx))
        self._conn: Optional[WireConn] = None
        self._reader: Optional[threading.Thread] = None
        self._pending: Dict[str, _Waiter] = {}
        self._ever_connected = False

    def _ensure_conn(self) -> WireConn:
        # caller holds self._lock
        if self._conn is not None and not self._conn.closed:
            return self._conn
        timeout_s = float(
            _env.get("MXNET_TPU_WIRE_CONNECT_TIMEOUT_MS")) / 1e3
        try:
            raw = socket.create_connection(
                (self._client.host, self._client.port), timeout=timeout_s)
        except OSError as e:
            raise WirePeerLost("cannot connect to %s:%d (%s)"
                               % (self._client.host, self._client.port, e))
        raw.settimeout(0.5)
        self._conn = WireConn(raw, peer="%s:%d" % (self._client.host,
                                                   self._client.port))
        if self._ever_connected:
            self._client._note_reconnect()
        self._ever_connected = True
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._conn,),
            name="mxtpu-wire-reader-%s-%d" % (self._client.peer,
                                              self._idx),
            daemon=True)
        self._reader.start()
        return self._conn

    def _forget(self, mid: str):
        with self._lock:
            self._pending.pop(mid, None)

    def request(self, bufs: List[memoryview], mid: str) -> _Waiter:
        w = _Waiter()
        w._on_cancel = lambda: self._forget(mid)
        with self._lock:
            try:
                conn = self._ensure_conn()
            except WireError:
                raise
            self._pending[mid] = w
        try:
            conn.send_frame(bufs)
        except WireError as e:
            with self._lock:
                self._pending.pop(mid, None)
            self._fail_pending(conn)
            raise WirePeerLost(str(e))
        return w

    def _read_loop(self, conn: WireConn):
        client = self._client
        while not client._stop.is_set() and not conn.closed:
            try:
                frame = conn.recv_frame(idle_ok=True)
            except WireError:
                break
            if frame is None:
                continue
            # a traced reply carries the peer's harvested spans: merge
            # BEFORE resolving the waiter (the root may finish right
            # after), same ordering as the fleet pipe reader
            payload = frame.meta.get("dtrace")
            if payload:
                trc = _dtrace._TRACER
                if trc is not None:
                    trc.absorb(payload)
            with self._lock:
                w = self._pending.pop(frame.mid, None)
            if w is not None:
                _tel.observe("wire.rtt_ms",
                             (time.perf_counter() - w.t0) * 1e3)
                client._note_rtt((time.perf_counter() - w.t0) * 1e3)
                w.resolve(frame)
        self._fail_pending(conn)

    def _fail_pending(self, conn: WireConn):
        conn.close()
        with self._lock:
            if self._conn is conn:
                self._conn = None
            pending = list(self._pending.values())
            self._pending.clear()
        for w in pending:
            w.fail(WirePeerLost("connection to %s lost mid-request"
                                % self._client.peer))

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def counters(self) -> Tuple[int, int, int, int, int]:
        with self._lock:
            c = self._conn
            if c is None:
                return (0, 0, 0, 0, 0)
            return (c.frames_tx, c.frames_rx, c.bytes_tx, c.bytes_rx,
                    c.stalls)

    def close(self):
        with self._lock:
            conn, self._conn = self._conn, None
            reader = self._reader
        if conn is not None:
            conn.close()
        if reader is not None:
            reader.join(timeout=2.0)
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for w in pending:
            w.fail(WirePeerLost("client for %s closed"
                                % self._client.peer))


class WireClient:
    """Pooled, reconnecting, mid-multiplexed client for one peer.

    ``request(op, arrays, meta, timeout_s)`` round-robins over
    ``MXNET_TPU_WIRE_POOL`` persistent connections and returns a waiter
    whose ``wait`` enforces the caller's per-attempt deadline. A dead
    connection fails its in-flight waiters with :class:`WirePeerLost`
    and reconnects on the next request (counted in
    ``wire.reconnects``); the retry decision belongs to the caller
    (the router already owns retry/hedge budgets).
    """

    def __init__(self, host: str, port: int, peer: Optional[str] = None,
                 pool: Optional[int] = None):
        self.host = host
        self.port = int(port)
        self.peer = peer or "%s:%d" % (host, port)
        n = int(_env.get("MXNET_TPU_WIRE_POOL") if pool is None else pool)
        self._stop = threading.Event()
        from .analysis import sanitizers as _san
        self._stats_lock = _san.maybe_instrument(
            threading.Lock(), "wire-stats-%s" % self.peer)
        self._rr = 0
        self._reconnects = 0
        self._rtts: List[float] = []
        self._conns = [_PooledConn(self, i) for i in range(max(1, n))]
        self._closed = False

    # -- bookkeeping --------------------------------------------------------
    def _note_reconnect(self):
        with self._stats_lock:
            self._reconnects += 1
        _tel.inc("wire.reconnects")

    def _note_rtt(self, ms: float):
        with self._stats_lock:
            self._rtts.append(ms)
            if len(self._rtts) > 4096:
                del self._rtts[:2048]

    # -- request path -------------------------------------------------------
    def request(self, op: str, arrays: Sequence = (),
                meta: Optional[dict] = None,
                trace_ctx: Optional[dict] = None) -> _Waiter:
        """Send one request; returns the waiter. Tries every pool slot
        once before giving up with :class:`WirePeerLost`."""
        if self._closed:
            raise WireError("WireClient for %s is closed" % self.peer)
        mid = uuid.uuid4().hex
        bufs = encode_frame(op, mid, arrays, meta, trace_ctx)
        last: Optional[BaseException] = None
        for _ in range(len(self._conns)):
            with self._stats_lock:
                slot = self._conns[self._rr % len(self._conns)]
                self._rr += 1
            try:
                w = slot.request(bufs, mid)
            except WirePeerLost as e:
                last = e
                continue
            _tel.set_gauge("wire.pending", self.pending_count())
            return w
        raise WirePeerLost("no usable connection to %s: %s"
                           % (self.peer, last))

    def call(self, op: str, arrays: Sequence = (),
             meta: Optional[dict] = None, timeout_s: float = 5.0,
             trace_ctx: Optional[dict] = None) -> Frame:
        """Synchronous convenience: request + wait. The reply frame's
        ``op`` is the peer's verdict ("ok"/"err"/...); callers own the
        taxonomy."""
        return self.request(op, arrays, meta, trace_ctx).wait(timeout_s)

    def pending_count(self) -> int:
        return sum(c.pending_count() for c in self._conns)

    def alive(self) -> bool:
        return not self._closed

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        """Per-peer rollup for the fleet bench / ``--view wire``:
        frames, bytes, rtt mean/p99, reconnects, backpressure stalls."""
        ftx = frx = btx = brx = stalls = 0
        for c in self._conns:
            a, b, c_, d, e = c.counters()
            ftx += a
            frx += b
            btx += c_
            brx += d
            stalls += e
        with self._stats_lock:
            rtts = sorted(self._rtts)
            reconnects = self._reconnects
        out = {"peer": self.peer, "pool": len(self._conns),
               "frames_tx": ftx, "frames_rx": frx,
               "bytes_tx": btx, "bytes_rx": brx,
               "reconnects": reconnects,
               "backpressure_stalls": stalls,
               "pending": self.pending_count()}
        if rtts:
            out["rtt_ms"] = {
                "count": len(rtts),
                "mean": round(sum(rtts) / len(rtts), 3),
                "p50": round(rtts[len(rtts) // 2], 3),
                "p99": round(rtts[min(len(rtts) - 1,
                                      int(0.99 * len(rtts)))], 3)}
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for c in self._conns:
            c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
