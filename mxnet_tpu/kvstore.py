"""KVStore: key-value parameter synchronization.

TPU-native re-design of the reference's KVStore tier
(``include/mxnet/kvstore.h``, ``src/kvstore/``):

* ``local`` / ``local_allreduce_cpu`` / ``local_update_cpu`` — single-process
  store; push reduces a list of per-device grads, pull broadcasts
  (reference ``kvstore_local.h``).
* ``device`` / ``tpu_sync`` — the reduce runs as one fused jax computation
  across the participating devices; on real hardware XLA lowers it to an
  ICI all-reduce. This replaces both the reference's ``CommDevice``
  GPU-P2P reduce (``comm.h:186-346``) and the ps-lite parameter-server
  tier: with ``pjit`` data parallelism the all-reduce happens *inside* the
  training step, and KVStore keeps the push/pull API for explicit use.
* ``dist_sync`` — multi-host via ``jax.distributed`` process groups.
  On a single host it degrades to ``local`` with rank 0 / size 1.
* ``dist_async`` — real asynchronous parameter server
  (``KVStoreDistAsync`` over ``parallel/ps.py``): per-push server-side
  optimizer updates with no cross-worker aggregation, the reference's
  async architecture (``kvstore_dist_server.h:199-207``) brought back
  as a host-side control plane (async semantics have no collective
  analogue).
"""
from __future__ import annotations

import pickle
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import telemetry as _tel
from .base import MXNetError
from .ndarray import NDArray, zeros

__all__ = ["KVStore", "create"]


def _nbytes(arr: NDArray) -> int:
    try:
        return int(arr.size) * np.dtype(arr.dtype).itemsize
    except Exception:
        return 0


_TREE_SUM = None


def _tree_sum(bufs):
    """One jitted balanced tree sum over a list of same-shaped arrays.
    The list length is static per trace, so jax caches one executable
    per (fan-in, shape, dtype) — a single dispatch regardless of
    fan-in, vs n-1 eager adds for the pairwise loop."""
    global _TREE_SUM
    if _TREE_SUM is None:
        def tree_sum(xs):
            while len(xs) > 1:
                half, odd = divmod(len(xs), 2)
                paired = [xs[2 * i] + xs[2 * i + 1] for i in range(half)]
                if odd:
                    paired.append(xs[-1])
                xs = paired
            return xs[0]

        from . import xprof as _xprof

        _TREE_SUM = _xprof.jit(tree_sum, site="kvstore.reduce",
                               arg_names=("grads",))
    return _TREE_SUM(list(bufs))


def _key_list(key):
    if isinstance(key, (int, str)):
        return [key], True
    return list(key), False


def _val_list(value, nkeys):
    """Normalize to list-of-lists: per key, a list of per-device values."""
    if isinstance(value, NDArray):
        return [[value]]
    if not isinstance(value, (list, tuple)):
        raise MXNetError("invalid kvstore value type %s" % type(value))
    if all(isinstance(v, NDArray) for v in value):
        if nkeys == 1:
            return [list(value)]
        if len(value) != nkeys:
            raise MXNetError("value count must match key count")
        return [[v] for v in value]
    return [list(v) if isinstance(v, (list, tuple)) else [v] for v in value]


class KVStore:
    """Single-process store; subclassed for device/dist flavors."""

    def __init__(self, kv_type: str = "local"):
        self._type = kv_type
        self._store: Dict = {}
        self._updater: Optional[Callable] = None
        self._optimizer = None

    # -- properties --------------------------------------------------------
    @property
    def type(self) -> str:
        return self._type

    @property
    def fused_step_compatible(self) -> bool:
        """True when the fused train step (MXNET_TPU_FUSED_STEP=1) may
        subsume this store's gradient aggregation: local/device stores
        and ``tpu_sync`` reduce inside the jitted step (GSPMD), so no
        explicit push/pull round remains. ``dist_*`` stores move bytes
        through a server between backward and update — they must keep
        the unfused three-phase loop."""
        return "dist" not in self._type

    @property
    def rank(self) -> int:
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self) -> int:
        try:
            import jax

            return jax.process_count()
        except Exception:
            return 1

    # -- core API ----------------------------------------------------------
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %s already initialized" % k)
            v = vlist[0]
            self._store[k] = v.copyto(v.context)

    def _reduce(self, vlist: List[NDArray]) -> NDArray:
        """Sum a list of per-device arrays (reference Comm::Reduce,
        comm.h): gather the inputs onto one device and sum in ONE jitted
        balanced tree reduction. The old host loop dispatched n-1 eager
        adds, each a separate device round-trip, so bandwidth.py's
        kvstore tier measured dispatch latency instead of reduction
        bandwidth. jax caches the traced fn per (fan-in, shape, dtype).
        This host-driven path is only used for explicit kvstore
        push/pull of unsharded arrays; the measured data-parallel
        training path does NOT go through here — executor_group shards
        the batch over a mesh and the in-step GSPMD all-reduce rides
        ICI (parallel/sharding.py)."""
        import jax

        if len(vlist) == 1:
            return vlist[0]
        target = self._store_device(vlist)
        bufs = [jax.device_put(v._data, target) for v in vlist]
        if _tel.enabled():
            _tel.inc("kvstore.fused_reduce")
        return NDArray(_tree_sum(bufs), ctx=vlist[0].context)

    def _store_device(self, vlist):
        return vlist[0]._data.devices().pop()

    def push(self, key, value, priority: int = 0):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            merged = self._reduce(vlist)
            if _tel.enabled():
                _tel.inc("kvstore.push")
                _tel.inc("kvstore.push_bytes",
                         sum(_nbytes(v) for v in vlist))
            if self._updater is not None:
                self._updater(k, merged, self._store[k])
            else:
                self._store[k][:] = merged

    def pull(self, key, out=None, priority: int = 0):
        if out is None:
            raise MXNetError("pull requires out")
        keys, single = _key_list(key)
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            src = self._store[k]
            if _tel.enabled():
                _tel.inc("kvstore.pull")
                _tel.inc("kvstore.pull_bytes",
                         _nbytes(src) * len(olist))
            for o in olist:
                src.copyto(o)

    # -- optimizer integration (reference set_optimizer -> serialized
    # optimizer controller, kvstore.py:231-258) ----------------------------
    def set_updater(self, updater: Callable):
        self._updater = updater

    def set_optimizer(self, optimizer):
        from .optimizer import get_updater

        if self.num_workers > 1:
            # multi-host: each process runs the same updater on its replica
            # of the (all-reduced) grads — consistent by construction.
            try:
                pickle.dumps(optimizer)
            except Exception:
                raise MXNetError("optimizer must be serializable for dist kvstore")
        self._optimizer = optimizer
        self.set_updater(get_updater(optimizer))

    # -- dist controls -----------------------------------------------------
    def barrier(self):
        if self.num_workers > 1:
            import jax

            # cross-host rendezvous via a tiny collective
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")

    def send_command_to_servers(self, head: int, body: str):
        # no server tier on TPU; optimizer runs worker-side. When a
        # controller was installed (MXKVStoreRunServer / the reference's
        # serialized-optimizer command channel) dispatch to it in-process.
        controller = getattr(self, "_controller", None)
        if controller is not None:
            controller(int(head), body)

    def num_dead_node(self, node_id: int = 0) -> int:
        """Count of failed peers (reference ``KVStore::get_num_dead_node``,
        ``kvstore_dist.h:149-158``). The jax.distributed runtime either
        has every process healthy or the job has already failed, so a
        reachable store always reports 0; recovery is checkpoint-based
        (docs/distributed.md)."""
        return 0

    def set_barrier_before_exit(self, do_barrier: bool = True):
        """Reference ``barrier_before_exit`` control (``c_api.cc:1295``):
        when set, interpreter exit waits for all workers. Registered via
        atexit for deterministic timing (a __del__ barrier could fire
        mid-run on GC, or never at interpreter teardown)."""
        import atexit

        if do_barrier and not getattr(self, "_exit_barrier", False):
            self._exit_barrier = True
            atexit.register(self._exit_barrier_hook)
        elif not do_barrier and getattr(self, "_exit_barrier", False):
            self._exit_barrier = False
            atexit.unregister(self._exit_barrier_hook)

    def _exit_barrier_hook(self):
        if getattr(self, "_exit_barrier", False):
            try:
                self.barrier()
            except Exception:
                pass

    def save_optimizer_states(self, fname: str):
        if self._optimizer is None or self._updater is None:
            raise MXNetError("no optimizer set")
        # crash-safe: tmp + fsync + os.replace, never a torn state file
        from .checkpoint import atomic_write_bytes
        atomic_write_bytes(fname, self._updater.get_states())

    def load_optimizer_states(self, fname: str):
        if self._updater is None:
            raise MXNetError("no optimizer set")
        with open(fname, "rb") as f:
            blob = f.read()
        try:
            self._updater.set_states(blob)
        except Exception as e:
            raise MXNetError(
                "invalid optimizer-states file %s: %s (partial/torn "
                "write?)" % (fname, e))


class KVStoreDist(KVStore):
    """Multi-process synchronous store (reference ``kvstore_dist.h`` +
    server tier): push reduces locally then all-reduces across worker
    processes via jax collectives; every worker runs the updater on the
    identical reduced gradient, so weights stay consistent without a
    server (the reference's server-side optimizer becomes a replicated
    worker-side update). init broadcasts rank-0 values (reference
    ``kvstore_dist.h:58-76``). The async tier is the separate
    ``KVStoreDistAsync`` below."""

    def __init__(self, kv_type: str = "dist_sync"):
        super().__init__(kv_type)
        from .parallel import distributed as dist

        dist.init_distributed()
        self._dist = dist

    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                raise MXNetError("key %s already initialized" % k)
            v = vlist[0]
            synced = self._dist.broadcast_np(v.asnumpy())
            arr = v.copyto(v.context)
            arr[:] = synced
            self._store[k] = arr

    def _reduce(self, vlist):
        from .ndarray import array as nd_array

        local = super()._reduce(vlist)
        if self.num_workers <= 1:
            return local
        reduced = self._dist.all_reduce_np(local.asnumpy())
        return nd_array(reduced, ctx=local.context)

    @property
    def fused_step_compatible(self) -> bool:
        """A single-process ``dist_sync`` world has no cross-process
        hop: its reduce IS the local device reduce, which the fused
        step's in-jit GSPMD exchange subsumes exactly like
        ``device_sync``. With real workers the host ``all_reduce_np``
        round (process_allgather + numpy sum) survives between
        dispatches, and the classic loop must keep it."""
        return self.num_workers <= 1

    @property
    def in_jit_gradient_exchange(self) -> bool:
        """Single-process ``dist_sync`` rides the device_sync in-jit
        exchange path by default (same contract: batch sharded over the
        mesh's data axes, gradients pinned to the kvstore reduce spec
        inside the one donated dispatch)."""
        return self.num_workers <= 1

    @property
    def fused_fallback(self):
        """(reason, detail) naming the surviving host path when the
        fused step cannot subsume this store — telemetry then counts
        ``step.fused_fallback.dist_host_exchange`` instead of a generic
        dist bucket."""
        if self.num_workers <= 1:
            return None
        return ("dist_host_exchange",
                "dist_sync with %d workers exchanges gradients "
                "host-side (all_reduce_np: process_allgather + numpy "
                "sum) between dispatches; the in-jit GSPMD exchange "
                "only spans the local mesh" % self.num_workers)

    def grad_reduce_sharding(self, mesh, param_sharding):
        """Reduce spec for the in-jit exchange (single-process world):
        identical to :meth:`DeviceSyncKVStore.grad_reduce_sharding`."""
        return param_sharding

    def barrier(self):
        self._dist.barrier()


class KVStoreDistAsync(KVStore):
    """Real asynchronous parameter server (reference
    ``kvstore_dist_server.h:199-207`` async mode): the server applies
    each worker's push IMMEDIATELY with the server-side optimizer — no
    aggregation, no per-step cross-worker barrier — and ``pull``
    returns whatever the weights are right now. Workers therefore run
    at their own pace on possibly-stale weights (Hogwild-style), the
    defining trade of the reference's ``dist_async``.

    The control plane is host-side TCP (``parallel/ps.py``), NOT XLA
    collectives: async semantics have no collective analogue, which is
    exactly why round-2 left this tier synchronous. Rank 0 hosts the
    server thread; every rank is a client. Rank/size come from the
    launcher env, so no jax.distributed coordination is needed at all."""

    def __init__(self, kv_type: str = "dist_async"):
        super().__init__(kv_type)
        import os

        from .parallel import ps

        self._rank = int(os.environ.get("MXTPU_WORKER_RANK", "0") or 0)
        self._size = int(os.environ.get("MXTPU_NUM_WORKERS", "1") or 1)
        host, port = ps.ps_address()
        self._server = None
        if self._rank == 0:
            try:
                self._server = ps.ParameterServer(host, port, self._size)
            except OSError:
                # the address is already served: a dedicated
                # DMLC_ROLE=server process (mxnet_tpu/kvstore_server.py,
                # the reference launch contract) owns the store — run
                # as a pure client like every other rank
                self._server = None
        self._client = ps.PSClient(host, port)
        self._client.call("hello", self._rank)

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def num_workers(self) -> int:
        return self._size

    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            self._client.call("init", self._rank, k,
                              vlist[0].asnumpy())
        # all ranks wait until the authoritative init landed, then sync
        # THE CALLER'S arrays from the server so every rank starts from
        # rank-0's values (reference rank-0 init + barrier,
        # kvstore_dist.h:58-76)
        self.barrier()
        from .ndarray import array as nd_array

        for k, vlist in zip(keys, vals):
            synced = nd_array(self._client.call("pull", k))
            for v in vlist:
                synced.copyto(v)

    def set_optimizer(self, optimizer):
        """COLLECTIVE: every rank must call this (the reference's
        ``kvstore.set_optimizer`` barriers the same way — calling it on
        rank 0 only deadlocks). Rank 0 alone ships the PICKLED
        optimizer to run server-side, once per push
        (``_send_command_to_servers``); the barrier keeps later ranks
        from pushing before it lands."""
        self._optimizer = optimizer
        if self._rank == 0:
            self._client.call("set_optimizer", pickle.dumps(optimizer))
        self.barrier()

    def push(self, key, value, priority: int = 0):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            merged = self._reduce(vlist)     # local-device reduce only
            if _tel.enabled():
                _tel.inc("kvstore.push")
                _tel.inc("kvstore.push_bytes", _nbytes(merged))
            self._client.call("push", k, merged.asnumpy())

    def pull(self, key, out=None, priority: int = 0):
        if out is None:
            raise MXNetError("pull requires out")
        keys, _ = _key_list(key)
        outs = _val_list(out, len(keys))
        from .ndarray import array as nd_array

        for k, olist in zip(keys, outs):
            cur = self._client.call("pull", k)
            src = nd_array(cur)
            if _tel.enabled():
                _tel.inc("kvstore.pull")
                _tel.inc("kvstore.pull_bytes", _nbytes(src) * len(olist))
            for o in olist:
                src.copyto(o)

    @property
    def fused_step_compatible(self) -> bool:
        return False

    @property
    def fused_fallback(self):
        """Async push/pull is host-side TCP by construction (Hogwild
        staleness has no collective analogue) — name the path precisely
        in the fallback telemetry."""
        return ("dist_async_host",
                "dist_async pushes/pulls through the host TCP "
                "parameter server (parallel/ps.py); asynchronous "
                "staleness semantics have no in-jit collective "
                "analogue")

    def barrier(self):
        self._client.call("barrier")

    def num_dead_node(self, node_id: int = 0) -> int:
        """Ranks that joined the async group and then lost every
        connection (reference ``KVStore::get_num_dead_node``); the
        supervisor's restart-from-checkpoint signal for this tier."""
        return int(self._client.call("num_dead"))

    def close(self):
        try:
            # graceful leave first — closing without it reads as a crash
            # to the server's dead-node accounting
            self._client.call("bye", self._rank)
        except (MXNetError, OSError, ConnectionError):
            pass
        # rank 0 stops the server whether it self-hosted OR a dedicated
        # DMLC_ROLE=server process owns it — otherwise an external
        # server would block in run() forever after the job ends
        if self._rank == 0:
            try:
                self._client.call("stop")
            except (MXNetError, OSError, ConnectionError):
                pass   # server already gone; still close our side
        if self._server is not None:
            self._server.close()
        self._client.close()


class TPUSyncKVStore(KVStore):
    """``tpu_sync`` / ``device``: reduce across device-resident shards with
    a single fused computation; the transfer rides ICI on real hardware."""

    def _reduce(self, vlist):
        import jax

        if len(vlist) == 1:
            return vlist[0]
        # stack-free tree add on the first value's device; XLA turns the
        # cross-device adds into collective transfers
        return super()._reduce(vlist)


class DeviceSyncKVStore(TPUSyncKVStore):
    """``device_sync``: multi-device single-process data parallelism with
    the gradient exchange INSIDE the donated fused jit. The store keeps
    the push/pull API (jitted tree-sum reduce) for explicit use, but its
    training-path contract is different: the module shards the batch
    over the executor group's mesh data axes (``dp``, and ``fsdp`` on a
    multi-axis mesh), and the fused step pins each vjp gradient to the
    sharding :meth:`grad_reduce_sharding` returns — GSPMD lowers that
    to the matching collective between backward and update (mean-psum
    all-reduce for a replicated param, ZeRO reduce-scatter for an
    fsdp-sharded one), one exchange per step, zero extra dispatches.
    This is the TPU-native answer to the reference's ps-lite push/pull
    round: bytes move on ICI inside the step instead of host-side
    between dispatches."""

    def __init__(self, kv_type: str = "device_sync"):
        super().__init__(kv_type)

    @property
    def fused_step_compatible(self) -> bool:
        return True

    @property
    def in_jit_gradient_exchange(self) -> bool:
        """Marker consulted by ``make_fused_step``: this store asks for
        the fused path by default (no MXNET_TPU_FUSED_STEP opt-in) and
        for the in-jit gradient constraint."""
        return True

    def grad_reduce_sharding(self, mesh, param_sharding):
        """The fsdp-aware reduce spec: the exchanged gradient lands on
        its PARAM's sharding. For a replicated param GSPMD emits the
        mean-psum all-reduce over every data axis; for an fsdp-sharded
        param it emits a reduce-scatter (sum over all devices, each
        keeping only the shard its param/opt-state slice needs) — the
        ZeRO exchange, chosen per-param with no new dispatch. Future
        axes (tp/pp/ep) widen this mapping here, not in fused_step."""
        return param_sharding


def create(name: str = "local") -> KVStore:
    """Factory (reference ``src/kvstore/kvstore.cc:17-45`` string-typed
    creation: any name containing 'device' -> device comm, 'dist' ->
    distributed, else local)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    lname = name.lower()
    if lname == "device_sync":
        kv = DeviceSyncKVStore(lname)
    elif "tpu" in lname or "device" in lname:
        kv = TPUSyncKVStore(lname)
    elif "async" in lname:
        kv = KVStoreDistAsync(lname)
    elif "dist" in lname:
        kv = KVStoreDist(lname)
    elif lname in ("local", "local_update_cpu", "local_allreduce_cpu"):
        kv = KVStore(lname)
    else:
        raise MXNetError("unknown kvstore type %s" % name)
    if _tel.enabled():
        # label exported metrics with this worker's rank so dist_async
        # runs are distinguishable per-process on one scrape dashboard
        from . import tracing as _tracing

        try:
            _tracing.set_worker_rank(kv.rank)
        except Exception:
            pass
    return kv
