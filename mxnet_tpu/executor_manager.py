"""Legacy data-parallel executor manager
(reference ``python/mxnet/executor_manager.py``): kept for API parity with
old training scripts; internally delegates to the TPU-native
DataParallelExecutorGroup (mesh-sharded single executor).
"""
from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np

from .base import MXNetError
from .context import Context
from .io import DataDesc

__all__ = ["_split_input_slice", "DataParallelExecutorManager"]


def _split_input_slice(batch_size: int, work_load_list: List[float]):
    """Split batch_size into slices proportional to work_load_list
    (reference executor_manager.py:14-46)."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("invalid work_load_list")
    num = len(work_load_list)
    parts = [int(round(batch_size * w / total)) for w in work_load_list]
    # fix rounding drift
    diff = batch_size - sum(parts)
    parts[-1] += diff
    slices = []
    begin = 0
    for p in parts:
        end = min(begin + p, batch_size)
        if begin >= end:
            raise MXNetError("too many slices; batch size too small")
        slices.append(slice(begin, end))
        begin = end
    return slices


class DataParallelExecutorManager:
    """reference executor_manager.py:264; wraps the mesh-sharded group."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        from .module.executor_group import DataParallelExecutorGroup

        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, list) else [ctx]
        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = [d.name for d in train_data.provide_data]
        label_names = [d.name for d in train_data.provide_label]
        self.param_names = param_names or [
            n for n in self.arg_names if n not in data_names + label_names]
        self.execgrp = DataParallelExecutorGroup(
            symbol, self.ctx, work_load_list,
            train_data.provide_data, train_data.provide_label,
            self.param_names, for_training=True, inputs_need_grad=False)

    @property
    def param_arrays(self):
        ex = self.execgrp.executor
        return [[ex.arg_dict[n]] for n in self.param_names
                if n in ex.arg_dict]

    @property
    def grad_arrays(self):
        ex = self.execgrp.executor
        return [[ex.grad_dict[n]] for n in self.param_names
                if n in ex.grad_dict]

    @property
    def aux_arrays(self):
        ex = self.execgrp.executor
        return [[a] for a in ex.aux_arrays]

    def install_monitor(self, monitor):
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        self.execgrp.get_params(arg_params, aux_params)

    def load_data_batch(self, data_batch):
        self.execgrp.load_data_batch(data_batch)

    def forward(self, is_train=False):
        self.execgrp.executor.forward(is_train=is_train)

    def backward(self):
        self.execgrp.executor.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)

    @property
    def curr_execgrp(self):
        """reference executor_manager.py:327: the group serving the
        current bucket; one group here (no bucketing at this layer)."""
        return self.execgrp

    def get_outputs(self):
        """Merged outputs of the last forward (reference collects and
        concatenates per-device outputs; the mesh-sharded executor
        already holds the full batch)."""
        return self.execgrp.get_outputs()
