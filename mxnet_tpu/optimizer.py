"""Optimizers (reference ``python/mxnet/optimizer.py``).

The update math runs as jitted jax functions over the underlying arrays —
one fused XLA kernel per (optimizer, shape) — while keeping the reference's
imperative ``update(index, weight, grad, state)`` interface, per-parameter
lr/wd multipliers (symbol attrs ``__lr_mult__``/``__wd_mult__``),
``rescale_grad`` and clipping semantics.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from .base import MXNetError, Registry
from .ndarray import NDArray, zeros
from .lr_scheduler import LRScheduler

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "Test", "create", "get_updater", "Updater"]

_REG: Registry = Registry.get_registry("optimizer")
def register(name_or_cls=None, override: bool = False):
    """Register an optimizer. Supports both the reference's bare-class
    decorator form (``@mx.optimizer.register`` — name = class name
    lowercased, speechSGD-style user optimizers) and the named form
    (``@register("sgd")``)."""
    if isinstance(name_or_cls, type):
        return _REG.register(override=True)(name_or_cls)
    return _REG.register(name_or_cls, override=override)



def _zeros_like_state(weight: NDArray) -> NDArray:
    """Optimizer state matching the weight's dtype AND device sharding —
    params may be replicated over a device mesh (executor_group), and the
    update math must stay colocated."""
    import jax
    import jax.numpy as jnp

    data = jax.device_put(jnp.zeros(weight.shape, dtype=weight.dtype),
                          weight._data.sharding)
    return NDArray(data, ctx=weight.context)


class Optimizer:
    """Base optimizer (reference ``optimizer.py`` ``Optimizer``)."""

    def __init__(self, rescale_grad: float = 1.0, param_idx2name=None,
                 wd: float = 0.0, clip_gradient: Optional[float] = None,
                 learning_rate: float = 0.01,
                 lr_scheduler: Optional[LRScheduler] = None,
                 sym=None, begin_num_update: int = 0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym = sym
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}
        if sym is not None:
            attrs = sym.attr_dict()
            for name in sym.list_arguments():
                if name in attrs:
                    if "__lr_mult__" in attrs[name]:
                        self.lr_mult[name] = float(attrs[name]["__lr_mult__"])
                    if "__wd_mult__" in attrs[name]:
                        self.wd_mult[name] = float(attrs[name]["__wd_mult__"])

    @staticmethod
    def create_optimizer(name: str, **kwargs) -> "Optimizer":
        cls = _REG.get(name)
        return cls(**kwargs)

    def create_state(self, index: int, weight: NDArray):
        return None

    def update(self, index: int, weight: NDArray, grad: NDArray, state):
        raise NotImplementedError

    def set_lr_mult(self, args_lr_mult: Dict[str, float]):
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[str, float]):
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index: int):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index: int) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, str(index))
        return lr * self.lr_mult.get(name, 1.0)

    def _get_wd(self, index: int) -> float:
        name = self.idx2name.get(index, str(index))
        wd = self.wd * self.wd_mult.get(name, 1.0)
        # bias/gamma/beta conventionally get no weight decay unless overridden
        return wd

    def _preprocess(self, grad):
        import jax.numpy as jnp

        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g


@register("sgd")
class SGD(Optimizer):
    """SGD with momentum (reference optimizer.py:234)."""

    def __init__(self, momentum: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mom = self.momentum
        opt = self

        def _do():
            g = opt._preprocess(grad._data) + wd * weight._data
            if state is None:
                weight._data = weight._data - lr * g
            else:
                state._data = mom * state._data - lr * g
                weight._data = weight._data + state._data
        from .engine import get_engine
        muts = [weight._var] if state is None else [weight._var, state._var]
        get_engine().push(_do, const_vars=[grad._var], mutable_vars=muts)


@register("ccsgd")
class ccSGD(SGD):
    """Alias of SGD kept for reference-script compatibility (the
    reference's C++-side ccSGD, optimizer.py:426)."""


@register("nag")
class NAG(SGD):
    """Nesterov accelerated gradient (reference optimizer.py:313)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mom = self.momentum
        opt = self

        def _do():
            g = opt._preprocess(grad._data) + wd * weight._data
            if state is None:
                weight._data = weight._data - lr * g
            else:
                state._data = mom * state._data + g
                weight._data = weight._data - lr * (g + mom * state._data)
        from .engine import get_engine
        muts = [weight._var] if state is None else [weight._var, state._var]
        get_engine().push(_do, const_vars=[grad._var], mutable_vars=muts)


@register("sgld")
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:361)."""

    def update(self, index, weight, grad, state):
        import jax

        from . import random as _random

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        opt = self

        def _do():
            g = opt._preprocess(grad._data) + wd * weight._data
            noise = jax.random.normal(_random.next_key(), weight.shape,
                                      dtype=weight._data.dtype)
            weight._data = weight._data - lr / 2 * g \
                + math.sqrt(lr) * noise
        from .engine import get_engine
        get_engine().push(_do, const_vars=[grad._var], mutable_vars=[weight._var])


@register("adam")
class Adam(Optimizer):
    """Adam (reference optimizer.py:504)."""

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mean, var = state
        opt = self

        def _do():
            g = opt._preprocess(grad._data) + wd * weight._data
            mean._data = opt.beta1 * mean._data + (1 - opt.beta1) * g
            var._data = opt.beta2 * var._data + (1 - opt.beta2) * g * g
            coef1 = 1.0 - opt.beta1 ** t
            coef2 = 1.0 - opt.beta2 ** t
            step_lr = lr * math.sqrt(coef2) / coef1
            weight._data = weight._data - step_lr * mean._data / \
                (jnp.sqrt(var._data) + opt.epsilon)
        from .engine import get_engine
        get_engine().push(_do, const_vars=[grad._var],
                          mutable_vars=[weight._var, mean._var, var._var])


@register("adagrad")
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:605)."""

    def __init__(self, eps: float = 1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        opt = self

        def _do():
            g = opt._preprocess(grad._data)
            state._data = state._data + g * g
            weight._data = weight._data - lr * (
                g / jnp.sqrt(state._data + opt.float_stable_eps)
                + wd * weight._data)
        from .engine import get_engine
        get_engine().push(_do, const_vars=[grad._var],
                          mutable_vars=[weight._var, state._var])


@register("rmsprop")
class RMSProp(Optimizer):
    """RMSProp (Tieleman & Hinton variant used by the reference,
    optimizer.py:654: running E[g^2], E[g], and momentum delta)."""

    def __init__(self, learning_rate: float = 0.002, gamma1: float = 0.95,
                 gamma2: float = 0.9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2

    def create_state(self, index, weight):
        return (_zeros_like_state(weight),   # n
                _zeros_like_state(weight),   # g
                _zeros_like_state(weight))   # delta

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        n, g_state, delta = state
        opt = self

        def _do():
            g = opt._preprocess(grad._data) + wd * weight._data
            n._data = (1 - opt.gamma1) * g * g + opt.gamma1 * n._data
            g_state._data = (1 - opt.gamma1) * g + opt.gamma1 * g_state._data
            delta._data = opt.gamma2 * delta._data - lr * g / jnp.sqrt(
                n._data - g_state._data * g_state._data + 1e-4)
            weight._data = weight._data + delta._data
        from .engine import get_engine
        get_engine().push(_do, const_vars=[grad._var],
                          mutable_vars=[weight._var, n._var, g_state._var,
                                        delta._var])


@register("adadelta")
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:728)."""

    def __init__(self, rho: float = 0.90, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        opt = self

        def _do():
            g = opt._preprocess(grad._data)
            acc_g._data = opt.rho * acc_g._data + (1 - opt.rho) * g * g
            cur_delta = jnp.sqrt(acc_delta._data + opt.epsilon) / \
                jnp.sqrt(acc_g._data + opt.epsilon) * g
            acc_delta._data = opt.rho * acc_delta._data + \
                (1 - opt.rho) * cur_delta * cur_delta
            weight._data = weight._data - cur_delta - wd * weight._data
        from .engine import get_engine
        get_engine().push(_do, const_vars=[grad._var],
                          mutable_vars=[weight._var, acc_g._var, acc_delta._var])


@register("test")
class Test(Optimizer):
    """Trivial optimizer for tests (reference optimizer.py:782)."""

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def create(name: str, **kwargs) -> Optimizer:
    return Optimizer.create_optimizer(name, **kwargs)


def _states_to_numpy(obj):
    """NDArray states -> numpy for pickling (NDArray holds engine vars with
    thread locks and device buffers, neither of which pickles)."""
    if isinstance(obj, NDArray):
        return obj.asnumpy()
    if isinstance(obj, tuple):
        return tuple(_states_to_numpy(o) for o in obj)
    if isinstance(obj, list):
        return [_states_to_numpy(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _states_to_numpy(v) for k, v in obj.items()}
    return obj


def _states_from_numpy(obj):
    import numpy as _np

    from .ndarray import array as _array

    if isinstance(obj, _np.ndarray):
        return _array(obj, dtype=obj.dtype)
    if isinstance(obj, tuple):
        return tuple(_states_from_numpy(o) for o in obj)
    if isinstance(obj, list):
        return [_states_from_numpy(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _states_from_numpy(v) for k, v in obj.items()}
    return obj


class Updater:
    """Closure bundling an optimizer with per-index states (reference
    ``get_updater``, optimizer.py:816). States serialize via
    get_states/set_states (numpy form) for checkpointing."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, Any] = {}

    def __call__(self, index: int, grad: NDArray, weight: NDArray):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def get_states(self):
        import pickle

        return pickle.dumps(_states_to_numpy(self.states))

    def set_states(self, states_bytes):
        import pickle

        self.states = _states_from_numpy(pickle.loads(states_bytes))


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
