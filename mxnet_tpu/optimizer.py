"""Optimizers (reference ``python/mxnet/optimizer.py``).

The update math runs as jitted jax functions over the underlying arrays —
one fused XLA kernel per (optimizer, shape) — while keeping the reference's
imperative ``update(index, weight, grad, state)`` interface, per-parameter
lr/wd multipliers (symbol attrs ``__lr_mult__``/``__wd_mult__``),
``rescale_grad`` and clipping semantics.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

from . import telemetry as _tel
from .base import MXNetError, Registry
from .ndarray import NDArray, zeros
from .lr_scheduler import LRScheduler

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "Adam", "AdaGrad", "RMSProp",
           "AdaDelta", "Test", "create", "get_updater", "Updater"]

_REG: Registry = Registry.get_registry("optimizer")
def register(name_or_cls=None, override: bool = False):
    """Register an optimizer. Supports both the reference's bare-class
    decorator form (``@mx.optimizer.register`` — name = class name
    lowercased, speechSGD-style user optimizers) and the named form
    (``@register("sgd")``)."""
    if isinstance(name_or_cls, type):
        return _REG.register(override=True)(name_or_cls)
    return _REG.register(name_or_cls, override=override)



# ---------------------------------------------------------------------------
# Fused, donated update kernels.
#
# Each optimizer's math is a pure function (weight, grad, states, scalars) ->
# (new_weight, new_states), jitted once per (kind, structure) with the weight
# and state buffers DONATED: XLA writes the new values into the old buffers,
# so training holds ONE copy of params + optimizer state in HBM instead of a
# transient two (the reference gets this from in-place C++/CUDA kernels,
# src/operator/optimizer_op-inl.h; buffer donation is the XLA-idiomatic
# form of in-place). Hyperparameters ride in one traced f32 vector, so an
# LRScheduler changing lr every step reuses the same compiled kernel, and
# the vector is cast to the weight dtype inside the kernel to preserve the
# weak-type promotion the eager form had (bf16 weights stay bf16).
# ---------------------------------------------------------------------------

_JIT_UPDATES: Dict[tuple, Any] = {}


_DONATION_WARNED = False


def _donation_ok() -> bool:
    """Donate only under engines that run host closures inline (XLAEngine /
    NaiveEngine, the defaults). A threaded engine may interleave a direct
    ``_data`` read between the donating dispatch and the write-back, and
    donation turns that stale read into a deleted-buffer error."""
    from . import env as _env
    from .engine import NaiveEngine, XLAEngine, get_engine

    if not _env.get("MXNET_TPU_DONATE"):
        return False
    # allowlist, not a not-ThreadedEngine check: native or third-party
    # engines that run closures on worker threads must stay excluded too
    if type(get_engine()) in (XLAEngine, NaiveEngine):
        return True
    global _DONATION_WARNED
    if not _DONATION_WARNED:
        _DONATION_WARNED = True
        import logging

        logging.getLogger(__name__).warning(
            "buffer donation disabled: engine %s runs closures off-thread, "
            "so in-place param/state updates are unsafe. Training holds a "
            "transient SECOND copy of params + optimizer state in HBM. Use "
            "MXNET_ENGINE_TYPE=XLAEngine (or NaiveEngine) to restore "
            "donation.", type(get_engine()).__name__)
    return False


def _update_math(kind: str, n_states: int, clipped: bool):
    """Pure update math. Scalar layout: ``s[0]`` = rescale_grad, then the
    kind-specific hyperparameters, then (when ``clipped``) the clip bound
    as ``s[-1]``."""
    import jax
    import jax.numpy as jnp

    def pre(g, s):
        g = g * s[0]
        if clipped:
            g = jnp.clip(g, -s[-1], s[-1])
        return g

    if kind in ("sgd", "nag"):
        nag = kind == "nag"

        def fn(w, g, states, s):
            s = s.astype(w.dtype)
            lr, wd, mom = s[1], s[2], s[3]
            g = pre(g, s) + wd * w
            if n_states == 0:
                return w - lr * g, states
            (m,) = states
            if nag:
                m = mom * m + g
                return w - lr * (g + mom * m), (m,)
            m = mom * m - lr * g
            return w + m, (m,)
    elif kind == "sgld":
        def fn(w, g, states, s, key):
            s = s.astype(w.dtype)
            lr, wd = s[1], s[2]
            g = pre(g, s) + wd * w
            noise = jax.random.normal(key, w.shape, dtype=w.dtype)
            return w - lr / 2 * g + jnp.sqrt(lr) * noise, states
    elif kind == "adam":
        def fn(w, g, states, s):
            s = s.astype(w.dtype)
            step_lr, wd, b1, b2, eps = s[1], s[2], s[3], s[4], s[5]
            mean, var = states
            g = pre(g, s) + wd * w
            mean = b1 * mean + (1 - b1) * g
            var = b2 * var + (1 - b2) * g * g
            w = w - step_lr * mean / (jnp.sqrt(var) + eps)
            return w, (mean, var)
    elif kind == "adagrad":
        def fn(w, g, states, s):
            s = s.astype(w.dtype)
            lr, wd, eps = s[1], s[2], s[3]
            (acc,) = states
            g = pre(g, s)
            acc = acc + g * g
            w = w - lr * (g / jnp.sqrt(acc + eps) + wd * w)
            return w, (acc,)
    elif kind == "rmsprop":
        def fn(w, g, states, s):
            s = s.astype(w.dtype)
            lr, wd, g1, g2 = s[1], s[2], s[3], s[4]
            n, gs, delta = states
            g = pre(g, s) + wd * w
            n = (1 - g1) * g * g + g1 * n
            gs = (1 - g1) * g + g1 * gs
            delta = g2 * delta - lr * g / jnp.sqrt(n - gs * gs + 1e-4)
            return w + delta, (n, gs, delta)
    elif kind == "adadelta":
        def fn(w, g, states, s):
            s = s.astype(w.dtype)
            wd, rho, eps = s[1], s[2], s[3]
            acc_g, acc_d = states
            g = pre(g, s)
            acc_g = rho * acc_g + (1 - rho) * g * g
            cur = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
            acc_d = rho * acc_d + (1 - rho) * cur * cur
            return w - cur - wd * w, (acc_g, acc_d)
    else:  # pragma: no cover
        raise MXNetError("unknown update kind %r" % kind)
    return fn


def _apply_update_multi(kind, n_states, clipped, ws, gs, ss, svs):
    """One jitted, donated call updating EVERY param of a structure
    group: the per-param math fns trace inline, XLA compiles them into
    one program, and one dispatch per step replaces one per param."""
    import jax
    import jax.numpy as jnp

    donate = _donation_ok()
    ck = ("multi", kind, n_states, clipped, len(ws), donate)
    fn = _JIT_UPDATES.get(ck)
    if fn is None:
        math_fn = _update_math(kind, n_states, clipped)

        def multi(ws, gs, ss, sv_mat):
            outs = [math_fn(w, g, s, sv_mat[i])
                    for i, (w, g, s) in enumerate(zip(ws, gs, ss))]
            return (tuple(o[0] for o in outs),
                    tuple(o[1] for o in outs))

        fn = jax.jit(multi, donate_argnums=(0, 2) if donate else ())
        _JIT_UPDATES[ck] = fn
    # scalar vectors ride as ONE stacked (n_params, k) array — per-param
    # tiny transfers would reintroduce the per-param overhead the fused
    # dispatch removes (uniform k within a structure group)
    sv_mat = jnp.asarray(svs, jnp.float32)
    return fn(ws, gs, ss, sv_mat)


def _apply_update(kind, w, g, states, scalars, clipped, key=None):
    import jax
    import jax.numpy as jnp

    donate = _donation_ok()
    ck = (kind, len(states), clipped, donate)
    fn = _JIT_UPDATES.get(ck)
    if fn is None:
        math_fn = _update_math(kind, len(states), clipped)
        fn = jax.jit(math_fn,
                     donate_argnums=(0, 2) if donate else ())
        _JIT_UPDATES[ck] = fn
    s_vec = jnp.asarray(scalars, jnp.float32)
    if key is not None:
        return fn(w, g, states, s_vec, key)
    return fn(w, g, states, s_vec)


def _zeros_like_state(weight: NDArray) -> NDArray:
    """Optimizer state matching the weight's dtype AND device sharding —
    params may be replicated over a device mesh (executor_group), and the
    update math must stay colocated."""
    import jax
    import jax.numpy as jnp

    data = jax.device_put(jnp.zeros(weight.shape, dtype=weight.dtype),
                          weight._data.sharding)
    return NDArray(data, ctx=weight.context)


class Optimizer:
    """Base optimizer (reference ``optimizer.py`` ``Optimizer``)."""

    def __init__(self, rescale_grad: float = 1.0, param_idx2name=None,
                 wd: float = 0.0, clip_gradient: Optional[float] = None,
                 learning_rate: float = 0.01,
                 lr_scheduler: Optional[LRScheduler] = None,
                 sym=None, begin_num_update: int = 0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym = sym
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}
        if sym is not None:
            attrs = sym.attr_dict()
            for name in sym.list_arguments():
                if name in attrs:
                    if "__lr_mult__" in attrs[name]:
                        self.lr_mult[name] = float(attrs[name]["__lr_mult__"])
                    if "__wd_mult__" in attrs[name]:
                        self.wd_mult[name] = float(attrs[name]["__wd_mult__"])

    @staticmethod
    def create_optimizer(name: str, **kwargs) -> "Optimizer":
        cls = _REG.get(name)
        return cls(**kwargs)

    def create_state(self, index: int, weight: NDArray):
        return None

    def update(self, index: int, weight: NDArray, grad: NDArray, state):
        """Default: execute this optimizer's plan as one fused kernel.
        Optimizers without a plan (custom user subclasses following the
        reference's override-update contract) override this directly;
        the base raises NotImplementedError through _plan."""
        kind, states, scalars = self._plan(index, weight, grad, state)
        self._run(kind, weight, grad, states, scalars)

    def _plan(self, index, weight, grad, state):
        """Per-step update plan: ``(kind, state_nds, scalars)`` with all
        per-index bookkeeping (update counts, lr schedule, multipliers)
        applied. Optimizers that expose a plan get fused multi-param
        updates for free; those that don't (custom user optimizers,
        SGLD's per-param PRNG) fall back to sequential update()."""
        raise NotImplementedError

    def _fusable(self) -> bool:
        """True when update_multi may run the plan instead of update().

        The plan must DESCRIBE the update actually in effect: a subclass
        that overrides update() below the class that defined _plan (e.g.
        ``class MySGD(SGD)`` with custom update math — the reference's
        extension contract) has custom semantics the inherited plan does
        not capture, so it must take the sequential path."""
        cls = type(self)
        plan_cls = next((c for c in cls.__mro__ if "_plan" in vars(c)),
                        None)
        upd_cls = next((c for c in cls.__mro__ if "update" in vars(c)),
                       None)
        if plan_cls is None or plan_cls is Optimizer:
            return False
        return cls.__mro__.index(upd_cls) >= cls.__mro__.index(plan_cls)

    def update_multi(self, items):
        """Apply this optimizer to MANY params in ONE donated XLA call
        per structure group (items: ``[(index, weight, grad, state)]``).

        The per-param path dispatches one kernel per parameter per step
        — ~161 dispatches for ResNet-50 — and dispatch latency is pure
        overhead on an accelerator (worse through a remote transport).
        Falls back to sequential update() when no plan describes the
        effective update() or fusion is disabled
        (MXNET_TPU_FUSED_UPDATE=0)."""
        from . import env as _env

        if not self._fusable() \
                or not _env.get("MXNET_TPU_FUSED_UPDATE"):
            for i, w, g, s in items:
                self.update(i, w, g, s)
            return
        clip = self.clip_gradient
        rescale = self.rescale_grad
        groups: Dict[tuple, list] = {}
        for i, w, g, s in items:
            kind, states, scalars = self._plan(i, w, g, s)
            full = (rescale,) + tuple(scalars) \
                + ((clip,) if clip is not None else ())
            groups.setdefault((kind, len(states)), []).append(
                (w, g, tuple(states), full))
        from .engine import get_engine

        for (kind, n_states), members in groups.items():
            def _do(kind=kind, n_states=n_states, members=members):
                _tel.inc("step.dispatches")
                new_ws, new_ss = _apply_update_multi(
                    kind, n_states, clip is not None,
                    tuple(m[0]._data for m in members),
                    tuple(m[1]._data for m in members),
                    tuple(tuple(s._data for s in m[2]) for m in members),
                    tuple(m[3] for m in members))
                for m, nw, ns in zip(members, new_ws, new_ss):
                    m[0]._data = nw
                    for snd, sv in zip(m[2], ns):
                        snd._data = sv
            muts = [m[0]._var for m in members] \
                + [s._var for m in members for s in m[2]]
            get_engine().push(_do, const_vars=[m[1]._var for m in members],
                              mutable_vars=muts)

    # -- checkpoint support (checkpoint.py) ---------------------------
    def get_checkpoint_state(self) -> dict:
        """The host-side scalars the per-step ``_plan`` reads — update
        counts and lr-schedule state. These never live on device, so a
        full-state snapshot must carry them explicitly: resuming
        without them replays the lr warm-up/decay from step 0 and the
        loss stream diverges."""
        st = {"num_update": self.num_update,
              "begin_num_update": self.begin_num_update,
              "index_update_count": dict(self._index_update_count)}
        if self.lr_scheduler is not None:
            st["lr_scheduler"] = {
                k: v for k, v in vars(self.lr_scheduler).items()
                if isinstance(v, (int, float, bool))}
        return st

    def set_checkpoint_state(self, st: dict) -> None:
        """Restore a state captured by :meth:`get_checkpoint_state`."""
        self.num_update = int(st["num_update"])
        self.begin_num_update = int(st["begin_num_update"])
        self._index_update_count = {int(k): int(v) for k, v in
                                    st["index_update_count"].items()}
        for k, v in st.get("lr_scheduler", {}).items():
            if self.lr_scheduler is not None:
                setattr(self.lr_scheduler, k, v)

    def set_lr_mult(self, args_lr_mult: Dict[str, float]):
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult: Dict[str, float]):
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index: int):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index: int) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, str(index))
        return lr * self.lr_mult.get(name, 1.0)

    def _get_wd(self, index: int) -> float:
        name = self.idx2name.get(index, str(index))
        wd = self.wd * self.wd_mult.get(name, 1.0)
        # bias/gamma/beta conventionally get no weight decay unless overridden
        return wd

    def _run(self, kind, weight, grad, state_nds, scalars, key=None):
        """Dispatch one fused, donated update kernel through the engine.

        ``state_nds`` are the state NDArrays (possibly empty); ``scalars``
        the per-step hyperparameters, packed into one traced f32 vector so
        an LRScheduler changing lr every step reuses the compiled kernel.
        """
        from .engine import get_engine

        clip = self.clip_gradient
        rescale = self.rescale_grad
        state_nds = tuple(state_nds)

        def _do():
            _tel.inc("step.dispatches")
            new_w, new_s = _apply_update(
                kind, weight._data, grad._data,
                tuple(s._data for s in state_nds),
                (rescale,) + tuple(scalars)
                + ((clip,) if clip is not None else ()),
                clipped=clip is not None, key=key)
            weight._data = new_w
            for nd, nv in zip(state_nds, new_s):
                nd._data = nv
        muts = [weight._var] + [s._var for s in state_nds]
        get_engine().push(_do, const_vars=[grad._var], mutable_vars=muts)


@register("sgd")
class SGD(Optimizer):
    """SGD with momentum (reference optimizer.py:234)."""

    def __init__(self, momentum: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like_state(weight)

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        return ("sgd", () if state is None else (state,),
                (self._get_lr(index), self._get_wd(index), self.momentum))



@register("ccsgd")
class ccSGD(SGD):
    """Alias of SGD kept for reference-script compatibility (the
    reference's C++-side ccSGD, optimizer.py:426)."""


@register("nag")
class NAG(SGD):
    """Nesterov accelerated gradient (reference optimizer.py:313)."""

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        return ("nag", () if state is None else (state,),
                (self._get_lr(index), self._get_wd(index), self.momentum))



@register("sgld")
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:361)."""

    def update(self, index, weight, grad, state):
        from . import random as _random

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._run("sgld", weight, grad, (), (lr, wd),
                  key=_random.next_key())


@register("adam")
class Adam(Optimizer):
    """Adam (reference optimizer.py:504)."""

    def __init__(self, learning_rate: float = 0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        step_lr = lr * math.sqrt(1.0 - self.beta2 ** t) \
            / (1.0 - self.beta1 ** t)
        return ("adam", tuple(state),
                (step_lr, self._get_wd(index), self.beta1, self.beta2,
                 self.epsilon))



@register("adagrad")
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:605)."""

    def __init__(self, eps: float = 1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        return ("adagrad", (state,),
                (self._get_lr(index), self._get_wd(index),
                 self.float_stable_eps))



@register("rmsprop")
class RMSProp(Optimizer):
    """RMSProp (Tieleman & Hinton variant used by the reference,
    optimizer.py:654: running E[g^2], E[g], and momentum delta)."""

    def __init__(self, learning_rate: float = 0.002, gamma1: float = 0.95,
                 gamma2: float = 0.9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2

    def create_state(self, index, weight):
        return (_zeros_like_state(weight),   # n
                _zeros_like_state(weight),   # g
                _zeros_like_state(weight))   # delta

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        return ("rmsprop", tuple(state),
                (self._get_lr(index), self._get_wd(index), self.gamma1,
                 self.gamma2))



@register("adadelta")
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:728)."""

    def __init__(self, rho: float = 0.90, epsilon: float = 1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like_state(weight), _zeros_like_state(weight))

    def _plan(self, index, weight, grad, state):
        self._update_count(index)
        return ("adadelta", tuple(state),
                (self._get_wd(index), self.rho, self.epsilon))



@register("test")
class Test(Optimizer):
    """Trivial optimizer for tests (reference optimizer.py:782)."""

    def create_state(self, index, weight):
        return _zeros_like_state(weight)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def create(name: str, **kwargs) -> Optimizer:
    return Optimizer.create_optimizer(name, **kwargs)


def _states_to_numpy(obj):
    """NDArray states -> numpy for pickling (NDArray holds engine vars with
    thread locks and device buffers, neither of which pickles)."""
    if isinstance(obj, NDArray):
        return obj.asnumpy()
    if isinstance(obj, tuple):
        return tuple(_states_to_numpy(o) for o in obj)
    if isinstance(obj, list):
        return [_states_to_numpy(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _states_to_numpy(v) for k, v in obj.items()}
    return obj


def _states_from_numpy(obj):
    import numpy as _np

    from .ndarray import array as _array

    if isinstance(obj, _np.ndarray):
        return _array(obj, dtype=obj.dtype)
    if isinstance(obj, tuple):
        return tuple(_states_from_numpy(o) for o in obj)
    if isinstance(obj, list):
        return [_states_from_numpy(o) for o in obj]
    if isinstance(obj, dict):
        return {k: _states_from_numpy(v) for k, v in obj.items()}
    return obj


class Updater:
    """Closure bundling an optimizer with per-index states (reference
    ``get_updater``, optimizer.py:816). States serialize via
    get_states/set_states (numpy form) for checkpointing."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, Any] = {}

    def __call__(self, index: int, grad: NDArray, weight: NDArray):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def update_multi(self, items):
        """Fused form of per-param __call__ (items: ``[(index, grad,
        weight)]``, same argument order) — one donated XLA dispatch per
        optimizer-structure group instead of one per parameter."""
        for index, grad, weight in items:
            if index not in self.states:
                self.states[index] = self.optimizer.create_state(index,
                                                                 weight)
        self.optimizer.update_multi(
            [(i, w, g, self.states[i]) for i, g, w in items])

    def get_states(self):
        import pickle

        return pickle.dumps(_states_to_numpy(self.states))

    def set_states(self, states_bytes):
        import pickle

        self.states = _states_from_numpy(pickle.loads(states_bytes))


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
