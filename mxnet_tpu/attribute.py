"""Attribute scoping for symbols (reference ``python/mxnet/attribute.py``).

``AttrScope`` attaches attributes like ``ctx_group`` (model-parallel
placement), ``__lr_mult__``/``__wd_mult__`` (per-param optimizer scaling) and
``force_mirroring`` to symbols created inside a ``with`` block.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope"]


class AttrScope:
    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope: Optional[AttrScope] = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("attributes must be strings")
        self._attr: Dict[str, str] = kwargs

    def get(self, attr: Optional[Dict[str, str]]) -> Dict[str, str]:
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        self._old_scope = AttrScope.current()
        attr = self._old_scope._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, *args):
        AttrScope._current.value = self._old_scope

    @staticmethod
    def current() -> "AttrScope":
        if not hasattr(AttrScope._current, "value") or AttrScope._current.value is None:
            AttrScope._current.value = AttrScope()
        return AttrScope._current.value
