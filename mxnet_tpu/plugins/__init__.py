"""Plugin bridges (reference ``plugin/``: torch, caffe, warpctc, ...).

Available here: the torch bridge (``plugin/torch`` modernized to PyTorch).
The caffe/warpctc/sframe plugins have no usable host libraries in this
environment and are intentionally absent.
"""
from . import torch_bridge  # noqa: F401
