"""Plugin bridges (reference ``plugin/``: torch, caffe, warpctc,
opencv, sframe).

- torch bridge (``plugin/torch`` modernized to PyTorch; imported lazily
  so the heavy torch import is only paid when used)
- caffe bridge (``plugin/caffe``'s CaffeOp/CaffeLoss over a jnp layer
  emulation registry; registered eagerly so ``sym.CaffeOp`` exists)
- warpctc is a first-class op (``mxnet_tpu/ops/ctc.py``), not a plugin —
  the TPU runtime needs no external CTC library.
- opencv (``plugin/opencv``): same surface (imdecode/resize/
  copyMakeBorder/crops/ImageListIter) with PIL+numpy standing in for
  cv2, which is absent here; lazy like torch.
- sframe (``plugin/sframe``): MXSFrameDataIter/MXSFrameImageIter with
  pandas standing in for graphlab's gl_sframe; registered eagerly so
  the iterator registry lists them.
"""
from . import caffe_op  # noqa: F401
from . import sframe  # noqa: F401


def __getattr__(name):
    # importlib (not `from . import X`): a from-import inside the
    # package's own __getattr__ re-enters it via the import system's
    # hasattr probe before the submodule lands -> infinite recursion
    if name in ("torch_bridge", "opencv"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
