"""Plugin bridges (reference ``plugin/``: torch, caffe, warpctc, ...).

- torch bridge (``plugin/torch`` modernized to PyTorch; imported lazily
  so the heavy torch import is only paid when used)
- caffe bridge (``plugin/caffe``'s CaffeOp/CaffeLoss over a jnp layer
  emulation registry; registered eagerly so ``sym.CaffeOp`` exists)
- warpctc is a first-class op (``mxnet_tpu/ops/ctc.py``), not a plugin —
  the TPU runtime needs no external CTC library.
- sframe has no usable host library in this environment.
"""
from . import caffe_op  # noqa: F401


def __getattr__(name):
    if name == "torch_bridge":
        from . import torch_bridge
        return torch_bridge
    raise AttributeError(name)
