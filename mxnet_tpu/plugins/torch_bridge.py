"""Torch bridge plugin.

Re-design of the reference's torch plugin (``plugin/torch/
torch_module-inl.h``, ``torch_criterion-inl.h``, ``python/mxnet/torch.py``
— which bridged Lua Torch modules/criterions into the graph): here any
**PyTorch** ``nn.Module`` (CPU) becomes a symbolic op. Forward runs the
module under ``torch.enable_grad`` inside a host callback; backward
re-runs it and uses ``torch.autograd.grad`` — wired into the XLA graph by
the CustomOp machinery (host callbacks + custom_vjp).
"""
from __future__ import annotations

import itertools
from typing import Callable

import numpy as np

from .. import operator as mop

__all__ = ["torch_module", "torch_criterion"]

_uid = itertools.count()


def _make_prop(module_factory: Callable, n_inputs: int, infer_shape_fn):
    class _TorchProp(mop.CustomOpProp):
        def __init__(self, **_kw):
            super().__init__(need_top_grad=True)
            self._module = module_factory()

        def list_arguments(self):
            return ["data%d" % i for i in range(n_inputs)] \
                if n_inputs > 1 else ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [infer_shape_fn(in_shape)], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            module = self._module

            class _TorchOp(mop.CustomOp):
                _seed = 0
                _is_train = False

                def _run(self, arrays, need_grad):
                    """Run the module under a forked, seeded torch RNG so
                    the backward re-run sees the SAME dropout masks as the
                    forward the user observed."""
                    import torch

                    # only float tensors can carry grad (int labels etc.
                    # are handled by autograd.grad(allow_unused=True))
                    tens = []
                    for a in arrays:
                        t = torch.from_numpy(np.ascontiguousarray(a))
                        if t.is_floating_point():
                            if need_grad:
                                t.requires_grad_(True)
                        else:
                            # torch criterions want Long targets; jax's
                            # default int is int32
                            t = t.long()
                        tens.append(t)
                    module.train(self._is_train)
                    with torch.random.fork_rng(devices=[]):
                        torch.manual_seed(self._seed)
                        with torch.enable_grad() if need_grad \
                                else torch.no_grad():
                            out = module(*tens)
                    return tens, out

                def forward(self, is_train, req, in_data, out_data, aux):
                    self._is_train = bool(is_train)
                    self._seed = int(np.random.randint(1 << 31))
                    _, out = self._run([x.asnumpy() for x in in_data], False)
                    self.assign(out_data[0], req[0], out.detach().numpy())

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    import torch

                    # re-running forward must not double-update stateful
                    # buffers (BatchNorm running stats)
                    buffers = {k: v.clone()
                               for k, v in module.named_buffers()}
                    tens, out = self._run([x.asnumpy() for x in in_data],
                                          True)
                    g = torch.from_numpy(
                        np.ascontiguousarray(out_grad[0].asnumpy()))
                    idx = [i for i, t in enumerate(tens)
                           if t.requires_grad]
                    got = torch.autograd.grad(out, [tens[i] for i in idx],
                                              g, allow_unused=True)
                    # restore AFTER grad (in-place restore would bump
                    # versions of tensors autograd saved)
                    with torch.no_grad():
                        for k, v in module.named_buffers():
                            v.copy_(buffers[k])
                    grads = [None] * len(tens)
                    for i, gr in zip(idx, got):
                        grads[i] = gr
                    for dst, r, gr, t in zip(in_grad, req, grads, tens):
                        self.assign(dst, r,
                                    gr.numpy() if gr is not None
                                    else np.zeros(t.shape, np.float32))
            return _TorchOp()
    return _TorchProp


def torch_module(module_factory: Callable, data, n_inputs: int = 1,
                 infer_shape_fn=None, name=None):
    """Wrap a PyTorch module as a symbol (reference ``mx.sym.TorchModule``).

    ``module_factory`` builds the (CPU) torch module; its parameters are
    owned torch-side (reference torch plugin semantics: the module carries
    its own weights). ``infer_shape_fn(in_shapes) -> out_shape`` defaults
    to same-as-first-input.
    """
    from .. import symbol as sym_mod

    if infer_shape_fn is None:
        infer_shape_fn = lambda in_shapes: in_shapes[0]  # noqa: E731
    reg_name = "_torch_module_%d" % next(_uid)
    mop.register(reg_name)(_make_prop(module_factory, n_inputs,
                                      infer_shape_fn))
    kwargs = {"op_type": reg_name}
    if name is not None:
        kwargs["name"] = name
    if isinstance(data, (list, tuple)):
        for i, d in enumerate(data):
            kwargs["data%d" % i if len(data) > 1 else "data"] = d
    else:
        kwargs["data"] = data
    return getattr(sym_mod, "Custom")(**kwargs)


def torch_criterion(criterion_factory: Callable, data, label, name=None):
    """Wrap a torch loss (reference ``mx.sym.TorchCriterion``): forward
    emits the scalar loss; backward is d(loss)/d(data), label gets zero
    grad."""
    from .. import symbol as sym_mod

    def factory():
        import torch

        crit = criterion_factory()

        class _Wrap(torch.nn.Module):
            def forward(self, data, label):
                return crit(data, label).reshape(1)
        return _Wrap()

    reg_name = "_torch_criterion_%d" % next(_uid)
    mop.register(reg_name)(
        _make_prop(factory, 2, lambda in_shapes: [1]))
    kwargs = {"op_type": reg_name, "data0": data, "data1": label}
    if name is not None:
        kwargs["name"] = name
    return getattr(sym_mod, "Custom")(**kwargs)
