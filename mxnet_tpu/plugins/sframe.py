"""SFrame plugin equivalent (reference ``plugin/sframe/iter_sframe.cc``):
``MXSFrameDataIter`` / ``MXSFrameImageIter`` — data iterators over a
columnar out-of-core table, selecting a data field and a label field
with declared shapes.

Backend substitution: GraphLab/Turi's ``gl_sframe`` does not exist in
this environment; pandas (CSV/Parquet-backed DataFrame) plays the
columnar-table role. The reference's parameter surface is preserved:
``path_sframe`` (here: .csv/.parquet path or a DataFrame),
``data_field`` / ``label_field``, ``data_shape`` / ``label_shape``,
``batch_size``.
"""
import numpy as np

from ..base import MXNetError, Registry
from ..ndarray import array
from .. import io as _io

_REG = Registry.get_registry("data_iter")


def _load_table(path_sframe):
    import pandas as pd

    if isinstance(path_sframe, pd.DataFrame):
        return path_sframe
    if str(path_sframe).endswith(".parquet"):
        return pd.read_parquet(path_sframe)
    return pd.read_csv(path_sframe)


def _cell_to_array(cell, shape):
    """A table cell is a scalar, a list, or a string of separated
    numbers — normalize to float32 with the declared shape."""
    if isinstance(cell, str):
        vals = np.asarray([float(v) for v in cell.split()], np.float32) \
            if " " in cell else np.asarray([float(cell)], np.float32)
    elif np.isscalar(cell):
        vals = np.asarray([cell], dtype=np.float32)
    else:
        vals = np.asarray(cell, dtype=np.float32).ravel()
    if int(np.prod(shape)) != vals.size:
        raise MXNetError(
            "SFrameIter: cell size %d does not match declared shape %s"
            % (vals.size, (shape,)))
    return vals.reshape(shape)


@_REG.register("MXSFrameDataIter")
class MXSFrameDataIter(_io.DataIter):
    """Dense-row iterator (reference SFrameDataIter): each row's
    data_field flattens into data_shape."""

    def __init__(self, path_sframe, data_field="data",
                 label_field="label", data_shape=(1,), label_shape=(1,),
                 batch_size=32, **kwargs):
        super().__init__()
        self._df = _load_table(path_sframe)
        for f in (data_field, label_field):
            if f not in self._df.columns:
                raise MXNetError("SFrameIter: field '%s' not in table "
                                 "(columns: %s)"
                                 % (f, list(self._df.columns)))
        self.data_field = data_field
        self.label_field = label_field
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_shape = tuple(int(x) for x in label_shape)
        self.batch_size = batch_size
        self.cursor = -batch_size
        self.num_data = len(self._df)

    @property
    def provide_data(self):
        return [_io.DataDesc("data",
                             (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_shape == (1,) \
            else (self.batch_size,) + self.label_shape
        return [_io.DataDesc("softmax_label", shape)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _rows(self):
        idx = [(self.cursor + i) % self.num_data
               for i in range(self.batch_size)]
        return self._df.iloc[idx]

    def getdata(self):
        rows = self._rows()
        data = np.stack([_cell_to_array(c, self.data_shape)
                         for c in rows[self.data_field]])
        return [array(data)]

    def getlabel(self):
        rows = self._rows()
        lab = np.stack([_cell_to_array(c, self.label_shape)
                        for c in rows[self.label_field]])
        if self.label_shape == (1,):
            lab = lab.ravel()
        return [array(lab.astype(np.float32))]

    def getpad(self):
        if self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


@_REG.register("MXSFrameImageIter")
class MXSFrameImageIter(MXSFrameDataIter):
    """Image-column iterator (reference SFrameImageIter): the data
    field holds encoded image bytes; decode through the opencv-plugin
    path, data_shape is (C, H, W)."""

    def getdata(self):
        from . import opencv as cv

        c, h, w = self.data_shape
        rows = self._rows()
        out = np.zeros((self.batch_size, c, h, w), dtype=np.float32)
        for n, cell in enumerate(rows[self.data_field]):
            if isinstance(cell, (bytes, bytearray)):
                raw = cell
            else:                            # path column also accepted
                with open(cell, "rb") as f:
                    raw = f.read()
            img = cv.imdecode(raw, cv.IMREAD_COLOR if c == 3
                              else cv.IMREAD_GRAYSCALE)
            img = cv.resize(img, (w, h))
            out[n] = img.asnumpy().astype(np.float32).transpose(2, 0, 1)
        return [array(out)]
