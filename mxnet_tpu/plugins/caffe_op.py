"""Caffe layer bridge: ``sym.CaffeOp`` / ``sym.CaffeLoss``.

Parity target: the reference's caffe plugin
(``/root/reference/plugin/caffe/caffe_op-inl.h`` ``CaffeOpParam``:
``prototxt``/``num_data``/``num_weight``/``num_out``; ``caffe_loss-inl.h``
``grad_scale``), which embedded real caffe layers into the symbolic graph
so users could write
``sym.CaffeOp(data_0=x, num_weight=2, prototxt='layer{type:"InnerProduct"
inner_product_param{num_output: 128}}')``.

TPU-native re-design: linking libcaffe (CPU-only, CUDA-era) into an XLA
graph would break tracing, so the plugin ships a **layer emulation
registry** — jnp implementations of the caffe layer zoo with caffe's
exact parameter names, weight layouts and defaults, selected by parsing
the same prototxt strings. User code written against the reference
plugin runs unchanged; custom layers register via
:func:`register_caffe_layer`. When a real pycaffe is importable it can
be bridged per-layer through ``mxnet_tpu.operator.CustomOp`` (host
callback), but none of the built-in emulations need it.
"""
from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from ..base import MXNetError
from ..ops.registry import Operator, Param, register_op

__all__ = ["parse_prototxt", "register_caffe_layer", "CAFFE_LAYERS"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jax():
    import jax
    return jax


# ---------------------------------------------------------------------------
# prototxt mini-parser: 'layer{type:"TanH" param{k: v}}' -> nested dict
# (the reference parsed this with caffe's protobuf TextFormat;
# caffe_fieldentry.h shows the same string-typed field contract)
# ---------------------------------------------------------------------------
_TOKEN = re.compile(
    r'[A-Za-z_][A-Za-z0-9_]*|"[^"]*"'
    r'|-?(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?|[{}:]|\S')


def parse_prototxt(text: str) -> Dict:
    # strip '#' comments first (standard in .prototxt files)
    text = re.sub(r"#[^\n]*", "", text)
    tokens = _TOKEN.findall(text)
    unknown = [t for t in tokens if len(t) == 1 and t not in "{}:"
               and not t.isalnum()]
    if unknown:
        raise MXNetError("prototxt: unexpected characters %r"
                         % sorted(set(unknown)))
    pos = [0]

    def parse_block():
        out: Dict = {}
        while pos[0] < len(tokens):
            tok = tokens[pos[0]]
            if tok == "}":
                pos[0] += 1
                return out
            key = tok
            pos[0] += 1
            if pos[0] < len(tokens) and tokens[pos[0]] == ":":
                pos[0] += 1
                val = tokens[pos[0]]
                pos[0] += 1
                if val.startswith('"'):
                    parsed = val[1:-1]
                else:
                    try:
                        parsed = int(val)
                    except ValueError:
                        try:
                            parsed = float(val)
                        except ValueError:
                            parsed = val  # bare enum like MAX / AVE
                _store(out, key, parsed)
            elif pos[0] < len(tokens) and tokens[pos[0]] == "{":
                pos[0] += 1
                _store(out, key, parse_block())
            else:
                raise MXNetError("prototxt parse error near %r" % key)
        return out

    def _store(d, k, v):
        if k in d:
            if not isinstance(d[k], list):
                d[k] = [d[k]]
            d[k].append(v)
        else:
            d[k] = v

    root = parse_block()
    return root.get("layer", root)


# ---------------------------------------------------------------------------
# layer emulation registry
# ---------------------------------------------------------------------------
CAFFE_LAYERS: Dict[str, "CaffeLayer"] = {}


def register_caffe_layer(type_name: str):
    def _do(cls):
        CAFFE_LAYERS[type_name] = cls()
        return cls
    return _do


class CaffeLayer:
    """One caffe layer type: weight shapes + forward in jnp. Weight
    layouts follow caffe (InnerProduct W is (num_output, dim) etc.) so
    converted caffemodels drop in."""

    def weight_shapes(self, cfg, in_shapes) -> List:
        return []

    def infer(self, cfg, in_shapes) -> List:
        return [in_shapes[0]]

    def forward(self, cfg, inputs, weights, is_train, rng):
        raise NotImplementedError


@register_caffe_layer("InnerProduct")
class _InnerProduct(CaffeLayer):
    def _dim(self, in_shape):
        return int(np.prod(in_shape[1:]))

    def weight_shapes(self, cfg, in_shapes):
        p = cfg.get("inner_product_param", {})
        n = int(p.get("num_output"))
        shapes = [(n, self._dim(in_shapes[0]))]
        if p.get("bias_term", True):
            shapes.append((n,))
        return shapes

    def infer(self, cfg, in_shapes):
        n = int(cfg.get("inner_product_param", {}).get("num_output"))
        return [(in_shapes[0][0], n)]

    def forward(self, cfg, inputs, weights, is_train, rng):
        x = inputs[0].reshape(inputs[0].shape[0], -1)
        out = x @ weights[0].T
        if len(weights) > 1:
            out = out + weights[1]
        return [out]


class _Elementwise(CaffeLayer):
    fn = None

    def forward(self, cfg, inputs, weights, is_train, rng):
        return [type(self).fn(inputs[0])]


@register_caffe_layer("TanH")
class _TanH(_Elementwise):
    fn = staticmethod(lambda x: _jnp().tanh(x))


@register_caffe_layer("Sigmoid")
class _Sigmoid(_Elementwise):
    fn = staticmethod(lambda x: _jax().nn.sigmoid(x))


@register_caffe_layer("ReLU")
class _ReLU(_Elementwise):
    fn = staticmethod(lambda x: _jnp().maximum(x, 0))


@register_caffe_layer("AbsVal")
class _AbsVal(_Elementwise):
    fn = staticmethod(lambda x: _jnp().abs(x))


@register_caffe_layer("Softmax")
class _Softmax(CaffeLayer):
    def forward(self, cfg, inputs, weights, is_train, rng):
        return [_jax().nn.softmax(inputs[0], axis=1)]


@register_caffe_layer("Dropout")
class _Dropout(CaffeLayer):
    def forward(self, cfg, inputs, weights, is_train, rng):
        ratio = float(cfg.get("dropout_param", {})
                      .get("dropout_ratio", 0.5))
        if not is_train or ratio <= 0 or rng is None:
            return [inputs[0]]
        jax = _jax()
        keep = 1.0 - ratio
        mask = jax.random.bernoulli(rng, keep, inputs[0].shape)
        return [_jnp().where(mask, inputs[0] / keep, 0)]


def _pair(p, key, default=0):
    v = p.get(key, p.get(key + "_h", default))
    return int(v)


@register_caffe_layer("Pooling")
class _Pooling(CaffeLayer):
    def _params(self, cfg):
        p = cfg.get("pooling_param", {})
        k = _pair(p, "kernel_size", 2)
        s = _pair(p, "stride", 1)
        pad = _pair(p, "pad", 0)
        mode = str(p.get("pool", "MAX")).upper()
        return k, s, pad, mode

    @staticmethod
    def _pooled(dim, k, s, pad):
        """caffe pooling_layer.cpp: ceil-mode dims, then clip any window
        that would start entirely inside the padding."""
        out = int(np.ceil((dim + 2 * pad - k) / float(s))) + 1
        if pad > 0 and (out - 1) * s >= dim + pad:
            out -= 1
        return out

    def infer(self, cfg, in_shapes):
        k, s, pad, _ = self._params(cfg)
        n, c, h, w = in_shapes[0]
        return [(n, c, self._pooled(h, k, s, pad),
                 self._pooled(w, k, s, pad))]

    def forward(self, cfg, inputs, weights, is_train, rng):
        jnp = _jnp()
        lax = _jax().lax
        k, s, pad, mode = self._params(cfg)
        x = inputs[0]
        n, c, h, w = x.shape
        oh = self._pooled(h, k, s, pad)
        ow = self._pooled(w, k, s, pad)
        # pad so every (possibly partial) window fits; padding is -inf
        # for MAX (never wins: the clip rule guarantees a real cell in
        # each window) and 0 for AVE (doesn't perturb the sum)
        eh = max(pad, (oh - 1) * s + k - h - pad)
        ew = max(pad, (ow - 1) * s + k - w - pad)
        if mode == "AVE":
            init, op, fill = 0.0, lax.add, 0.0
        else:
            init, op, fill = -jnp.inf, lax.max, -jnp.inf
        xp = jnp.pad(x, ((0, 0), (0, 0), (pad, eh), (pad, ew)),
                     constant_values=fill)
        out = lax.reduce_window(xp, init, op, (1, 1, k, k), (1, 1, s, s),
                                "valid")
        if mode == "AVE":
            # caffe divides by the window area clipped to the padded
            # image extent [0, dim + 2*pad) capped at dim + pad on the
            # far side (pool_size in pooling_layer.cpp)
            area_h = np.minimum(np.arange(oh) * s + k, h + 2 * pad) \
                - np.arange(oh) * s
            area_w = np.minimum(np.arange(ow) * s + k, w + 2 * pad) \
                - np.arange(ow) * s
            area = jnp.asarray(np.outer(area_h, area_w),
                               dtype=out.dtype)
            out = out / area[None, None]
        return [out]


@register_caffe_layer("Convolution")
class _Convolution(CaffeLayer):
    def _params(self, cfg):
        p = cfg.get("convolution_param", {})
        return (int(p.get("num_output")), _pair(p, "kernel_size", 1),
                _pair(p, "stride", 1), _pair(p, "pad", 0),
                int(p.get("group", 1)), p.get("bias_term", True))

    def weight_shapes(self, cfg, in_shapes):
        n_out, k, _, _, group, bias = self._params(cfg)
        c = in_shapes[0][1]
        shapes = [(n_out, c // group, k, k)]
        if bias:
            shapes.append((n_out,))
        return shapes

    def infer(self, cfg, in_shapes):
        n_out, k, s, pad, _, _ = self._params(cfg)
        n, c, h, w = in_shapes[0]
        oh = (h + 2 * pad - k) // s + 1
        ow = (w + 2 * pad - k) // s + 1
        return [(n, n_out, oh, ow)]

    def forward(self, cfg, inputs, weights, is_train, rng):
        lax = _jax().lax
        _, k, s, pad, group, bias = self._params(cfg)
        out = lax.conv_general_dilated(
            inputs[0], weights[0], window_strides=(s, s),
            padding=[(pad, pad), (pad, pad)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=group)
        if bias and len(weights) > 1:
            out = out + weights[1].reshape(1, -1, 1, 1)
        return [out]


@register_caffe_layer("EuclideanLoss")
class _EuclideanLoss(CaffeLayer):
    def infer(self, cfg, in_shapes):
        return [(1,)]

    def forward(self, cfg, inputs, weights, is_train, rng):
        jnp = _jnp()
        d = inputs[0] - inputs[1].reshape(inputs[0].shape)
        return [jnp.sum(d * d)[None] / (2.0 * inputs[0].shape[0])]


@register_caffe_layer("SoftmaxWithLoss")
class _SoftmaxWithLoss(CaffeLayer):
    def infer(self, cfg, in_shapes):
        return [(1,)]

    def forward(self, cfg, inputs, weights, is_train, rng):
        jax = _jax()
        jnp = _jnp()
        lp = jax.nn.log_softmax(inputs[0], axis=1)
        labels = inputs[1].astype(jnp.int32).reshape(-1)
        n = inputs[0].shape[0]
        picked = lp[jnp.arange(n), labels]
        return [-picked.sum()[None] / n]


# ---------------------------------------------------------------------------
# the symbolic operators
# ---------------------------------------------------------------------------
def _single_layer_cfg(prototxt: str) -> Dict:
    cfg = parse_prototxt(prototxt)
    if isinstance(cfg, list):
        raise MXNetError(
            "CaffeOp/CaffeLoss take exactly ONE layer{...} block per node "
            "(got %d); split the net into one CaffeOp per layer like the "
            "reference plugin" % len(cfg))
    return cfg


def _layer(cfg):
    ltype = cfg.get("type")
    layer = CAFFE_LAYERS.get(ltype)
    if layer is None:
        raise MXNetError(
            "CaffeOp: no emulation for layer type %r (known: %s); register "
            "one with mxnet_tpu.plugins.caffe_op.register_caffe_layer"
            % (ltype, sorted(CAFFE_LAYERS)))
    return layer


@register_op("CaffeOp")
class CaffeOp(Operator):
    """reference plugin/caffe/caffe_op-inl.h: run a caffe layer as a
    symbol node. Inputs data_0..data_{num_data-1}, then num_weight
    trainable blobs in caffe layout."""

    name_hint = "caffeop"
    PARAMS = {
        "prototxt": Param(str, "layer{}"),
        "num_data": Param(int, 1),
        "num_weight": Param(int, 0),
        "num_out": Param(int, 1),
    }

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cfg = _single_layer_cfg(self.prototxt)

    def list_arguments(self):
        # reference naming (caffe_op-inl.h:222-231): data_i, then
        # "0_weight" and "i_bias" for the remaining blobs — which also
        # routes them to the right Initializer rules
        args = ["data_%d" % i for i in range(self.num_data)]
        for i in range(self.num_weight):
            args.append("%d_weight" % i if i == 0 else "%d_bias" % i)
        return args

    def list_outputs(self):
        return ["output"] if self.num_out == 1 \
            else ["output%d" % i for i in range(self.num_out)]

    def infer_shape(self, in_shapes):
        data = in_shapes[:self.num_data]
        if any(s is None for s in data):
            raise MXNetError("CaffeOp: data shape unknown")
        layer = _layer(self._cfg)
        wshapes = layer.weight_shapes(self._cfg, data)
        if len(wshapes) != self.num_weight:
            raise MXNetError(
                "CaffeOp: layer %s has %d weight blobs, num_weight=%d"
                % (self._cfg.get("type"), len(wshapes), self.num_weight))
        out = layer.infer(self._cfg, data)
        if len(out) != self.num_out:
            raise MXNetError("CaffeOp: layer produces %d outputs, "
                             "num_out=%d" % (len(out), self.num_out))
        return list(data) + wshapes, out, []

    def apply(self, ctx, inputs, aux):
        layer = _layer(self._cfg)
        data = list(inputs[:self.num_data])
        weights = list(inputs[self.num_data:])
        return layer.forward(self._cfg, data, weights, ctx.is_train,
                             ctx.rng), []


@register_op("CaffeLoss")
class CaffeLoss(Operator):
    """reference plugin/caffe/caffe_loss-inl.h: a caffe loss layer;
    backward seeds the loss top-diff with grad_scale (ibid.:153)."""

    name_hint = "caffeloss"
    PARAMS = {
        "prototxt": Param(str, "layer{}"),
        "num_data": Param(int, 2),
        "num_out": Param(int, 1),
        "grad_scale": Param(float, 1.0),
    }

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cfg = _single_layer_cfg(self.prototxt)
        if self.num_data != 2 or self.num_out != 1:
            raise MXNetError(
                "CaffeLoss: this bridge supports num_data=2 (data, label) "
                "and num_out=1; got num_data=%d num_out=%d"
                % (self.num_data, self.num_out))

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("CaffeLoss: data shape unknown")
        label = in_shapes[1] or (data[0],)
        layer = _layer(self._cfg)
        out = layer.infer(self._cfg, [data, label])
        return [data, label], out, []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        layer = _layer(self._cfg)
        cfg = self._cfg
        scale = self.grad_scale

        @jax.custom_vjp
        def f(data, label):
            return layer.forward(cfg, [data, label], [], ctx.is_train,
                                 ctx.rng)[0]

        def f_fwd(data, label):
            return f(data, label), (data, label)

        def f_bwd(res, g):
            data, label = res
            # reference CaffeLoss: top diff is grad_scale, head grads
            # ignored (caffe_loss-inl.h:153)
            grad = jax.grad(
                lambda d: layer.forward(cfg, [d, label], [], True,
                                        None)[0].sum())(data)
            return grad * scale, _jnp().zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0], inputs[1])], []
