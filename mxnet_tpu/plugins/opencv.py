"""OpenCV plugin equivalent (reference ``plugin/opencv/opencv.py`` +
``cv_api.cc``): imdecode / resize / copyMakeBorder NDArray functions and
the crop/normalize helpers + ``ImageListIter``.

Backend substitution: this environment has no OpenCV, so the decode /
resize / border kernels run on PIL + numpy (the reference's were cv2
calls through C glue — the plugin surface, semantics, and HWC/BGR
conventions are preserved; interpolation and border flags accept the
cv2 integer constants). Zero-copy is not a goal here: images are host
arrays until they enter an executor.
"""
import os
import random as _random

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array
from .. import io as _io

# cv2 constants accepted by the API (values match OpenCV's headers)
IMREAD_GRAYSCALE = 0
IMREAD_COLOR = 1
INTER_NEAREST = 0
INTER_LINEAR = 1
INTER_CUBIC = 2
BORDER_CONSTANT = 0
BORDER_REPLICATE = 1
BORDER_REFLECT = 2
BORDER_REFLECT_101 = 4


def _resample(interpolation):
    from PIL import Image

    return {INTER_NEAREST: Image.NEAREST,
            INTER_LINEAR: Image.BILINEAR,
            INTER_CUBIC: Image.BICUBIC}.get(interpolation, Image.BILINEAR)


def imdecode(str_img, flag=IMREAD_COLOR):
    """Decode an encoded image buffer -> NDArray (H, W, C) uint8 in BGR
    channel order (reference MXCVImdecode semantics)."""
    import io as _bytesio

    from PIL import Image

    img = Image.open(_bytesio.BytesIO(str_img))
    if flag == IMREAD_GRAYSCALE:
        arr = np.asarray(img.convert("L"), dtype=np.uint8)[:, :, None]
    else:
        rgb = np.asarray(img.convert("RGB"), dtype=np.uint8)
        arr = rgb[:, :, ::-1]                    # cv2 returns BGR
    return array(np.ascontiguousarray(arr))


def resize(src, size, interpolation=INTER_LINEAR):
    """Resize (H, W, C) NDArray to size=(w, h) (reference MXCVResize)."""
    from PIL import Image

    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    squeeze = arr.shape[2] == 1
    pim = Image.fromarray(arr.astype(np.uint8).squeeze() if squeeze
                          else arr.astype(np.uint8))
    pim = pim.resize((int(size[0]), int(size[1])), _resample(interpolation))
    out = np.asarray(pim, dtype=np.uint8)
    if out.ndim == 2:
        out = out[:, :, None]
    return array(out)


def copyMakeBorder(src, top, bot, left, right,
                   border_type=BORDER_CONSTANT, value=0):
    """Pad an image border (reference MXCVcopyMakeBorder)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    pad = ((top, bot), (left, right), (0, 0))
    if border_type == BORDER_CONSTANT:
        out = np.pad(arr, pad, constant_values=value)
    elif border_type == BORDER_REPLICATE:
        out = np.pad(arr, pad, mode="edge")
    elif border_type == BORDER_REFLECT:
        # cv2's BORDER_REFLECT duplicates the edge pixel -> np
        # "symmetric"; np "reflect" is cv2's BORDER_REFLECT_101
        out = np.pad(arr, pad, mode="symmetric")
    elif border_type == BORDER_REFLECT_101:
        out = np.pad(arr, pad, mode="reflect")
    else:
        raise MXNetError("copyMakeBorder: unknown border_type %d"
                         % border_type)
    return array(out)


def scale_down(src_size, size):
    """Scale down crop size if it's bigger than the image size."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def fixed_crop(src, x0, y0, w, h, size=None, interpolation=INTER_CUBIC):
    """Crop at a fixed location and optionally resize."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w, :]
    if size is not None and (w, h) != tuple(size):
        return resize(array(out), size, interpolation)
    return array(out)


def random_crop(src, size):
    """Random crop; upsamples when src is smaller than size."""
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = scale_down((w, h), size)
    x0 = _random.randint(0, w - new_w)
    y0 = _random.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size, min_area=0.25, ratio=(3.0 / 4.0, 4.0 / 3.0)):
    """Random area + aspect-ratio crop, reference fallback included."""
    h, w = src.shape[0], src.shape[1]
    area = w * h
    for _ in range(10):
        new_area = _random.uniform(min_area, 1.0) * area
        new_ratio = _random.uniform(*ratio)
        new_w = int(np.sqrt(new_area * new_ratio))
        new_h = int(np.sqrt(new_area / new_ratio))
        if _random.uniform(0.0, 1.0) < 0.5:
            new_w, new_h = new_h, new_w
        if new_w > w or new_h > h:
            continue
        x0 = _random.randint(0, w - new_w)
        y0 = _random.randint(0, h - new_h)
        return fixed_crop(src, x0, y0, new_w, new_h, size), \
            (x0, y0, new_w, new_h)
    return random_crop(src, size)


def color_normalize(src, mean, std=None):
    """(src - mean) / std in float32."""
    arr = src.asnumpy().astype(np.float32)
    arr -= np.asarray(mean, dtype=np.float32)
    if std is not None:
        arr /= np.asarray(std, dtype=np.float32)
    return array(arr)


class ImageListIter(_io.DataIter):
    """Iterate (root + list-file) images through the plugin decode path
    (reference plugin/opencv/opencv.py ImageListIter): batches are
    (N, H, W, 3) float NDArrays with optional mean subtraction."""

    def __init__(self, root, flist, batch_size, size, mean=None):
        super().__init__()
        self.root = root
        with open(flist) as f:
            self.list = [line.strip() for line in f if line.strip()]
        self.cur = 0
        self.batch_size = batch_size
        self.size = size
        self.mean = np.asarray(mean, dtype=np.float32) \
            if mean is not None else None

    @property
    def provide_data(self):
        return [_io.DataDesc("data", (self.batch_size, self.size[1],
                                      self.size[0], 3))]

    @property
    def provide_label(self):
        return [_io.DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self.cur = 0

    def next(self):
        if self.cur >= len(self.list):
            raise StopIteration
        batch = np.zeros((self.batch_size, self.size[1], self.size[0], 3),
                         dtype=np.float32)
        labels = np.zeros((self.batch_size,), dtype=np.float32)
        n = 0
        for i in range(self.cur, min(len(self.list),
                                     self.cur + self.batch_size)):
            entry = self.list[i].split("\t")
            # accepted line formats: "name" | "label\tname" |
            # im2rec's "idx\tlabel\tname"
            name = entry[-1]
            if len(entry) >= 3:
                label = float(entry[1])
            elif len(entry) == 2:
                label = float(entry[0])
            else:
                label = 0.0
            path = os.path.join(self.root, name)
            with open(path, "rb") as f:
                img = imdecode(f.read(), IMREAD_COLOR)
            img = resize(img, self.size)
            arr = img.asnumpy().astype(np.float32)
            if self.mean is not None:
                arr -= self.mean
            batch[n] = arr
            labels[n] = label
            n += 1
        pad = self.batch_size - n
        self.cur += self.batch_size
        return _io.DataBatch([array(batch)], [array(labels)], pad, None)
