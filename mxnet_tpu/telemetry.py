"""Unified telemetry: framework-wide counters, gauges, histograms and
host-side spans.

The reference's only observability was the ``Monitor`` callback,
``Speedometer`` and per-op engine logging (SURVEY §5); ``profiler.py``
added the XLA device trace. Neither instruments the layers where
regressions actually hide — engine dispatch, the input pipeline, kvstore
traffic, JIT recompilation. This module is the process-global metric
registry those layers report through:

* **Counters** — monotonically increasing ints (``engine.push``,
  ``io.batches``, ``kvstore.push_bytes``).
* **Gauges** — last-write-wins floats (``train.samples_per_sec``).
* **Histograms** — bounded: running count/sum/min/max plus a fixed-size
  reservoir of recent samples for percentiles. Memory is O(capacity)
  no matter how long the job runs.
* **Spans** — host-side wall-time intervals (``with telemetry.span(n)``)
  kept in a bounded ring; when an XLA trace capture is active they also
  emit ``TraceAnnotation`` so host work lines up with device ops in the
  same Perfetto view.

Overhead contract: telemetry is DISABLED by default; every recording
helper starts with one module-level flag check and returns immediately,
taking no locks and allocating nothing. Enable with
``MXNET_TPU_TELEMETRY=1`` or :func:`enable`. The write path when enabled
takes one small per-metric lock (increments from engine worker threads
must not lose updates); the disabled path takes none.

Exporters::

    telemetry.snapshot()            # nested dict, one leaf per metric
    telemetry.dump_jsonl(path)      # append ONE step record (crash-safe)
    telemetry.write_chrome_trace(p) # host spans -> Perfetto-loadable json

See docs/performance.md ("Telemetry") for the metric name table and the
JSONL schema.
"""
from __future__ import annotations

import bisect
import contextlib
import copy
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence

from . import env as _env
from .base import MXNetError

__all__ = ["enabled", "enable", "disable", "counter", "gauge", "histogram",
           "inc", "set_gauge", "observe", "span", "snapshot", "reset",
           "dump_jsonl", "write_chrome_trace", "Counter", "Gauge",
           "Histogram", "peek", "metrics_items", "merge_snapshots",
           "bucket_quantile", "sample_quantile", "DEFAULT_BUCKET_BOUNDS"]

_ENABLED = _env.get("MXNET_TPU_TELEMETRY")

_reg_lock = threading.Lock()
_metrics: Dict[str, object] = {}

# span ring: bounded so a never-exported long run cannot grow host memory
_SPAN_CAP = _env.get("MXNET_TPU_TELEMETRY_SPAN_CAP")
_spans: deque = deque(maxlen=_SPAN_CAP)
# perf_counter -> wall-clock offset, fixed at import so span timestamps
# from every thread share one epoch (and can be laid next to an XLA
# trace, which stamps wall time)
_EPOCH = time.time() - time.perf_counter()

_step_lock = threading.Lock()
_step = 0


def enabled() -> bool:
    return _ENABLED


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


class Counter:
    """Monotonic counter; thread-safe increments."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def export(self):
        return self._value


class Gauge:
    """Last-write-wins float."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float):
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value

    def export(self):
        return self._value


# Default latency-oriented bucket ladder (milliseconds). Finite upper
# bounds only; the implicit +Inf bucket count is the histogram's total
# count, so JSON exports never need an "Infinity" literal.
DEFAULT_BUCKET_BOUNDS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                         250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class Histogram:
    """Bounded histogram: exact count/sum/min/max, fixed cumulative
    buckets (Prometheus ``le`` semantics, exact forever), plus a ring of
    the most recent ``capacity`` samples for percentile estimates."""

    __slots__ = ("name", "capacity", "bounds", "_lock", "_count", "_sum",
                 "_min", "_max", "_ring", "_idx", "_bucket_counts")

    def __init__(self, name: str, capacity: int = 512,
                 bounds: Optional[Sequence[float]] = None):
        self.name = name
        self.capacity = int(capacity)
        self.bounds = tuple(sorted(float(b) for b in
                                   (DEFAULT_BUCKET_BOUNDS if bounds is None
                                    else bounds)))
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._ring = []
        self._idx = 0
        # per-bucket (non-cumulative) counts; index len(bounds) = overflow
        self._bucket_counts = [0] * (len(self.bounds) + 1)

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            self._bucket_counts[bisect.bisect_left(self.bounds, v)] += 1
            if len(self._ring) < self.capacity:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self.capacity

    @property
    def count(self) -> int:
        return self._count

    def export(self, include_sample: bool = False) -> dict:
        """Summary dict. ``buckets`` carries cumulative counts per finite
        ``le`` bound (the +Inf count is ``count``); with
        ``include_sample`` the sorted sample ring rides along so a
        federator can merge exact percentiles instead of interpolating
        from buckets."""
        with self._lock:
            n, s = self._count, self._sum
            lo, hi = self._min, self._max
            sample = sorted(self._ring)
            per_bucket = list(self._bucket_counts)
        cum, acc = [], 0
        for c in per_bucket[:-1]:
            acc += c
            cum.append(acc)
        buckets = {"bounds": list(self.bounds), "counts": cum}
        if n == 0:
            return {"count": 0, "buckets": buckets}
        m = len(sample)
        out = {
            "count": n,
            "sum": s,
            "mean": s / n,
            "min": lo,
            "max": hi,
            "p50": sample[m // 2],
            "p90": sample[min(m - 1, int(m * 0.9))],
            "p99": sample[min(m - 1, int(m * 0.99))],
            "buckets": buckets,
        }
        if include_sample:
            out["sample"] = sample
        return out


def _get(name: str, cls, **kw):
    m = _metrics.get(name)
    if m is None:
        with _reg_lock:
            m = _metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                _metrics[name] = m
    if not isinstance(m, cls):
        raise MXNetError("telemetry metric %r is a %s, not a %s"
                         % (name, type(m).__name__, cls.__name__))
    return m


def counter(name: str) -> Counter:
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    return _get(name, Gauge)


def histogram(name: str, capacity: int = 512,
              bounds: Optional[Sequence[float]] = None) -> Histogram:
    return _get(name, Histogram, capacity=capacity, bounds=bounds)


def peek(name: str, kind: str = "counter"):
    """Read a metric's current raw value WITHOUT registering it: a
    counter/gauge value, or a histogram's running sum when
    ``kind="hist_sum"``. Returns None for an unregistered name. This is
    the step-trace delta reader — it must not materialize metrics the
    instrumented layers never touched."""
    m = _metrics.get(name)
    if m is None:
        return None
    if isinstance(m, Histogram):
        return m._sum if kind == "hist_sum" else m._count
    return m._value


def metrics_items():
    """Sorted (name, metric) pairs — the exposition-format reader."""
    with _reg_lock:
        return sorted(_metrics.items())


# -- recording fast path (one flag check, immediate return when off) ----
def inc(name: str, n: int = 1):
    if not _ENABLED:
        return
    counter(name).inc(n)


def set_gauge(name: str, v: float):
    if not _ENABLED:
        return
    gauge(name).set(v)


def observe(name: str, v: float):
    if not _ENABLED:
        return
    histogram(name).observe(v)


# -- spans ---------------------------------------------------------------
@contextlib.contextmanager
def span(name: str):
    """Host-side named interval. Recorded into the bounded span ring and
    the ``span.<name>_ms`` histogram; while an XLA trace capture is
    running it additionally nests a ``TraceAnnotation`` so the interval
    shows up inside the device trace too."""
    if not _ENABLED:
        yield
        return
    ann = None
    try:
        from . import profiler as _prof

        if _prof.is_running():
            import jax

            ann = jax.profiler.TraceAnnotation(name)
            ann.__enter__()
    except Exception:
        ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        _spans.append((name, threading.get_ident(), t0, dur))
        observe("span.%s_ms" % name, dur * 1e3)


def spans():
    """The buffered (name, tid, start_perf_counter, duration_s) tuples."""
    return list(_spans)


def write_chrome_trace(path: str, extra_events: Optional[list] = None):
    """Write buffered host spans in the chrome trace event format.
    Timestamps are wall-clock microseconds, the same clock domain the
    XLA trace stamps, so both load side by side in Perfetto.

    ``process_name``/``thread_name`` metadata events (ph="M") name
    this process's lanes, so a multi-process merged trace reads as
    named lanes instead of bare pids/tids. ``extra_events`` appends
    pre-built chrome events verbatim — the distributed tracer
    (:mod:`mxnet_tpu.dtrace`) reuses this writer for its merged
    cross-process span trees."""
    import sys

    spans = list(_spans)
    pid = os.getpid()
    thread_names = {t.ident: t.name for t in threading.enumerate()}
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": "%s (pid %d)"
                      % (os.path.basename(sys.argv[0] or "python"),
                         pid)}}]
    for tid in sorted({tid for _, tid, _, _ in spans}):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid,
                     "args": {"name": thread_names.get(
                         tid, "tid-%d" % tid)}})
    events = meta + [
        {"name": name, "ph": "X", "cat": "host",
         "pid": pid, "tid": tid,
         "ts": (t0 + _EPOCH) * 1e6, "dur": dur * 1e6}
        for name, tid, t0, dur in spans]
    if extra_events:
        events.extend(extra_events)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)


# -- exporters -----------------------------------------------------------
def snapshot() -> dict:
    """All metrics as a nested dict keyed by the dot-split name
    (``engine.push`` -> ``{"engine": {"push": N}}``). Counters export
    ints, gauges floats, histograms summary dicts. A name that is both
    a leaf and a prefix keeps its leaf value under ``"_value"``."""
    with _reg_lock:
        items = sorted(_metrics.items())
    out: dict = {}
    for name, m in items:
        parts = name.split(".")
        node = out
        for p in parts[:-1]:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = {} if nxt is None else {"_value": nxt}
                node[p] = nxt
            node = nxt
        leaf = parts[-1]
        if isinstance(node.get(leaf), dict):
            node[leaf]["_value"] = m.export()
        else:
            node[leaf] = m.export()
    return out


# -- federation primitives -----------------------------------------------
def sample_quantile(sample: Sequence[float], q: float) -> Optional[float]:
    """Quantile of a pre-sorted sample, using the same nearest-rank
    convention as :meth:`Histogram.export` (``sample[int(m*q)]``,
    clamped). Returns None for an empty sample."""
    m = len(sample)
    if m == 0:
        return None
    if q == 0.5:
        return sample[m // 2]
    return sample[min(m - 1, int(m * q))]


def bucket_quantile(buckets: dict, count: int, q: float,
                    hi: Optional[float] = None) -> Optional[float]:
    """Quantile interpolated from a cumulative-bucket export
    (``{"bounds": [...], "counts": [...]}``). Linear within the bucket
    holding the target rank; ranks past the last finite bound clamp to
    ``hi`` (observed max) or the last bound. Returns None when empty."""
    if count <= 0 or not buckets:
        return None
    bounds = buckets.get("bounds") or []
    counts = buckets.get("counts") or []
    target = q * count
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in zip(bounds, counts):
        if cum >= target:
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return bound
            frac = (target - prev_cum) / in_bucket
            return prev_bound + (bound - prev_bound) * min(1.0, frac)
        prev_bound, prev_cum = bound, cum
    if hi is not None:
        return float(hi)
    return float(bounds[-1]) if bounds else None


def _is_hist_export(d) -> bool:
    return isinstance(d, dict) and "count" in d and "buckets" in d


_MERGE_SAMPLE_CAP = 4096


def _merge_hist(a: dict, b: dict) -> dict:
    ba, bb = a.get("buckets") or {}, b.get("buckets") or {}
    bounds_a = list(ba.get("bounds") or [])
    bounds_b = list(bb.get("bounds") or [])
    if bounds_a and bounds_b and bounds_a != bounds_b:
        raise MXNetError(
            "merge_snapshots: conflicting histogram bucket bounds "
            "%r vs %r — federation requires one ladder per metric"
            % (bounds_a, bounds_b))
    n = int(a.get("count", 0)) + int(b.get("count", 0))
    bounds = bounds_a or bounds_b
    counts_a = list(ba.get("counts") or [0] * len(bounds))
    counts_b = list(bb.get("counts") or [0] * len(bounds))
    counts = [x + y for x, y in zip(counts_a, counts_b)]
    out = {"count": n, "buckets": {"bounds": bounds, "counts": counts}}
    if n == 0:
        return out
    out["sum"] = float(a.get("sum", 0.0)) + float(b.get("sum", 0.0))
    out["mean"] = out["sum"] / n
    mins = [v for v in (a.get("min"), b.get("min")) if v is not None]
    maxs = [v for v in (a.get("max"), b.get("max")) if v is not None]
    if mins:
        out["min"] = min(mins)
    if maxs:
        out["max"] = max(maxs)
    sample = sorted((a.get("sample") or []) + (b.get("sample") or []))
    if len(sample) > _MERGE_SAMPLE_CAP:
        # decimate evenly rather than truncate: keeps the distribution
        step = len(sample) / float(_MERGE_SAMPLE_CAP)
        sample = [sample[int(i * step)] for i in range(_MERGE_SAMPLE_CAP)]
    if sample:
        out["sample"] = sample
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            out[key] = sample_quantile(sample, q)
    else:
        for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
            v = bucket_quantile(out["buckets"], n, q, hi=out.get("max"))
            if v is not None:
                out[key] = v
    return out


def _merge_into(dst: dict, src: dict, path: str):
    for k, v in src.items():
        here = "%s.%s" % (path, k) if path else k
        if k not in dst:
            dst[k] = copy.deepcopy(v)
            continue
        cur = dst[k]
        if _is_hist_export(cur) and _is_hist_export(v):
            dst[k] = _merge_hist(cur, v)
        elif _is_hist_export(cur) or _is_hist_export(v):
            raise MXNetError("merge_snapshots: %r is a histogram in one "
                             "snapshot and not in another" % here)
        elif isinstance(cur, dict) and isinstance(v, dict):
            _merge_into(cur, v, here)
        elif isinstance(cur, (int, float)) and isinstance(v, (int, float)):
            # counters (ints) and gauges (floats) both merge by sum; a
            # federator wanting per-source gauge fan-out keeps the
            # original snapshots alongside the merged view
            dst[k] = cur + v
        else:
            raise MXNetError("merge_snapshots: %r has mismatched kinds "
                             "(%s vs %s)" % (here, type(cur).__name__,
                                             type(v).__name__))


def merge_snapshots(snaps: Sequence[dict]) -> dict:
    """Merge N :func:`snapshot`-shaped nested dicts into one fleet
    rollup: counters and gauges sum, histogram exports merge bucket-wise
    (counts/sums add, min/max combine, samples concatenate, percentiles
    recomputed — exact from merged samples when every input carried one,
    bucket-interpolated otherwise). Histograms with conflicting bucket
    ladders raise :class:`MXNetError` rather than silently misbinning.
    Inputs are never mutated."""
    out: dict = {}
    for s in snaps:
        if s:
            _merge_into(out, s, "")
    return out


def dump_jsonl(path: str, extra: Optional[dict] = None) -> dict:
    """Append ONE step record (timestamp, step index, full snapshot) to
    ``path``. Crash-safe: the whole line goes out in a single
    ``os.write`` on an ``O_APPEND`` fd — POSIX appends of one write are
    atomic with respect to other appenders, so a crash (or a concurrent
    writer) can interleave or truncate at worst the final line, never
    the middle of an earlier record the flight recorder will read back.
    ``MXNET_TPU_TELEMETRY_FSYNC=1`` adds an fsync per record for
    machines where losing the last buffered lines to a power cut
    matters more than the syscall cost."""
    global _step
    with _step_lock:
        _step += 1
        step = _step
    rec = {"ts": round(time.time(), 6), "step": step,
           "telemetry": snapshot()}
    if extra:
        rec.update(extra)
    line = (json.dumps(rec) + "\n").encode("utf-8")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
        if _env.get("MXNET_TPU_TELEMETRY_FSYNC"):
            os.fsync(fd)
    finally:
        os.close(fd)
    return rec


def reset():
    """Clear every metric, span, and the step counter (bench/test
    isolation). The enabled flag is left as-is."""
    global _step
    with _reg_lock:
        _metrics.clear()
    _spans.clear()
    with _step_lock:
        _step = 0
