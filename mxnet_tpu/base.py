"""Base types shared across the framework.

TPU-native re-design of the reference's ``include/mxnet/base.h`` +
``dmlc-core`` basics: error type, dtype table (mshadow ``MSHADOW_TYPE_SWITCH``
equivalent -> jnp dtypes), environment-variable config access
(``dmlc::GetEnv`` equivalent), and the string-keyed registry that backs
operators / io iterators / optimizers / metrics / initializers
(``DMLC_REGISTRY_*`` equivalent, see reference ``include/mxnet/operator.h:537``).
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

import numpy as np

__all__ = [
    "MXNetError", "mx_real_t", "mx_uint", "DTYPE_NP_TO_ID", "DTYPE_ID_TO_NP",
    "getenv", "Registry", "string_types",
]


class MXNetError(Exception):
    """Error raised by the framework (reference: ``MXGetLastError`` convention,
    ``src/c_api/c_api_error.h``)."""


string_types = (str,)
mx_uint = int
mx_real_t = np.float32

# dtype id table mirrors mshadow type flags so saved params stay stable
# (reference: mshadow MSHADOW_TYPE_SWITCH over fp32/fp64/fp16/u8/i32).
DTYPE_NP_TO_ID: Dict[Any, int] = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    # TPU-native addition: bfloat16 is the MXU-preferred compute dtype
    np.dtype(np.bool_): 8,
}
try:
    import ml_dtypes  # jax dependency, provides the numpy bfloat16 scalar type

    DTYPE_NP_TO_ID[np.dtype(ml_dtypes.bfloat16)] = 7
except Exception:  # pragma: no cover
    pass

DTYPE_ID_TO_NP = {v: k for k, v in DTYPE_NP_TO_ID.items()}


def getenv(name: str, default):
    """``dmlc::GetEnv`` equivalent with type coercion from the default."""
    val = os.environ.get(name)
    if val is None:
        return default
    if isinstance(default, bool):
        return val.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(val)
    if isinstance(default, float):
        return float(val)
    return val


T = TypeVar("T")


class Registry(Generic[T]):
    """String-keyed registry (``DMLC_REGISTRY_ENABLE`` equivalent).

    Used for operators, io iterators, optimizers, metrics, initializers and
    ndarray functions, mirroring the reference's dmlc registries.
    """

    _registries: Dict[str, "Registry"] = {}

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, T] = {}
        Registry._registries[kind] = self

    @staticmethod
    def get_registry(kind: str) -> "Registry":
        if kind not in Registry._registries:
            Registry(kind)
        return Registry._registries[kind]

    def register(self, name: Optional[str] = None, override: bool = False) -> Callable[[T], T]:
        def _do(entry: T) -> T:
            key = name or getattr(entry, "__name__", None)
            if key is None:
                raise MXNetError("registry entry needs a name")
            lname = key.lower()
            if lname in self._entries and not override:
                raise MXNetError(
                    "%s '%s' already registered" % (self.kind, key))
            self._entries[lname] = entry
            return entry
        return _do

    def find(self, name: str) -> Optional[T]:
        return self._entries.get(name.lower())

    def get(self, name: str) -> T:
        entry = self.find(name)
        if entry is None:
            raise MXNetError("%s '%s' is not registered; known: %s" % (
                self.kind, name, sorted(self._entries)))
        return entry

    def list_names(self) -> List[str]:
        return sorted(self._entries)

    def items(self):
        return self._entries.items()
