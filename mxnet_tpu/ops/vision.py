"""Vision-specific operators.

TPU-native implementations of the reference's detection/vision ops:
ROIPooling (``src/operator/roi_pooling-inl.h``, Faster R-CNN),
SpatialTransformer (``spatial_transformer-inl.h``), Correlation
(``correlation-inl.h``). All are formulated as dense masked/gather
computations with static shapes so XLA can fuse and tile them; a Pallas
kernel can later replace the ROIPooling inner loop.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .registry import Operator, Param, REQUIRED, register_op


def _jax():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


@register_op("ROIPooling")
class ROIPooling(Operator):
    """Max-pool features inside scaled ROIs to a fixed grid (reference
    roi_pooling-inl.h). rois: (num_rois, 5) = [batch_idx, x1, y1, x2, y2]."""

    name_hint = "roipooling"
    PARAMS = {
        "pooled_size": Param("shape", REQUIRED, "(h, w)"),
        "spatial_scale": Param(float, REQUIRED),
    }

    def list_arguments(self):
        return ["data", "rois"]

    def infer_shape(self, in_shapes):
        data, rois = in_shapes
        if data is None or rois is None:
            raise MXNetError("ROIPooling: shapes unknown")
        ph, pw = self.pooled_size
        return [data, rois], [(rois[0], data[1], ph, pw)], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        jax = _jax()
        data, rois = inputs
        n, c, h, w = data.shape
        ph, pw = self.pooled_size
        scale = self.spatial_scale

        def one_roi(roi):
            batch_idx = roi[0].astype(jnp.int32)
            # reference: round(coord * scale); end is inclusive
            x1 = jnp.round(roi[1] * scale)
            y1 = jnp.round(roi[2] * scale)
            x2 = jnp.round(roi[3] * scale)
            y2 = jnp.round(roi[4] * scale)
            roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
            roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
            bin_h = roi_h / ph
            bin_w = roi_w / pw
            img = data[batch_idx]  # (c, h, w)

            iy = jnp.arange(ph, dtype=data.dtype)
            ix = jnp.arange(pw, dtype=data.dtype)
            # bin [start, end) with floor/ceil like the reference
            ys = jnp.clip(jnp.floor(y1 + iy * bin_h), 0, h)        # (ph,)
            ye = jnp.clip(jnp.ceil(y1 + (iy + 1) * bin_h), 0, h)
            xs = jnp.clip(jnp.floor(x1 + ix * bin_w), 0, w)
            xe = jnp.clip(jnp.ceil(x1 + (ix + 1) * bin_w), 0, w)
            rows = jnp.arange(h, dtype=data.dtype)
            cols = jnp.arange(w, dtype=data.dtype)
            row_mask = (rows[None, :] >= ys[:, None]) & (rows[None, :] < ye[:, None])  # (ph, h)
            col_mask = (cols[None, :] >= xs[:, None]) & (cols[None, :] < xe[:, None])  # (pw, w)
            mask = row_mask[:, None, :, None] & col_mask[None, :, None, :]  # (ph,pw,h,w)
            neg = jnp.asarray(-jnp.inf, data.dtype)
            masked = jnp.where(mask[None], img[:, None, None, :, :], neg)
            pooled = masked.max(axis=(3, 4))  # (c, ph, pw)
            # empty bins yield 0 like the reference
            return jnp.where(jnp.isfinite(pooled), pooled, 0.0)

        out = jax.vmap(one_roi)(rois)
        return [out.astype(data.dtype)], []


@register_op("SpatialTransformer")
class SpatialTransformer(Operator):
    """Affine spatial transformer with bilinear sampling (reference
    spatial_transformer-inl.h; transform_type=affine, sampler=bilinear)."""

    name_hint = "spatialtransformer"
    PARAMS = {
        "target_shape": Param("shape", REQUIRED, "(h, w)"),
        "transform_type": Param(str, "affine"),
        "sampler_type": Param(str, "bilinear"),
    }

    def list_arguments(self):
        return ["data", "loc"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SpatialTransformer: data shape unknown")
        th, tw = self.target_shape
        return ([data, (data[0], 6)],
                [(data[0], data[1], th, tw)], [])

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        jax = _jax()
        data, loc = inputs
        n, c, h, w = data.shape
        th, tw = self.target_shape

        # normalized target grid in [-1, 1]
        yt, xt = jnp.meshgrid(jnp.linspace(-1, 1, th),
                              jnp.linspace(-1, 1, tw), indexing="ij")
        ones = jnp.ones_like(xt)
        grid = jnp.stack([xt.ravel(), yt.ravel(), ones.ravel()])  # (3, th*tw)

        def one(img, theta):
            theta = theta.reshape(2, 3)
            src = theta @ grid                       # (2, th*tw) in [-1,1]
            xs = (src[0] + 1.0) * (w - 1) / 2.0
            ys = (src[1] + 1.0) * (h - 1) / 2.0
            x0 = jnp.floor(xs)
            y0 = jnp.floor(ys)
            wx = xs - x0
            wy = ys - y0

            def sample(yi, xi):
                inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
                yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
                vals = img[:, yc, xc]                # (c, th*tw)
                return jnp.where(inb[None], vals, 0.0)

            out = (sample(y0, x0) * (1 - wy) * (1 - wx)
                   + sample(y0, x0 + 1) * (1 - wy) * wx
                   + sample(y0 + 1, x0) * wy * (1 - wx)
                   + sample(y0 + 1, x0 + 1) * wy * wx)
            return out.reshape(c, th, tw)

        out = jax.vmap(one)(data, loc)
        return [out.astype(data.dtype)], []


@register_op("Correlation")
class Correlation(Operator):
    """Cross-correlation of two feature maps over a displacement window
    (reference correlation-inl.h, FlowNet-style)."""

    name_hint = "correlation"
    PARAMS = {
        "kernel_size": Param(int, 1),
        "max_displacement": Param(int, 1),
        "stride1": Param(int, 1),
        "stride2": Param(int, 1),
        "pad_size": Param(int, 0),
        "is_multiply": Param(bool, True),
    }

    def list_arguments(self):
        return ["data1", "data2"]

    def _out_geom(self, data):
        n, c, h, w = data
        pad = self.pad_size
        bor = self.max_displacement + (self.kernel_size - 1) // 2
        ph, pw = h + 2 * pad, w + 2 * pad
        out_h = int(np.ceil((ph - bor * 2) / self.stride1))
        out_w = int(np.ceil((pw - bor * 2) / self.stride1))
        d = 2 * (self.max_displacement // self.stride2) + 1
        return out_h, out_w, d * d

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Correlation: shapes unknown")
        out_h, out_w, top_c = self._out_geom(data)
        return [data, data], [(data[0], top_c, out_h, out_w)], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        d1, d2 = inputs
        n, c, h, w = d1.shape
        pad = self.pad_size
        k = self.kernel_size
        md = self.max_displacement
        s2 = self.stride2
        out_h, out_w, _ = self._out_geom(d1.shape)
        bor = md + (k - 1) // 2

        p1 = jnp.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        p2 = jnp.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        disps = range(-md, md + 1, s2)
        maps = []
        for dy in disps:
            for dx in disps:
                shifted = jnp.roll(p2, (-dy, -dx), axis=(2, 3))
                if self.is_multiply:
                    prod = (p1 * shifted).sum(axis=1) / c   # (n, ph, pw)
                else:
                    prod = -jnp.abs(p1 - shifted).sum(axis=1) / c
                window = prod[:, bor:bor + out_h * self.stride1:self.stride1,
                              bor:bor + out_w * self.stride1:self.stride1]
                maps.append(window)
        out = jnp.stack(maps, axis=1)
        return [out.astype(d1.dtype)], []


@register_op("uniform", aliases=["_sample_uniform"])
class SampleUniform(Operator):
    """Symbolic random source (reference sample_op: uniform)."""

    name_hint = "uniform"
    PARAMS = {
        "low": Param(float, 0.0),
        "high": Param(float, 1.0),
        "shape": Param("shape", REQUIRED),
    }

    def list_arguments(self):
        return []

    def infer_shape(self, in_shapes):
        return [], [tuple(self.params["shape"])], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        if ctx.rng is None:
            raise MXNetError("uniform op needs an rng (bind via executor)")
        return [jax.random.uniform(ctx.rng, tuple(self.params["shape"]),
                                   minval=self.low, maxval=self.high)], []


@register_op("normal", aliases=["_sample_normal"])
class SampleNormal(Operator):
    name_hint = "normal"
    PARAMS = {
        "loc": Param(float, 0.0),
        "scale": Param(float, 1.0),
        "shape": Param("shape", REQUIRED),
    }

    def list_arguments(self):
        return []

    def infer_shape(self, in_shapes):
        return [], [tuple(self.params["shape"])], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        if ctx.rng is None:
            raise MXNetError("normal op needs an rng (bind via executor)")
        return [self.loc + self.scale *
                jax.random.normal(ctx.rng, tuple(self.params["shape"]))], []


@register_op("softmax_cross_entropy")
class SoftmaxCrossEntropy(Operator):
    """Per-example softmax cross-entropy loss value (reference
    loss_binary_op-inl.h)."""

    name_hint = "softmax_cross_entropy"

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("softmax_cross_entropy: data shape unknown")
        return [data, (data[0],)], [(1,)], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        data, label = inputs
        logp = jax.nn.log_softmax(data, axis=-1)
        lab = label.astype(jnp.int32)
        nll = -logp[jnp.arange(data.shape[0]), lab]
        return [jnp.sum(nll).reshape((1,))], []
