"""Operator registry and base class.

TPU-native re-design of the reference's operator interface
(``include/mxnet/operator.h:165-485`` ``OperatorProperty``): each operator
declares its arguments/outputs/auxiliary states, shape+type inference, and a
pure ``apply`` function over jnp arrays. Gradients come from jax autodiff
through ``apply``; ops whose reference gradient differs from the
mathematical one (SoftmaxOutput, MakeLoss, BlockGrad, regression outputs)
implement it with ``jax.custom_vjp`` inside ``apply``.

Registration (reference ``MXNET_REGISTER_OP_PROPERTY``,
``operator.h:537``) also auto-generates the symbol creation function, like
the reference's C-registry-driven codegen
(``python/mxnet/symbol.py`` ``_init_symbol_module``).

Parameter declaration mirrors ``dmlc::Parameter``/``DMLC_DECLARE_PARAMETER``:
a ``PARAMS`` dict of :class:`Param` specs with type/default/doc, parsed and
validated at symbol creation and round-tripped through JSON serialization.
"""
from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, Registry

__all__ = ["Param", "REQUIRED", "Operator", "OpContext", "register_op",
           "OP_REGISTRY", "create_operator"]

OP_REGISTRY: Registry = Registry.get_registry("operator")

REQUIRED = object()


class Param:
    """One declared parameter (``DMLC_DECLARE_PARAMETER`` field)."""

    def __init__(self, ptype, default=REQUIRED, doc=""):
        self.ptype = ptype      # int/float/bool/str/'shape'
        self.default = default
        self.doc = doc

    def parse(self, value):
        if value is None:
            return None
        if self.ptype == "shape":
            if isinstance(value, str):
                value = ast.literal_eval(value)
            if isinstance(value, int):
                value = (value,)
            return tuple(int(v) for v in value)
        if self.ptype is bool:
            if isinstance(value, str):
                return value.lower() in ("1", "true", "yes")
            return bool(value)
        if self.ptype is int and isinstance(value, str):
            return int(value)
        if self.ptype is float and isinstance(value, str):
            return float(value)
        return self.ptype(value)


class OpContext:
    """Per-invocation context handed to ``apply`` (reference ``OpContext``,
    ``operator.h:44-62``): training mode flag and a PRNG key (the reference's
    per-device ``Random<xpu>`` resource, ``include/mxnet/resource.h``)."""

    __slots__ = ("is_train", "rng")

    def __init__(self, is_train: bool, rng=None):
        self.is_train = is_train
        self.rng = rng


class Operator:
    """Base class: one instance per graph node, holding parsed params."""

    # subclasses override
    PARAMS: Dict[str, Param] = {}
    name_hint = "op"

    def __init__(self, **kwargs):
        unknown = [k for k in kwargs if k not in self.PARAMS]
        if unknown:
            # report typos before "missing required" — a misspelled kwarg
            # otherwise surfaces as a confusing missing-parameter error
            raise MXNetError("%s: unknown parameters %s (known: %s)" % (
                type(self).__name__, sorted(unknown), sorted(self.PARAMS)))
        params = {}
        for key, spec in self.PARAMS.items():
            if key in kwargs:
                params[key] = spec.parse(kwargs.pop(key))
            elif spec.default is REQUIRED:
                raise MXNetError("%s: required parameter '%s' missing"
                                 % (type(self).__name__, key))
            else:
                params[key] = spec.default
        self.params = params

    def __getattr__(self, item):
        try:
            return self.__dict__["params"][item]
        except KeyError:
            raise AttributeError(item)

    # -- interface ---------------------------------------------------------
    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    @property
    def num_outputs(self) -> int:
        return len(self.list_outputs())

    def infer_shape(self, in_shapes: List[Optional[Tuple[int, ...]]]):
        """Returns (in_shapes, out_shapes, aux_shapes); must fill unknowns or
        raise (reference ``OperatorProperty::InferShape``)."""
        shape = _first_known(in_shapes)
        if shape is None:
            raise MXNetError("%s: cannot infer shape" % type(self).__name__)
        return [shape] * len(in_shapes), [shape], []

    def infer_type(self, in_types, out_types=None):
        """Default same-dtype rule: inputs and outputs all share the first
        known dtype, looking at BOTH sides so the symbol-level fixpoint can
        propagate backward (reference ``InferNodeTypes`` iterates nodes in
        both directions). Returns None-filled lists when nothing is known —
        never speculate; the symbol-level pass defaults leftover variables
        to float32 afterwards."""
        import numpy as np

        known = list(in_types) + list(out_types or [])
        dtype = next((t for t in known if t is not None), None)
        if dtype is None:
            return (list(in_types), [None] * self.num_outputs,
                    [np.float32] * len(self.list_auxiliary_states()))
        return ([dtype] * len(in_types), [dtype] * self.num_outputs,
                [np.float32] * len(self.list_auxiliary_states()))

    def apply(self, ctx: OpContext, inputs: Sequence[Any], aux: Sequence[Any]):
        """Pure function over jnp arrays -> (outputs, new_aux)."""
        raise NotImplementedError

    # serialization helpers
    def param_str_dict(self) -> Dict[str, str]:
        return {k: str(v) for k, v in self.params.items() if v is not None}


def _first_known(shapes):
    for s in shapes:
        if s is not None:
            return s
    return None


def register_op(name: str, aliases: Sequence[str] = ()):
    """Register an Operator subclass under ``name`` (+ aliases)."""

    def _do(cls):
        cls.op_name = name
        cls.op_aliases = tuple(aliases)
        OP_REGISTRY.register(name)(cls)
        for alias in aliases:
            # the registry keys case-insensitively, so an alias that only
            # differs in case (e.g. "crop" for "Crop") already resolves —
            # it still matters for namespace exposure via op_aliases
            if OP_REGISTRY.find(alias) is cls:
                continue
            OP_REGISTRY.register(alias)(cls)
        return cls
    return _do


def create_operator(op_name: str, **params) -> Operator:
    cls = OP_REGISTRY.get(op_name)
    return cls(**params)


def get_operator_class(op_name: str):
    """Registered Operator class, or None if unknown (no raise)."""
    return OP_REGISTRY.find(op_name)


def same_shape_binary(in_shapes):
    """Shape rule for elementwise binary ops: both inputs same shape."""
    known = _first_known(in_shapes)
    if known is None:
        raise MXNetError("cannot infer shape of elementwise op")
    filled = [s if s is not None else known for s in in_shapes]
    for s in filled:
        if s != known:
            raise MXNetError("elementwise op shape mismatch: %s" % (filled,))
    return filled, [known], []
