"""Neural-network layer operators.

TPU-native implementations of the reference's layer ops
(``src/operator/*-inl.h``). Convolution/pooling/batchnorm lower straight to
XLA (``lax.conv_general_dilated`` / ``reduce_window``), which tiles them
onto the MXU — the TPU equivalent of the reference's cuDNN fast path
(``src/operator/cudnn_*-inl.h``). Layout is NCHW like the reference; XLA
re-lays-out internally for the systolic array.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from .registry import Operator, OpContext, Param, REQUIRED, register_op


def _jax():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# FullyConnected (reference src/operator/fully_connected-inl.h)
# ---------------------------------------------------------------------------
@register_op("FullyConnected")
class FullyConnected(Operator):
    name_hint = "fullyconnected"
    PARAMS = {
        "num_hidden": Param(int, REQUIRED, "number of hidden units"),
        "no_bias": Param(bool, False, "whether to disable bias"),
    }

    def list_arguments(self):
        return ["data", "weight"] if self.no_bias else ["data", "weight", "bias"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("FullyConnected: data shape unknown")
        n = data[0]
        d = int(np.prod(data[1:])) if len(data) > 1 else 1
        shapes = [data, (self.num_hidden, d)]
        if not self.no_bias:
            shapes.append((self.num_hidden,))
        return shapes, [(n, self.num_hidden)], []

    def apply(self, ctx, inputs, aux):
        # XLA is the measured fast path: the Pallas fused_linear kernel
        # benched 0.1-1.0x of the XLA dot on a v5e across 256..8192 sizes
        # (tools/bench_pallas.py, table in docs/pallas.md), so the former
        # MXNET_TPU_PALLAS gate was retired. The kernels remain available
        # explicitly via ops.pallas_kernels / rtc.
        jnp = _jnp()
        data = inputs[0]
        w = inputs[1]
        x = data.reshape((data.shape[0], -1))
        out = jnp.dot(x, w.T)
        if not self.no_bias:
            out = out + inputs[2]
        return [out], []


# ---------------------------------------------------------------------------
# Activation (reference src/operator/activation-inl.h)
# ---------------------------------------------------------------------------
@register_op("Activation")
class Activation(Operator):
    name_hint = "activation"
    PARAMS = {"act_type": Param(str, REQUIRED, "relu/sigmoid/tanh/softrelu")}

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        act = self.act_type
        if act == "relu":
            out = jnp.maximum(x, 0)
        elif act == "sigmoid":
            out = _jax().nn.sigmoid(x)
        elif act == "tanh":
            out = jnp.tanh(x)
        elif act == "softrelu":
            out = _jax().nn.softplus(x)
        else:
            raise MXNetError("unknown act_type %s" % act)
        return [out], []


@register_op("LeakyReLU")
class LeakyReLU(Operator):
    """reference src/operator/leaky_relu-inl.h (leaky/prelu/elu/rrelu)."""

    name_hint = "leakyrelu"
    PARAMS = {
        "act_type": Param(str, "leaky"),
        "slope": Param(float, 0.25),
        "lower_bound": Param(float, 0.125),
        "upper_bound": Param(float, 0.334),
    }

    def list_arguments(self):
        return ["data", "gamma"] if self.act_type == "prelu" else ["data"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("LeakyReLU: data shape unknown")
        if self.act_type == "prelu":
            return [data, (data[1],)], [data], []
        return [data], [data], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        act = self.act_type
        if act == "leaky":
            out = jnp.where(x > 0, x, self.slope * x)
        elif act == "elu":
            out = jnp.where(x > 0, x, self.slope * (jnp.exp(x) - 1.0))
        elif act == "prelu":
            gamma = inputs[1].reshape((1, -1) + (1,) * (x.ndim - 2))
            out = jnp.where(x > 0, x, gamma * x)
        elif act == "rrelu":
            if ctx.is_train and ctx.rng is not None:
                slope = _jax().random.uniform(
                    ctx.rng, x.shape, dtype=x.dtype,
                    minval=self.lower_bound, maxval=self.upper_bound)
            else:
                slope = (self.lower_bound + self.upper_bound) / 2.0
            out = jnp.where(x > 0, x, slope * x)
        else:
            raise MXNetError("unknown act_type %s" % act)
        return [out], []


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (reference convolution-inl.h:76-489)
# ---------------------------------------------------------------------------
def _conv_out_dim(x, k, s, p, d):
    dk = d * (k - 1) + 1
    return (x + 2 * p - dk) // s + 1


def _spatial_letters(nd: int) -> str:
    """Spatial chars for dimension_numbers; must avoid N/C/O/I."""
    if nd == 1:
        return "W"
    if nd == 2:
        return "HW"
    if nd == 3:
        return "DHW"
    raise MXNetError("unsupported spatial rank %d" % nd)


_VALID_LAYOUTS = {"NCW", "NWC", "NCHW", "NHWC", "NCDHW", "NDHWC"}


def _layout_is_nhwc(layout):
    """Validate + classify a layout string: channels-last -> True.
    None means the NCHW default; anything outside the supported set is
    an error (a typo'd layout must not silently run as NCHW)."""
    if layout is None:
        return False
    lay = str(layout).upper()
    if lay not in _VALID_LAYOUTS:
        raise MXNetError("unsupported layout '%s' (supported: %s)"
                         % (layout, sorted(_VALID_LAYOUTS)))
    return lay.endswith("C")


class _ConvBase(Operator):
    PARAMS = {
        "kernel": Param("shape", REQUIRED, "(kh, kw)"),
        "num_filter": Param(int, REQUIRED),
        "stride": Param("shape", None),
        "pad": Param("shape", None),
        "dilate": Param("shape", None),
        "num_group": Param(int, 1),
        "no_bias": Param(bool, False),
        "workspace": Param(int, 512, "ignored; XLA plans memory"),
        "cudnn_tune": Param(str, None, "ignored on TPU"),
        "layout": Param(str, None, "NCHW (default) or NHWC — TPU-first "
                        "extension: NHWC keeps channels on the minor "
                        "(lane) axis, the layout the TPU vector unit "
                        "wants, avoiding compiler-inserted transposes"),
    }

    def _is_nhwc(self):
        return _layout_is_nhwc(self.layout)

    def list_arguments(self):
        return ["data", "weight"] if self.no_bias else ["data", "weight", "bias"]

    def _norm_params(self):
        nd = len(self.kernel)
        stride = self.stride or (1,) * nd
        pad = self.pad or (0,) * nd
        dilate = self.dilate or (1,) * nd
        return self.kernel, stride, pad, dilate


@register_op("Convolution")
class Convolution(_ConvBase):
    name_hint = "convolution"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Convolution: data shape unknown")
        kernel, stride, pad, dilate = self._norm_params()
        if len(data) != len(kernel) + 2:
            raise MXNetError("Convolution: data must be N,C,spatial*%d" % len(kernel))
        nhwc = self._is_nhwc()
        n = data[0]
        c = data[-1] if nhwc else data[1]
        sp_in = data[1:-1] if nhwc else data[2:]
        wshape = (self.num_filter, c // self.num_group) + tuple(kernel)
        out_sp = tuple(_conv_out_dim(sp_in[i], kernel[i], stride[i],
                                     pad[i], dilate[i])
                       for i in range(len(kernel)))
        shapes = [data, wshape]
        if not self.no_bias:
            shapes.append((self.num_filter,))
        out = (n,) + out_sp + (self.num_filter,) if nhwc \
            else (n, self.num_filter) + out_sp
        return shapes, [out], []

    def apply(self, ctx, inputs, aux):
        lax = _jax().lax
        kernel, stride, pad, dilate = self._norm_params()
        nd = len(kernel)
        spatial = _spatial_letters(nd)
        nhwc = self._is_nhwc()
        if nd == 2 and self.num_group == 1:
            out = self._pallas_conv(inputs, stride, pad, dilate, nhwc)
            if out is not None:
                return [out], []
        # weight stays OIHW in BOTH layouts (checkpoint-canonical); XLA
        # re-lays it out at compile time, so NHWC costs no transposes at
        # runtime on TPU
        act = "N" + spatial + "C" if nhwc else "NC" + spatial
        dn = (act, "OI" + spatial, act)
        out = lax.conv_general_dilated(
            inputs[0], inputs[1],
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=self.num_group,
            preferred_element_type=inputs[0].dtype
            if inputs[0].dtype == np.float32 else None,
        )
        if not self.no_bias:
            bshape = (1,) + (1,) * nd + (-1,) if nhwc \
                else (1, -1) + (1,) * nd
            out = out + inputs[2].reshape(bshape)
        return [out], []

    def _pallas_conv(self, inputs, stride, pad, dilate, nhwc):
        """Trace-time routing of the conv *backward* through the Pallas
        dgrad/wgrad kernels: taken when `MXNET_TPU_PALLAS_CONV` pins it
        or the autotune cache holds a measured win for this chip. The
        forward stays `conv_general_dilated` either way (docs/pallas.md:
        XLA's forward conv already wins); `pallas_kernels.conv2d`
        returns None for any shape its tiles cannot cover, keeping the
        XLA path per-layer. All decisions happen while tracing — zero
        per-dispatch cost."""
        from .. import autotune as _autotune
        from . import pallas_kernels as _pk

        x = inputs[0]
        sig = _autotune.aval_sig(x.shape, x.dtype)
        if not _autotune.conv_kernel_enabled(sig):
            return None
        return _pk.conv2d(
            x, inputs[1], bias=None if self.no_bias else inputs[2],
            stride=stride, pad=pad, dilate=dilate,
            num_group=self.num_group, nhwc=nhwc,
            tiles=_autotune.conv_tiles(sig))


@register_op("Deconvolution")
class Deconvolution(_ConvBase):
    """Transposed convolution (reference deconvolution-inl.h); weight layout
    (C_in, num_filter/num_group, kh, kw) as in the reference."""

    name_hint = "deconvolution"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Deconvolution: data shape unknown")
        kernel, stride, pad, dilate = self._norm_params()
        nhwc = self._is_nhwc()
        n = data[0]
        c = data[-1] if nhwc else data[1]
        sp_in = data[1:-1] if nhwc else data[2:]
        wshape = (c, self.num_filter // self.num_group) + tuple(kernel)
        out_sp = tuple((sp_in[i] - 1) * stride[i] - 2 * pad[i] + kernel[i]
                       for i in range(len(kernel)))
        shapes = [data, wshape]
        if not self.no_bias:
            shapes.append((self.num_filter,))
        out = (n,) + out_sp + (self.num_filter,) if nhwc \
            else (n, self.num_filter) + out_sp
        return shapes, [out], []

    def apply(self, ctx, inputs, aux):
        # gradient-of-conv formulation: input dilation by stride, padding
        # (dk-1-p), spatially flipped kernel — output (i-1)*s - 2p + dk,
        # matching the reference's deconv shape rule
        lax = _jax().lax
        jnp = _jnp()
        kernel, stride, pad, dilate = self._norm_params()
        nd = len(kernel)
        spatial = _spatial_letters(nd)
        act = "N" + spatial + "C" if self._is_nhwc() else "NC" + spatial
        dn = (act, "IO" + spatial, act)
        w = inputs[1]
        w = w[(slice(None), slice(None)) + (slice(None, None, -1),) * nd]
        padding = []
        for i in range(nd):
            dk = dilate[i] * (kernel[i] - 1) + 1
            padding.append((dk - 1 - pad[i], dk - 1 - pad[i]))
        out = lax.conv_general_dilated(
            inputs[0], w,
            window_strides=(1,) * nd,
            padding=padding,
            lhs_dilation=stride,
            rhs_dilation=dilate,
            dimension_numbers=dn,
            feature_group_count=self.num_group,
        )
        if not self.no_bias:
            bshape = (1,) + (1,) * nd + (-1,) if self._is_nhwc() \
                else (1, -1) + (1,) * nd
            out = out + inputs[2].reshape(bshape)
        return [out], []


# ---------------------------------------------------------------------------
# Pooling (reference pooling-inl.h; mshadow pool/unpool)
# ---------------------------------------------------------------------------
@register_op("Pooling")
class Pooling(Operator):
    name_hint = "pooling"
    PARAMS = {
        "kernel": Param("shape", REQUIRED),
        "pool_type": Param(str, "max", "max/avg/sum"),
        "stride": Param("shape", None),
        "pad": Param("shape", None),
        "global_pool": Param(bool, False),
        "layout": Param(str, None, "NCHW (default) or NHWC"),
    }

    def _is_nhwc(self):
        return _layout_is_nhwc(self.layout)

    def _sp_base(self):
        return 1 if self._is_nhwc() else 2

    def _norm(self, data_shape):
        nd = len(self.kernel)
        base = self._sp_base()
        if self.global_pool:
            kernel = tuple(data_shape[base + i] for i in range(nd))
            return kernel, (1,) * nd, (0,) * nd
        return self.kernel, self.stride or (1,) * nd, self.pad or (0,) * nd

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Pooling: data shape unknown")
        kernel, stride, pad = self._norm(data)
        base = self._sp_base()
        if self.global_pool:
            out_sp = (1,) * len(kernel)
        else:
            out_sp = tuple(
                (data[base + i] + 2 * pad[i] - kernel[i]) // stride[i] + 1
                for i in range(len(kernel)))
        if self._is_nhwc():
            out = (data[0],) + out_sp + (data[-1],)
        else:
            out = data[:2] + out_sp
        return [data], [out], []

    def apply(self, ctx, inputs, aux):
        lax = _jax().lax
        jnp = _jnp()
        x = inputs[0]
        kernel, stride, pad = self._norm(x.shape)
        nd = len(kernel)
        if self._is_nhwc():
            window = (1,) + tuple(kernel) + (1,)
            strides = (1,) + tuple(stride) + (1,)
            padding = ((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),)
        else:
            window = (1, 1) + tuple(kernel)
            strides = (1, 1) + tuple(stride)
            padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
        is_float = jnp.issubdtype(x.dtype, jnp.floating)  # incl. bfloat16
        if self.pool_type == "max":
            init = -jnp.inf if is_float else np.iinfo(x.dtype).min
            out = lax.reduce_window(x, init, lax.max, window, strides, padding)
        elif self.pool_type in ("avg", "sum"):
            out = lax.reduce_window(x, 0.0 if is_float else 0,
                                    lax.add, window, strides, padding)
            if self.pool_type == "avg":
                out = out / float(np.prod(kernel))
        else:
            raise MXNetError("unknown pool_type %s" % self.pool_type)
        return [out], []


# ---------------------------------------------------------------------------
# BatchNorm (reference batch_norm-inl.h; aux moving_mean/moving_var)
# ---------------------------------------------------------------------------
# CuDNNBatchNorm (reference cudnn_batch_norm.cc) is the same op with a
# vendor fast path; XLA is the single backend here, so it aliases.
@register_op("BatchNorm", aliases=("CuDNNBatchNorm",))
class BatchNorm(Operator):
    name_hint = "batchnorm"
    PARAMS = {
        "eps": Param(float, 1e-3),
        "momentum": Param(float, 0.9),
        "fix_gamma": Param(bool, True),
        "use_global_stats": Param(bool, False),
        "axis": Param(int, 1, "channel axis (1 = NCHW; -1 for NHWC)"),
    }

    def list_arguments(self):
        return ["data", "gamma", "beta"]

    def list_auxiliary_states(self):
        return ["moving_mean", "moving_var"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("BatchNorm: data shape unknown")
        c = (data[self.axis],)
        return [data, c, c], [data], [c, c]

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        jax = _jax()
        x, gamma, beta = inputs
        moving_mean, moving_var = aux
        caxis = self.axis % x.ndim
        axes = tuple(i for i in range(x.ndim) if i != caxis)
        bshape = tuple(-1 if i == caxis else 1 for i in range(x.ndim))
        if self.fix_gamma:
            gamma = jnp.ones_like(gamma)
        use_batch_stats = ctx.is_train and not self.use_global_stats
        if use_batch_stats:
            # statistics in f32 even under bf16 mixed precision: a batch
            # mean over 1e5+ elements accumulated in bf16 loses the
            # moving averages (standard TPU mixed-precision practice).
            # One-pass form (var = E[x^2] - E[x]^2): both reductions read
            # x once and XLA fuses them into a single multi-output reduce
            # over the conv output — the two-pass (x - mean)^2 form
            # materializes the centered activations and dominated the
            # ResNet step (the conv MXU work is the minority of the time).
            x32 = x.astype(jnp.promote_types(x.dtype, jnp.float32))
            mean = jnp.mean(x32, axis=axes)
            meansq = jnp.mean(jnp.square(x32), axis=axes)
            var = jnp.maximum(meansq - jnp.square(mean), 0.0)
            m = self.momentum
            new_mean = moving_mean * m + jax.lax.stop_gradient(
                mean.astype(moving_mean.dtype)) * (1 - m)
            new_var = moving_var * m + jax.lax.stop_gradient(
                var.astype(moving_var.dtype)) * (1 - m)
            new_aux = [new_mean, new_var]
        else:
            mean = jax.lax.stop_gradient(moving_mean)
            var = jax.lax.stop_gradient(moving_var)
            new_aux = [moving_mean, moving_var]
        # fold the affine into one per-channel scale/shift applied to x in
        # its own dtype: a single fused multiply-add pass instead of
        # subtract/normalize/scale/shift chains
        inv = jax.lax.rsqrt(var + self.eps)
        scale = (gamma.astype(inv.dtype) * inv).astype(x.dtype)
        shift = (beta.astype(inv.dtype) - mean * gamma.astype(inv.dtype)
                 * inv).astype(x.dtype)
        out = None
        if caxis == x.ndim - 1:
            out = self._fused_norm(x, scale, shift)
        if out is None:
            out = x * scale.reshape(bshape) + shift.reshape(bshape)
        return [out], new_aux

    def _fused_norm(self, x, scale, shift):
        """Trace-time: the one-pass Pallas scale/shift kernel (forward
        and backward each a single VMEM pass, f32 math) when the
        autotune cache holds a measured `block_rows` win for this chip.
        None -> the XLA elementwise path. The kernel's scale/shift
        cotangents chain through the traced batch statistics, so
        training gradients are unchanged."""
        from .. import autotune as _autotune
        from . import pallas_kernels as _pk

        br = _autotune.norm_block_rows(
            _autotune.aval_sig(x.shape, x.dtype))
        if not br:
            return None
        return _pk.fused_norm_act(x, scale, shift, act="none",
                                  block_rows=br)


# ---------------------------------------------------------------------------
# Dropout (reference dropout-inl.h)
# ---------------------------------------------------------------------------
@register_op("Dropout")
class Dropout(Operator):
    name_hint = "dropout"
    PARAMS = {"p": Param(float, 0.5)}

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        if not ctx.is_train or self.p <= 0.0 or ctx.rng is None:
            return [x], []
        jax = _jax()
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
        return [_jnp().where(mask, x / keep, 0.0).astype(x.dtype)], []


# ---------------------------------------------------------------------------
# Softmax output + friends (reference softmax_output-inl.h)
# ---------------------------------------------------------------------------
def _softmax(x, axis):
    return _jax().nn.softmax(x, axis=axis)


@register_op("SoftmaxOutput", aliases=["Softmax"])
class SoftmaxOutput(Operator):
    """Fused softmax + cross-entropy gradient: forward is softmax(data);
    backward is (softmax - one_hot(label)) * grad_scale, ignoring the head
    gradient (reference softmax_output-inl.h; this is why MXNet training
    loops call ``backward()`` with no head grads)."""

    name_hint = "softmax"
    PARAMS = {
        "grad_scale": Param(float, 1.0),
        "ignore_label": Param(float, -1.0),
        "multi_output": Param(bool, False),
        "use_ignore": Param(bool, False),
        "preserve_shape": Param(bool, False,
                                "softmax over the last axis of an N-d "
                                "input with (shape[:-1]) labels"),
        "normalization": Param(str, "null", "null/batch/valid"),
    }

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SoftmaxOutput: data shape unknown")
        if self.multi_output:
            label = (data[0],) + tuple(data[2:])
        elif self.preserve_shape:
            # reference softmax_output-inl.h preserve_shape: softmax on
            # the trailing axis, one label per leading position (the
            # time-major RNN head: data (T, N, V), label (T, N))
            label = tuple(data[:-1])
        else:
            label = (data[0],)
        return [data, label], [data], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        axis = 1 if self.multi_output else -1
        nclass_axis = 1 if self.multi_output else len(inputs[0].shape) - 1
        op = self

        @jax.custom_vjp
        def f(data, label):
            return _softmax(data, axis)

        def f_fwd(data, label):
            out = _softmax(data, axis)
            return out, (out, label)

        def f_bwd(res, g):
            out, label = res
            nclass = out.shape[nclass_axis]
            lab = label.astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, nclass, dtype=out.dtype,
                                    axis=nclass_axis)
            grad = out - onehot
            valid = None
            if op.use_ignore:
                valid = (label != op.ignore_label)
                mask = jnp.expand_dims(valid, nclass_axis).astype(out.dtype)
                grad = grad * mask
            scale = op.grad_scale
            if op.normalization == "batch":
                grad = grad / out.shape[0]
            elif op.normalization == "valid":
                if valid is None:
                    valid = jnp.ones(label.shape, dtype=bool)
                grad = grad / jnp.maximum(jnp.sum(valid.astype(out.dtype)), 1.0)
            grad = grad * scale
            return grad.astype(out.dtype), jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0], inputs[1])], []


@register_op("SoftmaxActivation")
class SoftmaxActivation(Operator):
    """Plain softmax with true autodiff gradient (reference
    softmax_activation-inl.h)."""

    name_hint = "softmaxactivation"
    PARAMS = {"mode": Param(str, "instance", "instance/channel")}

    def apply(self, ctx, inputs, aux):
        axis = 1 if self.mode == "channel" else -1
        return [_softmax(inputs[0], axis)], []


class _RegressionOutput(Operator):
    """Base for regression outputs (reference regression_output-inl.h):
    forward transforms data, backward is (out - label) * grad_scale / batch
    regardless of head gradient."""

    PARAMS = {"grad_scale": Param(float, 1.0)}
    transform = staticmethod(lambda x: x)
    grad_fn = staticmethod(lambda out, label: out - label)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("%s: data shape unknown" % type(self).__name__)
        return [data, data], [data], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        op = self

        @jax.custom_vjp
        def f(data, label):
            return op.transform(data)

        def f_fwd(data, label):
            out = op.transform(data)
            return out, (out, label)

        def f_bwd(res, g):
            out, label = res
            label = label.reshape(out.shape)
            num = float(np.prod(out.shape[1:])) or 1.0
            grad = op.grad_fn(out, label) * (op.grad_scale / num)
            return grad.astype(out.dtype), jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0], inputs[1])], []


@register_op("LinearRegressionOutput")
class LinearRegressionOutput(_RegressionOutput):
    name_hint = "linearregressionoutput"


@register_op("LogisticRegressionOutput")
class LogisticRegressionOutput(_RegressionOutput):
    name_hint = "logisticregressionoutput"
    transform = staticmethod(lambda x: _jax().nn.sigmoid(x))


@register_op("MAERegressionOutput")
class MAERegressionOutput(_RegressionOutput):
    name_hint = "maeregressionoutput"
    grad_fn = staticmethod(lambda out, label: _jnp().sign(out - label))


@register_op("SVMOutput")
class SVMOutput(Operator):
    """reference svmoutput-inl.h: hinge-loss output layer."""

    name_hint = "svmoutput"
    PARAMS = {
        "margin": Param(float, 1.0),
        "regularization_coefficient": Param(float, 1.0),
        "use_linear": Param(bool, False),
    }

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SVMOutput: data shape unknown")
        return [data, (data[0],)], [data], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        op = self

        @jax.custom_vjp
        def f(data, label):
            return data

        def f_fwd(data, label):
            return data, (data, label)

        def f_bwd(res, g):
            data, label = res
            lab = label.astype(jnp.int32)
            onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
            sign = 2.0 * onehot - 1.0          # +1 at true class, -1 elsewhere
            viol = (op.margin - sign * data) > 0
            if op.use_linear:
                grad = -sign * viol.astype(data.dtype)
            else:
                grad = -2.0 * sign * jnp.maximum(op.margin - sign * data, 0.0)
            grad = grad * op.regularization_coefficient
            return grad.astype(data.dtype), jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0], inputs[1])], []


# ---------------------------------------------------------------------------
# Embedding (reference embedding-inl.h)
# ---------------------------------------------------------------------------
@register_op("Embedding")
class Embedding(Operator):
    name_hint = "embedding"
    PARAMS = {
        "input_dim": Param(int, REQUIRED),
        "output_dim": Param(int, REQUIRED),
    }

    def list_arguments(self):
        return ["data", "weight"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Embedding: data shape unknown")
        return ([data, (self.input_dim, self.output_dim)],
                [tuple(data) + (self.output_dim,)], [])

    def infer_type(self, in_types, out_types=None):
        # indices keep their own dtype (often int); weight/output share a
        # float dtype and must NOT inherit the index dtype. No speculative
        # float32 — an unknown weight stays None until the symbol-level
        # default pass (it is a plain variable there).
        data_t, weight_t = in_types
        out_t = (out_types or [None])[0]
        w = weight_t if weight_t is not None else out_t
        return [data_t, w], [w], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        data, weight = inputs
        idx = _jax().lax.stop_gradient(data).astype(jnp.int32)
        return [jnp.take(weight, idx, axis=0)], []


# ---------------------------------------------------------------------------
# Normalization ops
# ---------------------------------------------------------------------------
@register_op("LRN")
class LRN(Operator):
    """Cross-channel local response normalization (reference lrn-inl.h)."""

    name_hint = "lrn"
    PARAMS = {
        "alpha": Param(float, 1e-4),
        "beta": Param(float, 0.75),
        "knorm": Param(float, 2.0),
        "nsize": Param(int, REQUIRED),
    }

    def apply(self, ctx, inputs, aux):
        lax = _jax().lax
        x = inputs[0]
        half = self.nsize // 2
        sq = x * x
        window = (1, self.nsize) + (1,) * (x.ndim - 2)
        padding = ((0, 0), (half, self.nsize - 1 - half)) + ((0, 0),) * (x.ndim - 2)
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, (1,) * x.ndim, padding)
        denom = (self.knorm + (self.alpha / self.nsize) * ssum) ** self.beta
        return [x / denom], []


@register_op("L2Normalization")
class L2Normalization(Operator):
    """reference l2_normalization-inl.h (mode=instance/channel/spatial)."""

    name_hint = "l2normalization"
    PARAMS = {
        "eps": Param(float, 1e-10),
        "mode": Param(str, "instance"),
    }

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        if self.mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif self.mode == "channel":
            axes = (1,)
        elif self.mode == "spatial":
            axes = tuple(range(2, x.ndim))
        else:
            raise MXNetError("unknown mode %s" % self.mode)
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return [x / norm], []


# ---------------------------------------------------------------------------
# UpSampling (reference upsampling-inl.h; nearest only — bilinear is a
# Deconvolution in the reference too)
# ---------------------------------------------------------------------------
@register_op("UpSampling")
class UpSampling(Operator):
    name_hint = "upsampling"
    PARAMS = {
        "scale": Param(int, REQUIRED),
        "sample_type": Param(str, "nearest"),
        "num_args": Param(int, 1),
    }

    def list_arguments(self):
        return ["data"] if self.num_args == 1 else \
            ["arg%d" % i for i in range(self.num_args)]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("UpSampling: data shape unknown")
        out = data[:2] + tuple(s * self.scale for s in data[2:])
        return [data], [out], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        for ax in range(2, x.ndim):
            x = jnp.repeat(x, self.scale, axis=ax)
        return [x], []
