"""Sequence operators + fused RNN.

TPU-native equivalents of the reference's sequence ops
(``src/operator/sequence_{last,mask,reverse}-inl.h``) and of the cuDNN fused
RNN (``src/operator/cudnn_rnn-inl.h:127-150``: RNN_RELU/RNN_TANH/LSTM/GRU).
The recurrence is a ``jax.lax.scan`` over time with one fused cell matmul
per step — the XLA-idiomatic formulation: weights stay resident in
registers/VMEM across iterations and the (x,h)->gates matmul hits the MXU.

Layout is time-major TNC like the reference RNN op. Parameters are a single
flat vector like cuDNN blobs; layout is documented in :func:`rnn_param_size`
(per layer/direction: W_x, W_h, b_x, b_h, gates in cuDNN order).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..base import MXNetError
from .registry import Operator, Param, REQUIRED, register_op


def _jax():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# sequence_* ops: per-example lengths along time-major axis
# ---------------------------------------------------------------------------
class _SeqBase(Operator):
    PARAMS = {"use_sequence_length": Param(bool, False)}

    def list_arguments(self):
        if self.use_sequence_length:
            return ["data", "sequence_length"]
        return ["data"]


@register_op("SequenceLast")
class SequenceLast(_SeqBase):
    name_hint = "sequencelast"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SequenceLast: data shape unknown")
        shapes = [data]
        if self.use_sequence_length:
            shapes.append((data[1],))
        return shapes, [tuple(data[1:])], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        if self.use_sequence_length:
            idx = (inputs[1].astype(jnp.int32) - 1).clip(0, x.shape[0] - 1)
            return [x[idx, jnp.arange(x.shape[1])]], []
        return [x[-1]], []


@register_op("SequenceMask")
class SequenceMask(_SeqBase):
    name_hint = "sequencemask"
    PARAMS = dict(_SeqBase.PARAMS, value=Param(float, 0.0))

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SequenceMask: data shape unknown")
        shapes = [data]
        if self.use_sequence_length:
            shapes.append((data[1],))
        return shapes, [data], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        if not self.use_sequence_length:
            return [x], []
        lengths = inputs[1].astype(jnp.int32)
        t = jnp.arange(x.shape[0])[:, None]
        mask = (t < lengths[None, :]).reshape(
            (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2))
        return [jnp.where(mask, x, jnp.asarray(self.value, x.dtype))], []


@register_op("SequenceReverse")
class SequenceReverse(_SeqBase):
    name_hint = "sequencereverse"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SequenceReverse: data shape unknown")
        shapes = [data]
        if self.use_sequence_length:
            shapes.append((data[1],))
        return shapes, [data], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        if not self.use_sequence_length:
            return [x[::-1]], []
        lengths = inputs[1].astype(_jnp().int32)
        t = jnp.arange(x.shape[0])[:, None]
        # index of reversed element within each valid prefix
        src = jnp.where(t < lengths[None, :], lengths[None, :] - 1 - t, t)
        return [x[src, jnp.arange(x.shape[1])[None, :]]], []


# ---------------------------------------------------------------------------
# fused RNN (reference rnn-inl.h param struct :70-100 + cudnn_rnn-inl.h)
# ---------------------------------------------------------------------------
_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers: int, input_size: int, state_size: int,
                   bidirectional: bool, mode: str) -> int:
    """Total flat parameter count. Layout (contiguous, per layer then per
    direction): W_x (G*H, in), W_h (G*H, H), b_x (G*H), b_h (G*H)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (in_size + state_size + 2)
    return size


@register_op("RNN")
class RNN(Operator):
    name_hint = "rnn"
    PARAMS = {
        "state_size": Param(int, REQUIRED),
        "num_layers": Param(int, REQUIRED),
        "mode": Param(str, REQUIRED, "rnn_relu/rnn_tanh/lstm/gru"),
        "bidirectional": Param(bool, False),
        "p": Param(float, 0.0, "dropout between layers"),
        "state_outputs": Param(bool, False),
    }

    def list_arguments(self):
        args = ["data", "parameters", "state"]
        if self.mode == "lstm":
            args.append("state_cell")
        return args

    def list_outputs(self):
        outs = ["output"]
        if self.state_outputs:
            outs.append("state")
            if self.mode == "lstm":
                outs.append("state_cell")
        return outs

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("RNN: data shape unknown")
        t, n, input_size = data
        dirs = 2 if self.bidirectional else 1
        h = self.state_size
        psize = rnn_param_size(self.num_layers, input_size, h,
                               self.bidirectional, self.mode)
        state_shape = (self.num_layers * dirs, n, h)
        shapes = [data, (psize,), state_shape]
        if self.mode == "lstm":
            shapes.append(state_shape)
        outs = [(t, n, h * dirs)]
        if self.state_outputs:
            outs.append(state_shape)
            if self.mode == "lstm":
                outs.append(state_shape)
        return shapes, outs, []

    # -- flat parameter unpacking ------------------------------------------
    def _slices(self, input_size):
        gates = _GATES[self.mode]
        dirs = 2 if self.bidirectional else 1
        h = self.state_size
        offset = 0
        layout = []  # [layer][dir] = dict of (offset, shape)
        for layer in range(self.num_layers):
            in_size = input_size if layer == 0 else h * dirs
            per_dir = []
            for _ in range(dirs):
                entry = {}
                for key, shape in (("wx", (gates * h, in_size)),
                                   ("wh", (gates * h, h)),
                                   ("bx", (gates * h,)),
                                   ("bh", (gates * h,))):
                    size = int(np.prod(shape))
                    entry[key] = (offset, shape)
                    offset += size
                per_dir.append(entry)
            layout.append(per_dir)
        return layout

    def _cell(self, mode):
        jnp = _jnp()
        jax = _jax()
        h_units = self.state_size

        if mode in ("rnn_relu", "rnn_tanh"):
            act = (lambda v: jnp.maximum(v, 0)) if mode == "rnn_relu" else jnp.tanh

            def cell(carry, xw, wh, bh):
                h_prev, = carry
                h = act(xw + jnp.dot(h_prev, wh.T) + bh)
                return (h,), h
        elif mode == "lstm":
            def cell(carry, xw, wh, bh):
                h_prev, c_prev = carry
                gates = xw + jnp.dot(h_prev, wh.T) + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i = jax.nn.sigmoid(i)
                f = jax.nn.sigmoid(f)
                g = jnp.tanh(g)
                o = jax.nn.sigmoid(o)
                c = f * c_prev + i * g
                h = o * jnp.tanh(c)
                return (h, c), h
        elif mode == "gru":
            def cell(carry, xw, wh, bh):
                h_prev, = carry
                hw = jnp.dot(h_prev, wh.T) + bh
                xr, xz, xn = jnp.split(xw, 3, axis=-1)
                hr, hz, hn = jnp.split(hw, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h = (1 - z) * n + z * h_prev
                return (h,), h
        else:
            raise MXNetError("unknown RNN mode %s" % mode)
        return cell

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        data = inputs[0]
        params = inputs[1]
        state0 = inputs[2]
        cell0 = inputs[3] if self.mode == "lstm" else None
        t, n, input_size = data.shape
        dirs = 2 if self.bidirectional else 1
        layout = self._slices(input_size)
        cell = self._cell(self.mode)

        def take(off_shape):
            off, shape = off_shape
            return jax.lax.dynamic_slice_in_dim(
                params, off, int(np.prod(shape))).reshape(shape)

        x = data
        h_finals, c_finals = [], []
        for layer in range(self.num_layers):
            outs_dirs = []
            for d in range(dirs):
                entry = layout[layer][d]
                wx, wh = take(entry["wx"]), take(entry["wh"])
                bx, bh = take(entry["bx"]), take(entry["bh"])
                sidx = layer * dirs + d
                h0 = state0[sidx]
                carry = (h0, cell0[sidx]) if self.mode == "lstm" else (h0,)
                seq = x if d == 0 else x[::-1]
                # hoist the input projection out of the scan: one big
                # (T*N, in) x (in, G*H) matmul for the MXU
                xw_all = jnp.einsum("tni,gi->tng", seq, wx) + bx

                def step(carry, xw, _wh=wh, _bh=bh):
                    new_carry, h = cell(carry, xw, _wh, _bh)
                    return new_carry, h

                final, hs = jax.lax.scan(step, carry, xw_all)
                if d == 1:
                    hs = hs[::-1]
                outs_dirs.append(hs)
                h_finals.append(final[0])
                if self.mode == "lstm":
                    c_finals.append(final[1])
            x = outs_dirs[0] if dirs == 1 else jnp.concatenate(outs_dirs, axis=-1)
            if self.p > 0 and ctx.is_train and ctx.rng is not None \
                    and layer < self.num_layers - 1:
                keep = 1.0 - self.p
                key = jax.random.fold_in(ctx.rng, layer)
                mask = jax.random.bernoulli(key, keep, x.shape)
                x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

        outputs = [x]
        if self.state_outputs:
            outputs.append(jnp.stack(h_finals))
            if self.mode == "lstm":
                outputs.append(jnp.stack(c_finals))
        return outputs, []
