"""Connectionist Temporal Classification loss.

Parity target: the reference's warp-ctc plugin
(``/root/reference/plugin/warpctc/warpctc-inl.h:33-200``), whose operator
``WarpCTC(data, label, label_length, input_length)`` outputs ``softmax(data)``
and back-propagates the CTC gradient, ignoring the head gradient (same
contract as SoftmaxOutput).

TPU-native design: instead of binding Baidu's hand-written CUDA kernels, the
CTC forward-backward is expressed as a log-semiring alpha recursion over
``lax.scan`` — a single differentiable XLA computation. The gradient
``softmax - posterior`` falls out of ``jax.grad`` of the negative
log-likelihood, which is mathematically identical to warp-ctc's explicit
beta-pass gradient but needs no hand-written backward kernel: XLA
differentiates the scan (it keeps the alpha trellis as the residual, the
same memory warp-ctc spends on its workspace).

Conventions match warp-ctc: blank label is 0; ``label`` rows are padded with
0 (``labelLengths`` in the reference counts non-blank entries, ibid.:86-99).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .registry import Operator, Param, register_op


def _jax():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


_NEG_INF = -1e30  # finite stand-in for log(0): keeps grads NaN-free


def ctc_neg_log_likelihood(log_probs, labels, blank: int = 0):
    """Per-sequence CTC negative log-likelihood.

    log_probs: (T, B, A) log-softmax scores. labels: (B, L) int32, padded
    with ``blank``; the real length of row b is its non-blank count.
    Differentiable: ``jax.grad`` of its sum w.r.t. the pre-softmax logits
    yields warp-ctc's ``softmax - posterior`` gradient.
    """
    jax = _jax()
    jnp = _jnp()
    lax = jax.lax

    log_probs = log_probs.astype(jnp.float32)
    T, B, A = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    labels = labels.astype(jnp.int32)
    # compact non-blank labels to the left (reference removeBlank,
    # warpctc-inl.h:100-109, tolerates blanks anywhere in the row);
    # stable argsort of the blank mask left-justifies the real labels
    order = jnp.argsort(labels == blank, axis=1, stable=True)
    labels = jnp.take_along_axis(labels, order, axis=1)

    # extended label sequence: blank-interleaved (b, l1, b, l2, ..., b)
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    label_len = (labels != blank).sum(axis=1)          # (B,)

    # s may take the diagonal skip s-2 -> s only onto a non-blank that
    # differs from the previous non-blank (standard CTC transition rule)
    skip_ok = jnp.zeros((B, S), dtype=bool)
    if S > 2:
        skip_ok = skip_ok.at[:, 2:].set(
            (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    batch_idx = jnp.arange(B)[:, None]                  # (B, 1)

    def emit(lp_t):
        return lp_t[batch_idx, ext]                     # (B, S)

    alpha0 = jnp.full((B, S), _NEG_INF, dtype=jnp.float32)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, :, blank])
    if S > 1:
        alpha0 = alpha0.at[:, 1].set(log_probs[0][batch_idx[:, 0], ext[:, 1]])

    def shift(a, k):
        pad = jnp.full((B, k), _NEG_INF, dtype=a.dtype)
        return jnp.concatenate([pad, a[:, :S - k]], axis=1)

    def step(alpha, lp_t):
        stay = alpha
        diag = shift(alpha, 1)
        skip = jnp.where(skip_ok, shift(alpha, 2), _NEG_INF)
        m = jnp.maximum(jnp.maximum(stay, diag), skip)
        tot = m + jnp.log(jnp.exp(stay - m) + jnp.exp(diag - m)
                          + jnp.exp(skip - m))
        return tot + emit(lp_t), None

    alpha_T, _ = lax.scan(step, alpha0, log_probs[1:])

    # end states: s = 2*len (trailing blank) and s = 2*len - 1 (last label)
    end = 2 * label_len                                 # (B,)
    a_end = alpha_T[batch_idx[:, 0], end]
    a_last = jnp.where(label_len > 0,
                       alpha_T[batch_idx[:, 0],
                               jnp.maximum(end - 1, 0)], _NEG_INF)
    m = jnp.maximum(a_end, a_last)
    ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_last - m))
    return -ll                                          # (B,)


@register_op("WarpCTC")
class WarpCTC(Operator):
    """warp-ctc plugin parity: forward = row softmax of ``data``
    ((T*B, A), time-major blocks, ibid.:67-84); backward = CTC gradient
    w.r.t. ``data``, head gradient ignored (ibid.:113-199)."""

    name_hint = "warpctc"
    PARAMS = {
        "label_length": Param(int, 0, "padded label length per sequence"),
        "input_length": Param(int, 0, "time steps per sequence"),
    }

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("WarpCTC: data shape unknown")
        if len(data) != 2:
            raise MXNetError("WarpCTC: data must be 2D (T*B, alphabet)")
        if self.input_length <= 0 or data[0] % self.input_length:
            raise MXNetError("WarpCTC: rows %d not divisible by "
                             "input_length %d" % (data[0], self.input_length))
        minibatch = data[0] // self.input_length
        # reference InferShape assigns a FLAT label (label_length*minibatch,)
        # (warpctc-inl.h:237-239); a user-supplied (minibatch, label_length)
        # is accepted too — apply() reshapes either form
        label = in_shapes[1]
        if label is None or int(np.prod(label)) != \
                minibatch * self.label_length:
            label = (minibatch * self.label_length,)
        return [data, label], [data], []

    def infer_type(self, in_types, out_types=None):
        dt = in_types[0] or (out_types[0] if out_types else None) \
            or np.float32
        return [dt, in_types[1] or np.float32], [dt], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        T = self.input_length
        A = inputs[0].shape[1]
        B = inputs[0].shape[0] // T

        @jax.custom_vjp
        def f(data, label):
            return jax.nn.softmax(data.astype(jnp.float32), axis=-1)

        def f_fwd(data, label):
            return f(data, label), (data, label)

        def f_bwd(res, g):
            data, label = res
            lab2d = label.reshape(B, -1)

            def nll(d):
                lp = jax.nn.log_softmax(
                    d.astype(jnp.float32).reshape(T, B, A), axis=-1)
                return ctc_neg_log_likelihood(lp, lab2d).sum()

            grad = jax.grad(nll)(data).astype(data.dtype)
            return grad, jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0], inputs[1])], []


@register_op("CTCLoss", aliases=("ctc_loss",))
class CTCLoss(Operator):
    """Per-sequence CTC loss as an ordinary differentiable op (the shape
    later MXNet exposes as ``contrib.ctc_loss``): data (T, B, A) raw
    activations, label (B, L) 0-padded -> loss (B,). Gradients flow via
    autodiff of the scan; use with MakeLoss-style heads."""

    name_hint = "ctcloss"
    PARAMS = {}

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shapes):
        data, label = in_shapes
        if data is None:
            raise MXNetError("CTCLoss: data shape unknown")
        if len(data) != 3:
            raise MXNetError("CTCLoss: data must be (T, B, alphabet)")
        if label is None:
            raise MXNetError("CTCLoss: label shape unknown (B, L)")
        return [data, label], [(data[1],)], []

    def infer_type(self, in_types, out_types=None):
        dt = in_types[0] or (out_types[0] if out_types else None) \
            or np.float32
        return [dt, in_types[1] or np.float32], [np.float32], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        lp = jax.nn.log_softmax(inputs[0].astype(_jnp().float32), axis=-1)
        return [ctc_neg_log_likelihood(lp, inputs[1])], []
