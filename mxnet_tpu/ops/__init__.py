"""Operator library: registry + op families.

Importing this package registers all built-in operators (the reference's
static registration via ``MXNET_REGISTER_OP_PROPERTY`` /
``MXNET_REGISTER_SIMPLE_OP``).
"""
from .registry import (Operator, OpContext, Param, REQUIRED, OP_REGISTRY,
                       register_op, create_operator)
from . import nn      # noqa: F401
from . import tensor  # noqa: F401
from . import seq     # noqa: F401
from . import vision  # noqa: F401
from . import ctc     # noqa: F401
# plugin ops that register symbols (caffe bridge); imported here so the
# creators exist before symbol-module generation
from ..plugins import caffe_op as _caffe_op  # noqa: F401,E402

__all__ = ["Operator", "OpContext", "Param", "REQUIRED", "OP_REGISTRY",
           "register_op", "create_operator"]
