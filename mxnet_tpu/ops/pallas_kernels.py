"""Pallas TPU kernels — the cuDNN-class fast path.

The reference reached peak GPU throughput with hand-tuned cuDNN kernels
(``src/operator/cudnn_*-inl.h``); on TPU the analogue is Pallas: kernels
that tile HBM->VMEM explicitly and feed the MXU. This module provides the
first such kernel — a fused linear layer (tiled matmul + bias + activation
in one VMEM-resident pass) used by FullyConnected when shapes are
tile-aligned — plus the availability plumbing shared by future kernels
(conv/pool/attention).

Gradients route through ``jax.custom_vjp``: the backward matmuls are plain
XLA (already MXU-optimal); only the fused forward is hand-written.

On CPU the kernels run in interpreter mode so the whole path is testable
without hardware.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from .. import env as _env

__all__ = ["fused_linear", "flash_attention", "pallas_available",
           "conv2d", "conv_dgrad", "conv_wgrad", "conv_backward_applicable",
           "fused_norm_act", "norm_act_applicable"]

# float32 MXU-friendly tiles (sublane 8, lane 128)
TILE_M = 128
TILE_N = 128
TILE_K = 128


@functools.lru_cache(None)
def pallas_available() -> bool:
    if _env.get("MXNET_TPU_NO_PALLAS"):
        return False
    try:
        import jax
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(None)
def _interpret_mode() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _linear_call(x, w_t, bias, act: str):
    """Tiled (M,K)x(K,N) matmul with fused bias+activation epilogue."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    _, n = w_t.shape
    grid = (m // TILE_M, n // TILE_N, k // TILE_K)
    nk = grid[2]

    def kernel(x_ref, w_ref, b_ref, o_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        o_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                            preferred_element_type=jnp.float32)

        @pl.when(kk == nk - 1)
        def _():
            acc = o_ref[:] + b_ref[:]
            if act == "relu":
                acc = jnp.maximum(acc, 0.0)
            elif act == "tanh":
                acc = jnp.tanh(acc)
            elif act == "sigmoid":
                acc = jax.nn.sigmoid(acc)
            o_ref[:] = acc

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_M, TILE_K), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((TILE_K, TILE_N), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, TILE_N), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((TILE_M, TILE_N), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=_interpret_mode(),
    )(x, w_t, bias)


def fused_linear(x, weight, bias=None, act: str = "none") -> Optional[object]:
    """out = act(x @ weight.T + bias) via the Pallas kernel.

    ``weight`` uses the framework layout (num_hidden, in_dim). Returns None
    when the kernel does not apply (shape misalignment / pallas missing) —
    callers fall back to the XLA path.
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    m, k = x.shape
    n = weight.shape[0]
    if (m % TILE_M or k % TILE_K or n % TILE_N
            or x.dtype != jnp.float32 or weight.dtype != jnp.float32):
        return None
    b = bias if bias is not None else jnp.zeros((n,), jnp.float32)

    @jax.custom_vjp
    def f(x, w, b):
        return _linear_call(x, w.T, b.reshape(1, n), act)

    def f_fwd(x, w, b):
        out = f(x, w, b)
        return out, (x, w, b, out)

    def f_bwd(res, g):
        x, w, b, out = res
        if act == "relu":
            g = jnp.where(out > 0, g, 0.0)
        elif act == "tanh":
            g = g * (1.0 - out * out)
        elif act == "sigmoid":
            g = g * out * (1.0 - out)
        gx = jnp.dot(g, w)
        gw = jnp.dot(g.T, x)
        gb = jnp.sum(g, axis=0)
        return gx, gw, gb

    f.defvjp(f_fwd, f_bwd)
    return f(x, weight, b)


# ---------------------------------------------------------------------------
# Flash attention
# ---------------------------------------------------------------------------

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _flash_call(q, k, v, scale: float, causal: bool):
    """Online-softmax tiled attention. q/k/v: (BH, T, D) float32.

    The cuDNN-class fused kernel of this framework (the reference's GPU
    fast path was cudnn_*-inl.h): one pass over K/V blocks per Q block,
    carrying running max / normalizer / weighted accumulator in VMEM
    scratch, so the (T, T) score matrix never materializes in HBM.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    grid = (bh, t // BLOCK_Q, t // BLOCK_K)
    nk = grid[2]

    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        ik = pl.program_id(2)

        @pl.when(ik == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        iq = pl.program_id(1)

        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (BQ, BK)
            if causal:
                row = iq * BLOCK_Q + jax.lax.broadcasted_iota(
                    jnp.int32, (BLOCK_Q, BLOCK_K), 0)
                col = ik * BLOCK_K + jax.lax.broadcasted_iota(
                    jnp.int32, (BLOCK_Q, BLOCK_K), 1)
                s = jnp.where(row >= col, s, _NEG_INF)

            m_prev = m_ref[:, :1]                          # (BQ, 1)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                         # (BQ, BK)
            alpha = jnp.exp(m_prev - m_new)                # (BQ, 1)
            l_ref[:, :1] = (l_ref[:, :1] * alpha
                            + p.sum(axis=-1, keepdims=True))
            m_ref[:, :1] = m_new
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p, v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            # blocks fully above the diagonal contribute nothing; skip
            # their MXU work (their DMA is already pipelined by pallas)
            @pl.when(iq * BLOCK_Q // BLOCK_K >= ik)
            def _():
                body()
        else:
            body()

        @pl.when(ik == nk - 1)
        def _():
            o_ref[0] = acc_ref[:] / l_ref[:, :1]

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running max
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running normalizer
            pltpu.VMEM((BLOCK_Q, d), jnp.float32),     # weighted accumulator
        ],
        interpret=_interpret_mode(),
    )(q, k, v)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None) -> Optional[object]:
    """Fused attention over (B, T, H, D) inputs (layout shared with
    :mod:`mxnet_tpu.parallel.ring_attention`).

    Returns None when the kernel does not apply (seq len not a multiple
    of the 128 block, non-f32, pallas unavailable) — callers fall back to
    the XLA reference path. Backward recomputes through the reference
    attention (rematerialization: the O(T^2) probs never hit HBM in fwd).
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    b, t, h, d = q.shape
    if (t % BLOCK_Q or t % BLOCK_K or q.dtype != jnp.float32
            or d > 256):
        return None
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))

    def _pack(x):   # (B, T, H, D) -> (B*H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    def _unpack(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    @jax.custom_vjp
    def f(q, k, v):
        return _unpack(_flash_call(_pack(q), _pack(k), _pack(v),
                                   scale, causal))

    def _ref(q, k, v):
        # recompute path shares the single attention oracle, pinned to
        # the kernel's scale and finite mask value
        from ..parallel.ring_attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale,
                                   mask_value=_NEG_INF)

    def f_fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def f_bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(_ref, q, k, v)
        return vjp(g)

    f.defvjp(f_fwd, f_bwd)
    return f(q, k, v)


# ---------------------------------------------------------------------------
# conv backward: dgrad + wgrad as MXU-shaped matmuls over im2col tiles
# ---------------------------------------------------------------------------
#
# xprof's op-category breakdown pins the fused ResNet step on the conv
# backward (ROADMAP item 1), which XLA lowers as transposed convs. Here
# both halves become plain tiled matmuls — the shape the MXU actually
# is — over im2col patches:
#
#   wgrad:  gw = patches(x)^T @ g      (K*K*C, N*HO*WO) x (N*HO*WO, O)
#   dgrad:  dx = patches(g~) @ w~      (N*H*W, K*K*O)   x (K*K*O, C)
#
# where g~ is g stride-dilated + edge-padded and w~ the spatially
# flipped, O<->C-swapped kernel (the standard transposed-conv algebra).
# Patch extraction is a handful of strided slices XLA fuses into the
# operand feed; the MXU work runs in the Pallas kernels below with
# bf16-or-f32 inputs and f32 accumulation. Tile sizes are parameters —
# the autotuner (mxnet_tpu/autotune.py) measures candidates per chip.

_DEF_TILES = (128, 128, 128)


def _tiles_ok(tiles) -> bool:
    # both matmul kernels place every tile dimension on either the MXU
    # lane axis (128) or a sublane axis fed from one; 128-multiples
    # everywhere keep one rule valid for f32 and bf16 operand tiles
    return (len(tiles) == 3
            and all(t > 0 and t % 128 == 0 for t in tiles))


def _matmul(a, b, tiles, transpose_a=False):
    """Tiled matmul with f32 accumulation: ``a @ b`` or ``a.T @ b``.

    ``transpose_a`` contracts on ``a``'s FIRST axis without ever
    materializing the transpose — the wgrad shape (patches^T @ g) — so
    the only data movement is the tile feed itself. Inputs may be bf16
    (MXU-native) or f32; the accumulator and output are f32.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    tm, tn, tk = tiles
    if transpose_a:
        k, m = a.shape
    else:
        m, k = a.shape
    _, n = b.shape
    grid = (m // tm, n // tn, k // tk)
    nk = grid[2]

    def kernel(a_ref, b_ref, o_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        if transpose_a:
            o_ref[:] += jax.lax.dot_general(
                a_ref[:], b_ref[:], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            o_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                                preferred_element_type=jnp.float32)

    a_spec = (pl.BlockSpec((tk, tm), lambda i, j, kk: (kk, i))
              if transpose_a
              else pl.BlockSpec((tm, tk), lambda i, j, kk: (i, kk)))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[a_spec,
                  pl.BlockSpec((tk, tn), lambda i, j, kk: (kk, j))],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=_interpret_mode(),
    )(a, b)


def _patches(x, kh, kw, stride):
    """im2col over an already-padded NHWC tensor: (N, Hp, Wp, C) ->
    (N*HO*WO, KH*KW*C), minor order (kh, kw, c) — the flattening of
    ``w.transpose(2, 3, 1, 0)`` so the matmul contracts correctly."""
    import jax
    import jax.numpy as jnp

    n, hp, wp, c = x.shape
    sh, sw = stride
    ho = (hp - kh) // sh + 1
    wo = (wp - kw) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            cols.append(jax.lax.slice(
                x, (0, i, j, 0),
                (n, i + (ho - 1) * sh + 1, j + (wo - 1) * sw + 1, c),
                (1, sh, sw, 1)))
    p = jnp.stack(cols, axis=3)          # (N, HO, WO, KH*KW, C)
    return p.reshape(n * ho * wo, kh * kw * c)


def _cast_in(x, compute_dtype):
    import jax.numpy as jnp

    return x.astype(compute_dtype) if compute_dtype is not None \
        and x.dtype != compute_dtype else x


def conv_backward_applicable(x_shape, w_shape, stride, pad, dilate,
                             num_group, tiles=_DEF_TILES) -> bool:
    """Static (trace-time) applicability of the Pallas conv-backward
    pair for a 2D conv. Every condition is a shape/param fact, so the
    decision costs nothing per dispatch. ``x_shape`` is NHWC."""
    if not pallas_available() or not _tiles_ok(tiles):
        return False
    if len(x_shape) != 4 or len(w_shape) != 4 or num_group != 1:
        return False
    if tuple(dilate) != (1, 1):
        return False
    n, h, w, c = x_shape
    o, ci, kh, kw = w_shape
    sh, sw = stride
    ph, pw = pad
    if ci != c or ph > kh - 1 or pw > kw - 1:
        return False
    if (h + 2 * ph - kh) % sh or (w + 2 * pw - kw) % sw:
        return False   # dgrad's dilate+pad inversion is only exact here
    ho = (h + 2 * ph - kh) // sh + 1
    wo = (w + 2 * pw - kw) // sw + 1
    tm, tn, tk = tiles
    return not (n * h * w % tm or c % tn or kh * kw * o % tk      # dgrad
                or kh * kw * c % tm or o % tn or n * ho * wo % tk  # wgrad
                )


def conv_dgrad(w, g, x_shape, stride, pad, tiles=_DEF_TILES,
               compute_dtype=None):
    """Input gradient of a 2D conv as one tiled matmul.

    ``w`` OIHW, ``g`` NHWC output cotangent, ``x_shape`` the NHWC primal
    shape. Returns dx (NHWC, primal dtype) or None when the shapes don't
    tile. ``compute_dtype`` (e.g. bf16) casts the matmul operands; the
    accumulator stays f32 either way.
    """
    if not pallas_available():
        return None
    import jax.numpy as jnp

    n, h, wd, c = x_shape
    o, _, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    if not conv_backward_applicable(x_shape, w.shape, stride, pad,
                                    (1, 1), 1, tiles):
        return None
    ho = (h + 2 * ph - kh) // sh + 1
    wo = (wd + 2 * pw - kw) // sw + 1
    if (sh, sw) != (1, 1):
        gd = jnp.zeros((n, (ho - 1) * sh + 1, (wo - 1) * sw + 1, o),
                       g.dtype)
        gd = gd.at[:, ::sh, ::sw, :].set(g)
    else:
        gd = g
    gp = jnp.pad(gd, ((0, 0), (kh - 1 - ph,) * 2, (kw - 1 - pw,) * 2,
                      (0, 0)))
    pat = _patches(gp, kh, kw, (1, 1))            # (N*H*W, KH*KW*O)
    wt = w[:, :, ::-1, ::-1].transpose(2, 3, 0, 1).reshape(kh * kw * o, c)
    dx = _matmul(_cast_in(pat, compute_dtype),
                 _cast_in(wt, compute_dtype), tiles)
    return dx.reshape(n, h, wd, c).astype(g.dtype)


def conv_wgrad(x, g, w_shape, stride, pad, tiles=_DEF_TILES,
               compute_dtype=None):
    """Weight gradient of a 2D conv as one tiled ``patches^T @ g``
    matmul (the transpose is folded into the kernel's tile feed, never
    materialized). ``x``/``g`` NHWC, returns gw in OIHW, or None."""
    if not pallas_available():
        return None
    import jax.numpy as jnp

    o, c, kh, kw = w_shape
    ph, pw = pad
    if not conv_backward_applicable(x.shape, w_shape, stride, pad,
                                    (1, 1), 1, tiles):
        return None
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    pat = _patches(xp, kh, kw, stride)            # (N*HO*WO, KH*KW*C)
    gm = g.reshape(-1, o)
    gw = _matmul(_cast_in(pat, compute_dtype),
                 _cast_in(gm, compute_dtype), tiles, transpose_a=True)
    return gw.reshape(kh, kw, c, o).transpose(3, 2, 0, 1).astype(g.dtype)


def conv2d(x, w, bias=None, stride=(1, 1), pad=(0, 0), dilate=(1, 1),
           num_group=1, nhwc=False, tiles=_DEF_TILES,
           compute_dtype=None):
    """2D convolution whose *backward* runs the Pallas dgrad/wgrad
    kernels. The forward stays ``lax.conv_general_dilated`` — XLA's
    forward conv already saturates the MXU (docs/pallas.md policy); it
    is the backward, which XLA lowers as transposed convs, that the
    profile blames. Returns None when the kernels do not apply (shape
    misalignment, groups, dilation) — callers keep the XLA path.
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    x_nhwc_shape = x.shape if nhwc \
        else (x.shape[0], x.shape[2], x.shape[3], x.shape[1])
    if not conv_backward_applicable(x_nhwc_shape, w.shape, stride, pad,
                                    dilate, num_group, tiles):
        return None

    dn = ("NHWC", "OIHW", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    pads = [(p, p) for p in pad]

    def _fwd_conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=stride, padding=pads,
            dimension_numbers=dn,
            preferred_element_type=x.dtype
            if x.dtype == jnp.float32 else None)

    @jax.custom_vjp
    def f(x, w):
        return _fwd_conv(x, w)

    def f_fwd(x, w):
        return f(x, w), (x, w)

    def f_bwd(res, g):
        x, w = res
        xh = x if nhwc else x.transpose(0, 2, 3, 1)
        gh = g if nhwc else g.transpose(0, 2, 3, 1)
        dx = conv_dgrad(w, gh, xh.shape, stride, pad, tiles,
                        compute_dtype)
        gw = conv_wgrad(xh, gh, w.shape, stride, pad, tiles,
                        compute_dtype)
        if dx is None or gw is None:  # pragma: no cover - pre-checked
            _, vjp = jax.vjp(_fwd_conv, x, w)
            return vjp(g)
        if not nhwc:
            dx = dx.transpose(0, 3, 1, 2)
        return dx.astype(x.dtype), gw.astype(w.dtype)

    f.defvjp(f_fwd, f_bwd)
    out = f(x, w)
    if bias is not None:
        bshape = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
        out = out + bias.reshape(bshape)
    return out


# ---------------------------------------------------------------------------
# fused norm + activation (BN scale/shift + ReLU, forward and backward)
# ---------------------------------------------------------------------------
#
# BatchNorm's apply step is a per-channel scale/shift (the statistics
# are folded beforehand, ops/nn.py); its backward in XLA re-reads the
# activations twice (dx, then the per-channel reductions). Both
# directions here are one VMEM pass each: forward computes
# act(x*scale+shift) in f32; backward recomputes the pre-activation
# (cheaper than storing the mask), masks the cotangent, and emits dx
# plus the per-channel dscale/dshift partial sums in the same pass.

NORM_BLOCK_ROWS = 128
_NORM_BLOCK_C = 128


def norm_act_applicable(shape, dtype, block_rows=NORM_BLOCK_ROWS) -> bool:
    """Static applicability: channels-last tensor whose row count tiles
    ``block_rows`` and whose channel count tiles the 128 lane axis."""
    if not pallas_available():
        return False
    import jax.numpy as jnp

    if len(shape) < 2 or block_rows <= 0 or block_rows % 8:
        return False
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.float32),
                                jnp.dtype(jnp.bfloat16)):
        return False
    c = shape[-1]
    rows = 1
    for d in shape[:-1]:
        rows *= d
    return not (rows % block_rows or c % _NORM_BLOCK_C)


def _norm_act_fwd_call(x2, scale, shift, act, block_rows):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2.shape
    grid = (r // block_rows, c // _NORM_BLOCK_C)

    def kernel(x_ref, sc_ref, sh_ref, o_ref):
        y = (x_ref[:].astype(jnp.float32) * sc_ref[:]
             + sh_ref[:])
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        o_ref[:] = y.astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, _NORM_BLOCK_C),
                         lambda i, j: (i, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda i, j: (0, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_rows, _NORM_BLOCK_C),
                               lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((r, c), x2.dtype),
        interpret=_interpret_mode(),
    )(x2, scale, shift)


def _norm_act_bwd_call(x2, scale, shift, g2, act, block_rows):
    """One pass: dx + per-channel dscale/dshift partials. The row-tile
    axis is the LAST grid dimension so the (1, C) reduction outputs
    accumulate sequentially across row tiles (same revisit rule as the
    matmul K axis)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    r, c = x2.shape
    grid = (c // _NORM_BLOCK_C, r // block_rows)

    def kernel(x_ref, sc_ref, sh_ref, g_ref, dx_ref, dsc_ref, dsh_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            dsc_ref[:] = jnp.zeros_like(dsc_ref)
            dsh_ref[:] = jnp.zeros_like(dsh_ref)
        x = x_ref[:].astype(jnp.float32)
        ge = g_ref[:].astype(jnp.float32)
        if act == "relu":
            pre = x * sc_ref[:] + sh_ref[:]
            ge = jnp.where(pre > 0.0, ge, 0.0)
        dx_ref[:] = (ge * sc_ref[:]).astype(dx_ref.dtype)
        dsc_ref[:] += jnp.sum(ge * x, axis=0, keepdims=True)
        dsh_ref[:] += jnp.sum(ge, axis=0, keepdims=True)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, _NORM_BLOCK_C),
                         lambda j, i: (i, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda j, i: (0, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda j, i: (0, j)),
            pl.BlockSpec((block_rows, _NORM_BLOCK_C),
                         lambda j, i: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, _NORM_BLOCK_C),
                         lambda j, i: (i, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda j, i: (0, j)),
            pl.BlockSpec((1, _NORM_BLOCK_C), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, c), g2.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(x2, scale, shift, g2)


def fused_norm_act(x, scale, shift, act: str = "none",
                   block_rows: int = NORM_BLOCK_ROWS):
    """``act(x * scale + shift)`` with per-channel scale/shift over the
    last (channels) axis, forward and backward each one fused kernel.

    ``x`` is any-rank channels-last (bf16 or f32); ``scale``/``shift``
    are per-channel vectors. Math runs in f32 regardless of input dtype
    (bf16 compute, f32 accumulate); the output is cast back to
    ``x.dtype``. Returns None when the kernel does not apply — callers
    fall back to the XLA elementwise path. ``block_rows`` is the tuned
    row-tile knob (site ``norm_act`` in mxnet_tpu/autotune.py).
    """
    if act not in ("none", "relu"):
        return None
    if not norm_act_applicable(x.shape, x.dtype, block_rows):
        return None
    import jax
    import jax.numpy as jnp

    c = x.shape[-1]
    sc = scale.astype(jnp.float32).reshape(1, c)
    sh = shift.astype(jnp.float32).reshape(1, c)

    @jax.custom_vjp
    def f(x, sc, sh):
        return _norm_act_fwd_call(x.reshape(-1, c), sc, sh, act,
                                  block_rows).reshape(x.shape)

    def f_fwd(x, sc, sh):
        return f(x, sc, sh), (x, sc, sh)

    def f_bwd(res, g):
        x, sc, sh = res
        dx, dsc, dsh = _norm_act_bwd_call(
            x.reshape(-1, c), sc, sh, g.reshape(-1, c), act, block_rows)
        return (dx.reshape(x.shape).astype(x.dtype),
                dsc.reshape(sc.shape), dsh.reshape(sh.shape))

    f.defvjp(f_fwd, f_bwd)
    out = f(x, sc, sh)
    return out
