"""Pallas TPU kernels — the cuDNN-class fast path.

The reference reached peak GPU throughput with hand-tuned cuDNN kernels
(``src/operator/cudnn_*-inl.h``); on TPU the analogue is Pallas: kernels
that tile HBM->VMEM explicitly and feed the MXU. This module provides the
first such kernel — a fused linear layer (tiled matmul + bias + activation
in one VMEM-resident pass) used by FullyConnected when shapes are
tile-aligned — plus the availability plumbing shared by future kernels
(conv/pool/attention).

Gradients route through ``jax.custom_vjp``: the backward matmuls are plain
XLA (already MXU-optimal); only the fused forward is hand-written.

On CPU the kernels run in interpreter mode so the whole path is testable
without hardware.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from .. import env as _env

__all__ = ["fused_linear", "flash_attention", "pallas_available"]

# float32 MXU-friendly tiles (sublane 8, lane 128)
TILE_M = 128
TILE_N = 128
TILE_K = 128


@functools.lru_cache(None)
def pallas_available() -> bool:
    if _env.get("MXNET_TPU_NO_PALLAS"):
        return False
    try:
        import jax
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(None)
def _interpret_mode() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _linear_call(x, w_t, bias, act: str):
    """Tiled (M,K)x(K,N) matmul with fused bias+activation epilogue."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    _, n = w_t.shape
    grid = (m // TILE_M, n // TILE_N, k // TILE_K)
    nk = grid[2]

    def kernel(x_ref, w_ref, b_ref, o_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        o_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                            preferred_element_type=jnp.float32)

        @pl.when(kk == nk - 1)
        def _():
            acc = o_ref[:] + b_ref[:]
            if act == "relu":
                acc = jnp.maximum(acc, 0.0)
            elif act == "tanh":
                acc = jnp.tanh(acc)
            elif act == "sigmoid":
                acc = jax.nn.sigmoid(acc)
            o_ref[:] = acc

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_M, TILE_K), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((TILE_K, TILE_N), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, TILE_N), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((TILE_M, TILE_N), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=_interpret_mode(),
    )(x, w_t, bias)


def fused_linear(x, weight, bias=None, act: str = "none") -> Optional[object]:
    """out = act(x @ weight.T + bias) via the Pallas kernel.

    ``weight`` uses the framework layout (num_hidden, in_dim). Returns None
    when the kernel does not apply (shape misalignment / pallas missing) —
    callers fall back to the XLA path.
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    m, k = x.shape
    n = weight.shape[0]
    if (m % TILE_M or k % TILE_K or n % TILE_N
            or x.dtype != jnp.float32 or weight.dtype != jnp.float32):
        return None
    b = bias if bias is not None else jnp.zeros((n,), jnp.float32)

    @jax.custom_vjp
    def f(x, w, b):
        return _linear_call(x, w.T, b.reshape(1, n), act)

    def f_fwd(x, w, b):
        out = f(x, w, b)
        return out, (x, w, b, out)

    def f_bwd(res, g):
        x, w, b, out = res
        if act == "relu":
            g = jnp.where(out > 0, g, 0.0)
        elif act == "tanh":
            g = g * (1.0 - out * out)
        elif act == "sigmoid":
            g = g * out * (1.0 - out)
        gx = jnp.dot(g, w)
        gw = jnp.dot(g.T, x)
        gb = jnp.sum(g, axis=0)
        return gx, gw, gb

    f.defvjp(f_fwd, f_bwd)
    return f(x, weight, b)


# ---------------------------------------------------------------------------
# Flash attention
# ---------------------------------------------------------------------------

BLOCK_Q = 128
BLOCK_K = 128
_NEG_INF = -1e30


def _flash_call(q, k, v, scale: float, causal: bool):
    """Online-softmax tiled attention. q/k/v: (BH, T, D) float32.

    The cuDNN-class fused kernel of this framework (the reference's GPU
    fast path was cudnn_*-inl.h): one pass over K/V blocks per Q block,
    carrying running max / normalizer / weighted accumulator in VMEM
    scratch, so the (T, T) score matrix never materializes in HBM.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q.shape
    grid = (bh, t // BLOCK_Q, t // BLOCK_K)
    nk = grid[2]

    def kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        ik = pl.program_id(2)

        @pl.when(ik == 0)
        def _():
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)

        iq = pl.program_id(1)

        def body():
            s = jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (BQ, BK)
            if causal:
                row = iq * BLOCK_Q + jax.lax.broadcasted_iota(
                    jnp.int32, (BLOCK_Q, BLOCK_K), 0)
                col = ik * BLOCK_K + jax.lax.broadcasted_iota(
                    jnp.int32, (BLOCK_Q, BLOCK_K), 1)
                s = jnp.where(row >= col, s, _NEG_INF)

            m_prev = m_ref[:, :1]                          # (BQ, 1)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                         # (BQ, BK)
            alpha = jnp.exp(m_prev - m_new)                # (BQ, 1)
            l_ref[:, :1] = (l_ref[:, :1] * alpha
                            + p.sum(axis=-1, keepdims=True))
            m_ref[:, :1] = m_new
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p, v_ref[0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        if causal:
            # blocks fully above the diagonal contribute nothing; skip
            # their MXU work (their DMA is already pipelined by pallas)
            @pl.when(iq * BLOCK_Q // BLOCK_K >= ik)
            def _():
                body()
        else:
            body()

        @pl.when(ik == nk - 1)
        def _():
            o_ref[0] = acc_ref[:] / l_ref[:, :1]

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, d), lambda b, iq, ik: (b, iq, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, iq, ik: (b, ik, 0)),
            pl.BlockSpec((1, BLOCK_K, d), lambda b, iq, ik: (b, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, d), lambda b, iq, ik: (b, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running max
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),   # running normalizer
            pltpu.VMEM((BLOCK_Q, d), jnp.float32),     # weighted accumulator
        ],
        interpret=_interpret_mode(),
    )(q, k, v)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None) -> Optional[object]:
    """Fused attention over (B, T, H, D) inputs (layout shared with
    :mod:`mxnet_tpu.parallel.ring_attention`).

    Returns None when the kernel does not apply (seq len not a multiple
    of the 128 block, non-f32, pallas unavailable) — callers fall back to
    the XLA reference path. Backward recomputes through the reference
    attention (rematerialization: the O(T^2) probs never hit HBM in fwd).
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    b, t, h, d = q.shape
    if (t % BLOCK_Q or t % BLOCK_K or q.dtype != jnp.float32
            or d > 256):
        return None
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))

    def _pack(x):   # (B, T, H, D) -> (B*H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    def _unpack(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    @jax.custom_vjp
    def f(q, k, v):
        return _unpack(_flash_call(_pack(q), _pack(k), _pack(v),
                                   scale, causal))

    def _ref(q, k, v):
        # recompute path shares the single attention oracle, pinned to
        # the kernel's scale and finite mask value
        from ..parallel.ring_attention import reference_attention

        return reference_attention(q, k, v, causal=causal, scale=scale,
                                   mask_value=_NEG_INF)

    def f_fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def f_bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(_ref, q, k, v)
        return vjp(g)

    f.defvjp(f_fwd, f_bwd)
    return f(q, k, v)
