"""Pallas TPU kernels — the cuDNN-class fast path.

The reference reached peak GPU throughput with hand-tuned cuDNN kernels
(``src/operator/cudnn_*-inl.h``); on TPU the analogue is Pallas: kernels
that tile HBM->VMEM explicitly and feed the MXU. This module provides the
first such kernel — a fused linear layer (tiled matmul + bias + activation
in one VMEM-resident pass) used by FullyConnected when shapes are
tile-aligned — plus the availability plumbing shared by future kernels
(conv/pool/attention).

Gradients route through ``jax.custom_vjp``: the backward matmuls are plain
XLA (already MXU-optimal); only the fused forward is hand-written.

On CPU the kernels run in interpreter mode so the whole path is testable
without hardware.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from ..base import getenv

__all__ = ["fused_linear", "pallas_available"]

# float32 MXU-friendly tiles (sublane 8, lane 128)
TILE_M = 128
TILE_N = 128
TILE_K = 128


@functools.lru_cache(None)
def pallas_available() -> bool:
    if getenv("MXNET_TPU_NO_PALLAS", False):
        return False
    try:
        import jax
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(None)
def _interpret_mode() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _linear_call(x, w_t, bias, act: str):
    """Tiled (M,K)x(K,N) matmul with fused bias+activation epilogue."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    m, k = x.shape
    _, n = w_t.shape
    grid = (m // TILE_M, n // TILE_N, k // TILE_K)
    nk = grid[2]

    def kernel(x_ref, w_ref, b_ref, o_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)
        o_ref[:] += jnp.dot(x_ref[:], w_ref[:],
                            preferred_element_type=jnp.float32)

        @pl.when(kk == nk - 1)
        def _():
            acc = o_ref[:] + b_ref[:]
            if act == "relu":
                acc = jnp.maximum(acc, 0.0)
            elif act == "tanh":
                acc = jnp.tanh(acc)
            elif act == "sigmoid":
                acc = jax.nn.sigmoid(acc)
            o_ref[:] = acc

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_M, TILE_K), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((TILE_K, TILE_N), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, TILE_N), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((TILE_M, TILE_N), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=_interpret_mode(),
    )(x, w_t, bias)


def fused_linear(x, weight, bias=None, act: str = "none") -> Optional[object]:
    """out = act(x @ weight.T + bias) via the Pallas kernel.

    ``weight`` uses the framework layout (num_hidden, in_dim). Returns None
    when the kernel does not apply (shape misalignment / pallas missing) —
    callers fall back to the XLA path.
    """
    if not pallas_available():
        return None
    import jax
    import jax.numpy as jnp

    m, k = x.shape
    n = weight.shape[0]
    if (m % TILE_M or k % TILE_K or n % TILE_N
            or x.dtype != jnp.float32 or weight.dtype != jnp.float32):
        return None
    b = bias if bias is not None else jnp.zeros((n,), jnp.float32)

    @jax.custom_vjp
    def f(x, w, b):
        return _linear_call(x, w.T, b.reshape(1, n), act)

    def f_fwd(x, w, b):
        out = f(x, w, b)
        return out, (x, w, b, out)

    def f_bwd(res, g):
        x, w, b, out = res
        if act == "relu":
            g = jnp.where(out > 0, g, 0.0)
        elif act == "tanh":
            g = g * (1.0 - out * out)
        elif act == "sigmoid":
            g = g * out * (1.0 - out)
        gx = jnp.dot(g, w)
        gw = jnp.dot(g.T, x)
        gb = jnp.sum(g, axis=0)
        return gx, gw, gb

    f.defvjp(f_fwd, f_bwd)
    return f(x, weight, b)
