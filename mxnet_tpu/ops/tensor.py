"""Tensor manipulation + elementwise operators.

TPU-native implementations of the reference's elementwise / broadcast /
reduce / matrix op families (``src/operator/elementwise_*``,
``broadcast_reduce_op*``, ``matrix_op-inl.h``, ``mshadow_op.h`` functor
zoo). Internal ``_Plus``-style ops back Symbol operator overloading exactly
like the reference's registered internal ops.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..base import MXNetError
from .registry import (Operator, OpContext, Param, REQUIRED, register_op,
                       same_shape_binary)


def _jax():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# elementwise binary (reference elementwise_binary_op-inl.h)
# ---------------------------------------------------------------------------
class _BinaryOp(Operator):
    fn = None

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        return same_shape_binary(in_shapes)

    def apply(self, ctx, inputs, aux):
        return [type(self).fn(inputs[0], inputs[1])], []


def _def_binary(name, hint, fn):
    cls = type(name.strip("_"), (_BinaryOp,), {"fn": staticmethod(fn),
                                               "name_hint": hint})
    register_op(name)(cls)
    return cls


_def_binary("_Plus", "plus", lambda a, b: a + b)
_def_binary("_Minus", "minus", lambda a, b: a - b)
_def_binary("_Mul", "mul", lambda a, b: a * b)
_def_binary("_Div", "div", lambda a, b: a / b)
_def_binary("_Power", "power", lambda a, b: a ** b)
_def_binary("_Maximum", "maximum", lambda a, b: _jnp().maximum(a, b))
_def_binary("_Minimum", "minimum", lambda a, b: _jnp().minimum(a, b))


class _BroadcastBinaryOp(Operator):
    """reference elementwise_binary_broadcast_op-inl.h: same ndim, each dim
    equal or 1; gradients reduce over the broadcast dims (autodiff's vjp of
    jnp broadcasting does exactly that)."""

    fn = None

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        lhs, rhs = in_shapes
        if lhs is None or rhs is None:
            raise MXNetError("broadcast op: both input shapes required")
        if len(lhs) != len(rhs):
            raise MXNetError("broadcast op: ndim mismatch %s vs %s"
                             % (lhs, rhs))
        out = []
        for a, b in zip(lhs, rhs):
            if a != b and a != 1 and b != 1:
                raise MXNetError("broadcast op: incompatible dims %s vs %s"
                                 % (lhs, rhs))
            out.append(max(a, b))
        return [lhs, rhs], [tuple(out)], []

    def apply(self, ctx, inputs, aux):
        return [type(self).fn(inputs[0], inputs[1])], []


def _def_broadcast(name, hint, fn):
    cls = type(name, (_BroadcastBinaryOp,), {"fn": staticmethod(fn),
                                             "name_hint": hint})
    register_op(name)(cls)
    return cls


_def_broadcast("broadcast_plus", "broadcast_plus", lambda a, b: a + b)
_def_broadcast("broadcast_minus", "broadcast_minus", lambda a, b: a - b)
_def_broadcast("broadcast_mul", "broadcast_mul", lambda a, b: a * b)
_def_broadcast("broadcast_div", "broadcast_div", lambda a, b: a / b)
_def_broadcast("broadcast_power", "broadcast_power", lambda a, b: a ** b)


class _ScalarOp(Operator):
    PARAMS = {"scalar": Param(float, REQUIRED)}
    fn = None

    def apply(self, ctx, inputs, aux):
        return [type(self).fn(inputs[0], self.scalar)], []


def _def_scalar(name, hint, fn, aliases=()):
    cls = type(name.strip("_"), (_ScalarOp,), {"fn": staticmethod(fn),
                                               "name_hint": hint})
    # the reference registers these SimpleOps under snake_case names too
    # (operator_util.cc TOSTRING of the op name, e.g. "_plus_scalar")
    register_op(name, aliases=aliases)(cls)
    return cls


_def_scalar("_PlusScalar", "plusscalar", lambda a, s: a + s,
            aliases=("_plus_scalar",))
_def_scalar("_MinusScalar", "minusscalar", lambda a, s: a - s,
            aliases=("_minus_scalar",))
_def_scalar("_RMinusScalar", "rminusscalar", lambda a, s: s - a,
            aliases=("_rminus_scalar",))
_def_scalar("_MulScalar", "mulscalar", lambda a, s: a * s,
            aliases=("_mul_scalar",))
_def_scalar("_DivScalar", "divscalar", lambda a, s: a / s,
            aliases=("_div_scalar",))
_def_scalar("_RDivScalar", "rdivscalar", lambda a, s: s / a,
            aliases=("_rdiv_scalar",))
_def_scalar("_PowerScalar", "powerscalar", lambda a, s: a ** s,
            aliases=("_power_scalar",))
_def_scalar("_RPowerScalar", "rpowerscalar", lambda a, s: s ** a,
            aliases=("_rpower_scalar",))
_def_scalar("_MaximumScalar", "maximumscalar",
            lambda a, s: _jnp().maximum(a, s),
            aliases=("_maximum_scalar",))
_def_scalar("_MinimumScalar", "minimumscalar",
            lambda a, s: _jnp().minimum(a, s),
            aliases=("_minimum_scalar",))


# ---------------------------------------------------------------------------
# elementwise unary (reference elementwise_unary_op + mshadow_op.h)
# ---------------------------------------------------------------------------
class _UnaryOp(Operator):
    fn = None

    def apply(self, ctx, inputs, aux):
        return [type(self).fn(inputs[0])], []


def _def_unary(name, fn, aliases=()):
    cls = type("U_" + name, (_UnaryOp,), {"fn": staticmethod(fn),
                                          "name_hint": name})
    register_op(name, aliases=aliases)(cls)
    return cls


_def_unary("exp", lambda x: _jnp().exp(x))
_def_unary("log", lambda x: _jnp().log(x))
_def_unary("sqrt", lambda x: _jnp().sqrt(x))
_def_unary("rsqrt", lambda x: _jax().lax.rsqrt(x))
_def_unary("square", lambda x: x * x)
_def_unary("abs", lambda x: _jnp().abs(x))
_def_unary("sign", lambda x: _jnp().sign(x))
_def_unary("round", lambda x: _jnp().round(x))
_def_unary("ceil", lambda x: _jnp().ceil(x))
_def_unary("floor", lambda x: _jnp().floor(x))
_def_unary("cos", lambda x: _jnp().cos(x))
_def_unary("sin", lambda x: _jnp().sin(x))
_def_unary("negative", lambda x: -x)


@register_op("clip")
class Clip(Operator):
    """reference SimpleOp clip: elementwise clamp to [a_min, a_max]
    (registered for both NDArray and symbolic use, operator_util.h)."""

    name_hint = "clip"
    PARAMS = {"a_min": Param(float, REQUIRED), "a_max": Param(float, REQUIRED)}

    def apply(self, ctx, inputs, aux):
        return [_jnp().clip(inputs[0], self.a_min, self.a_max)], []


@register_op("argmax_channel")
class ArgmaxChannel(Operator):
    """reference SimpleOp argmax_channel: argmax over axis 1, output
    (batch,) float indices (used by metrics on multi-channel outputs)."""

    name_hint = "argmax_channel"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("argmax_channel: data shape unknown")
        if len(data) < 2:
            raise MXNetError("argmax_channel needs >=2 dims, got %s"
                             % (data,))
        return [data], [(data[0],) + tuple(data[2:])], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        x = jax.lax.stop_gradient(inputs[0])
        return [_jnp().argmax(x, axis=1).astype(inputs[0].dtype)], []


@register_op("smooth_l1")
class SmoothL1(Operator):
    """reference smooth_l1_unary-inl.h: f(x)=0.5(sx)^2/|x|<1/s^2 else |x|-0.5/s^2."""

    name_hint = "smooth_l1"
    PARAMS = {"scalar": Param(float, 1.0)}

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        s2 = self.scalar ** 2
        out = jnp.where(jnp.abs(x) < 1.0 / s2,
                        0.5 * s2 * x * x,
                        jnp.abs(x) - 0.5 / s2)
        return [out], []


# ---------------------------------------------------------------------------
# structural ops
# ---------------------------------------------------------------------------
@register_op("Flatten")
class Flatten(Operator):
    name_hint = "flatten"

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Flatten: data shape unknown")
        return [data], [(data[0], int(np.prod(data[1:])))], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        return [x.reshape((x.shape[0], -1))], []


@register_op("Reshape")
class Reshape(Operator):
    """reference reshape-inl.h; supports 0 (keep) and -1 (infer) entries."""

    name_hint = "reshape"
    PARAMS = {
        "shape": Param("shape", None),
        "target_shape": Param("shape", None),
        "reverse": Param(bool, False, "match 0-dims from the right"),
    }

    def _target(self, data):
        shape = self.params["shape"]
        if shape is None and self.target_shape is not None:
            # old API (reference reshape-inl.h target_shape): 0 means
            # "infer this dim", unlike the new API where 0 means "keep"
            shape = tuple(-1 if s == 0 else s for s in self.target_shape)
        if shape is None:
            raise MXNetError("Reshape: no target shape")
        if self.reverse:
            # reference reshape-inl.h reverse=True: apply the 0/-1 rules
            # with both shapes right-aligned
            data_r, shape_r = tuple(reversed(data)), tuple(reversed(shape))
            out = self._expand(data_r, shape_r)
            return tuple(reversed(out))
        return tuple(self._expand(data, shape))

    @staticmethod
    def _expand(data, shape):
        out = []
        for i, s in enumerate(shape):
            out.append(data[i] if s == 0 and i < len(data) else s)
        if out.count(-1) > 1:
            raise MXNetError("Reshape: at most one dim may be inferred "
                             "(-1, or 0 in the old target_shape API): %s"
                             % (tuple(shape),))
        if -1 in out:
            known = int(np.prod([s for s in out if s != -1]))
            out[out.index(-1)] = int(np.prod(data)) // max(known, 1)
        return out

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Reshape: data shape unknown")
        out = self._target(data)
        if int(np.prod(out)) != int(np.prod(data)):
            raise MXNetError("Reshape: size mismatch %s -> %s" % (data, out))
        return [data], [out], []

    def apply(self, ctx, inputs, aux):
        return [inputs[0].reshape(self._target(inputs[0].shape))], []


@register_op("Cast")
class Cast(Operator):
    name_hint = "cast"
    PARAMS = {"dtype": Param(str, REQUIRED)}

    def infer_type(self, in_types):
        # input dtype stays whatever upstream says (None = still unknown —
        # don't speculatively default during the fixpoint); output is fixed
        dtype = np.dtype(self.dtype)
        return [in_types[0]], [dtype], []

    def apply(self, ctx, inputs, aux):
        import jax.numpy as jnp
        return [inputs[0].astype(jnp.dtype(self.dtype))], []


@register_op("transpose")
class Transpose(Operator):
    name_hint = "transpose"
    PARAMS = {"axes": Param("shape", None)}

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("transpose: data shape unknown")
        axes = self.axes or tuple(reversed(range(len(data))))
        return [data], [tuple(data[a] for a in axes)], []

    def apply(self, ctx, inputs, aux):
        return [_jnp().transpose(inputs[0], self.axes)], []


@register_op("SwapAxis")
class SwapAxis(Operator):
    name_hint = "swapaxis"
    PARAMS = {"dim1": Param(int, 0), "dim2": Param(int, 0)}

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SwapAxis: data shape unknown")
        s = list(data)
        s[self.dim1], s[self.dim2] = s[self.dim2], s[self.dim1]
        return [data], [tuple(s)], []

    def apply(self, ctx, inputs, aux):
        return [_jnp().swapaxes(inputs[0], self.dim1, self.dim2)], []


@register_op("expand_dims")
class ExpandDims(Operator):
    name_hint = "expand_dims"
    PARAMS = {"axis": Param(int, REQUIRED)}

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("expand_dims: data shape unknown")
        s = list(data)
        # normalize negative axes the way jnp.expand_dims does
        axis = self.axis if self.axis >= 0 else len(data) + 1 + self.axis
        s.insert(axis, 1)
        return [data], [tuple(s)], []

    def apply(self, ctx, inputs, aux):
        return [_jnp().expand_dims(inputs[0], self.axis)], []


@register_op("Concat")
class Concat(Operator):
    name_hint = "concat"
    PARAMS = {"num_args": Param(int, REQUIRED), "dim": Param(int, 1)}

    def list_arguments(self):
        return ["arg%d" % i for i in range(self.num_args)]

    def infer_shape(self, in_shapes):
        known = next((s for s in in_shapes if s is not None), None)
        if known is None:
            raise MXNetError("Concat: no input shape known")
        filled = [s if s is not None else known for s in in_shapes]
        dim = self.dim
        out = list(known)
        out[dim] = sum(s[dim] for s in filled)
        return filled, [tuple(out)], []

    def apply(self, ctx, inputs, aux):
        return [_jnp().concatenate(list(inputs), axis=self.dim)], []


@register_op("SliceChannel")
class SliceChannel(Operator):
    """Split along an axis into num_outputs symbols (reference
    slice_channel-inl.h)."""

    name_hint = "slicechannel"
    PARAMS = {
        "num_outputs": Param(int, REQUIRED),
        "axis": Param(int, 1),
        "squeeze_axis": Param(bool, False),
    }

    def list_outputs(self):
        # note: self.params, not self.num_outputs — the base-class
        # num_outputs property derives from list_outputs
        n = self.params["num_outputs"]
        return ["output%d" % i for i in range(n)]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("SliceChannel: data shape unknown")
        n = self.params["num_outputs"]
        s = list(data)
        if s[self.axis] % n:
            raise MXNetError("SliceChannel: axis not divisible")
        s[self.axis] //= n
        if self.squeeze_axis and s[self.axis] == 1:
            del s[self.axis]
        return [data], [tuple(s)] * n, []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        outs = jnp.split(inputs[0], self.params["num_outputs"], axis=self.axis)
        if self.squeeze_axis:
            outs = [o.squeeze(self.axis) for o in outs]
        return list(outs), []


@register_op("ElementWiseSum", aliases=["add_n"])
class ElementWiseSum(Operator):
    name_hint = "elementwisesum"
    PARAMS = {"num_args": Param(int, REQUIRED)}

    def list_arguments(self):
        return ["arg%d" % i for i in range(self.num_args)]

    def infer_shape(self, in_shapes):
        known = next((s for s in in_shapes if s is not None), None)
        if known is None:
            raise MXNetError("ElementWiseSum: no input shape known")
        return [known] * len(in_shapes), [known], []

    def apply(self, ctx, inputs, aux):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out], []


@register_op("Crop", aliases=("crop",))
class Crop(Operator):
    """reference crop-inl.h: crop spatial dims to match a reference symbol
    or explicit h_w, with offset."""

    name_hint = "crop"
    PARAMS = {
        "num_args": Param(int, 1),
        "offset": Param("shape", (0, 0)),
        "h_w": Param("shape", (0, 0)),
        "center_crop": Param(bool, False),
        # matrix-crop form (reference crop() in matrix_op-inl.h, exposed
        # as mx.nd.crop(x, begin=..., end=...)): any-rank begin/end slice
        "begin": Param("shape", None),
        "end": Param("shape", None),
    }

    def list_arguments(self):
        return ["data"] if self.num_args == 1 else ["data", "crop_like"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("Crop: data shape unknown")
        if self.begin is not None:
            if self.end is None or len(self.begin) != len(data) \
                    or len(self.end) != len(data):
                raise MXNetError("Crop: begin/end must both cover all %d "
                                 "axes" % len(data))
            for b, e, d in zip(self.begin, self.end, data):
                if not (0 <= b < e <= d):
                    raise MXNetError(
                        "Crop: invalid range [%d, %d) on axis of size %d"
                        % (b, e, d))
            out = tuple(e - b for b, e in zip(self.begin, self.end))
            return [data], [out], []
        if self.num_args == 2:
            like = in_shapes[1]
            if like is None:
                raise MXNetError("Crop: crop_like shape unknown")
            out = data[:2] + like[2:4]
            return [data, like], [out], []
        h, w = self.h_w
        return [data], [data[:2] + (h, w)], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        if self.begin is not None:
            idx = tuple(slice(b, e) for b, e in zip(self.begin, self.end))
            return [x[idx]], []
        if self.num_args == 2:
            h, w = inputs[1].shape[2:4]
        else:
            h, w = self.h_w
        if self.center_crop:
            oh = (x.shape[2] - h) // 2
            ow = (x.shape[3] - w) // 2
        else:
            oh, ow = self.offset
        return [x[:, :, oh:oh + h, ow:ow + w]], []


@register_op("element_mask")
class ElementMask(Operator):
    """reference SimpleOp ``element_mask`` (broadcast_mask_op-inl.h:23-88):
    ``out[i, ...] = lhs[i, ...] * rhs[i]`` — a 1-D per-row mask broadcast
    over a >=2-D tensor. The reference backward masks only ``out_grad``
    into ``lhs_grad`` and assigns no ``rhs_grad``, so the mask is a
    constant for autodiff (stop_gradient)."""

    name_hint = "elementmask"

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        lhs, rhs = in_shapes
        if lhs is None:
            raise MXNetError("element_mask: lhs shape unknown")
        if len(lhs) < 2:
            raise MXNetError("element_mask: source tensor should be 2D or "
                             "more, got %s" % (lhs,))
        want_rhs = (lhs[0],)
        if rhs is not None and tuple(rhs) != want_rhs:
            raise MXNetError("element_mask: mask must be 1D of length %d, "
                             "got %s" % (lhs[0], rhs))
        return [lhs, want_rhs], [lhs], []

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        lhs, rhs = inputs
        mask = jax.lax.stop_gradient(rhs).reshape(
            (lhs.shape[0],) + (1,) * (len(lhs.shape) - 1))
        return [lhs * mask.astype(lhs.dtype)], []


@register_op("_crop_assign", aliases=("_CropAssign",))
class CropAssign(Operator):
    """reference SimpleOp ``_crop_assign`` (matrix_op-inl.h:452-524):
    write ``rhs`` into the ``[begin, end)`` region of ``lhs``. The
    reference mutates lhs in place (kWriteInplace); here the op is
    functional — ``at[...].set`` — and the executor's output buffer
    takes the role of the in-place destination."""

    name_hint = "cropassign"
    PARAMS = {
        "begin": Param("shape", REQUIRED),
        "end": Param("shape", REQUIRED),
    }

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        from ..ndarray import _check_crop_region

        lhs, rhs = in_shapes
        if lhs is None:
            raise MXNetError("_crop_assign: lhs shape unknown")
        region = _check_crop_region(lhs, self.begin, self.end,
                                    "_crop_assign")
        if rhs is not None and tuple(rhs) != region:
            raise MXNetError("_crop_assign: rhs shape %s does not match "
                             "region %s" % (rhs, region))
        return [lhs, region], [lhs], []

    def apply(self, ctx, inputs, aux):
        lhs, rhs = inputs
        idx = tuple(slice(b, e) for b, e in zip(self.begin, self.end))
        return [lhs.at[idx].set(rhs.astype(lhs.dtype))], []


@register_op("_crop_assign_scalar", aliases=("_CropAssignScalar",))
class CropAssignScalar(Operator):
    """reference SimpleOp ``_crop_assign_scalar`` (matrix_op-inl.h:526-600):
    fill the ``[begin, end)`` region of the input with a scalar."""

    name_hint = "cropassignscalar"
    PARAMS = {
        "scalar": Param(float, 0.0),
        "begin": Param("shape", REQUIRED),
        "end": Param("shape", REQUIRED),
    }

    def infer_shape(self, in_shapes):
        from ..ndarray import _check_crop_region

        data = in_shapes[0]
        if data is None:
            raise MXNetError("_crop_assign_scalar: data shape unknown")
        _check_crop_region(data, self.begin, self.end,
                           "_crop_assign_scalar")
        return [data], [data], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        idx = tuple(slice(b, e) for b, e in zip(self.begin, self.end))
        return [x.at[idx].set(np.asarray(self.scalar, dtype=x.dtype))], []


@register_op("_CrossDeviceCopy")
class CrossDeviceCopy(Operator):
    """reference ``_CrossDeviceCopy`` (cross_device_copy.cc): a graph node
    marking a device boundary. Placement is the executor's job (group2ctx
    inserts jax.device_put at ctx_group edges — executor.py make_graph_eval),
    so the op itself is the identity."""

    name_hint = "crossdevicecopy"

    def apply(self, ctx, inputs, aux):
        return [inputs[0]], []


@register_op("slice_axis")
class SliceAxis(Operator):
    """reference slice_axis (matrix_op-inl.h): take [begin, end) along one
    axis; backward scatters the gradient into zeros (autodiff here)."""

    name_hint = "slice_axis"
    PARAMS = {
        "axis": Param(int, REQUIRED),
        "begin": Param(int, REQUIRED),
        "end": Param(int, REQUIRED),
    }

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("slice_axis: data shape unknown")
        if not (-len(data) <= self.axis < len(data)):
            raise MXNetError("slice_axis: axis %d out of range for %d-d "
                             "input" % (self.axis, len(data)))
        ax = self.axis % len(data)
        if not (0 <= self.begin < self.end <= data[ax]):
            raise MXNetError("slice_axis: invalid [%d, %d) on axis %d of %s"
                             % (self.begin, self.end, ax, (data,)))
        out = tuple(self.end - self.begin if i == ax else d
                    for i, d in enumerate(data))
        return [data], [out], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        if not (-x.ndim <= self.axis < x.ndim):
            raise MXNetError("slice_axis: axis %d out of range for %d-d "
                             "input" % (self.axis, x.ndim))
        ax = self.axis % x.ndim
        idx = tuple(slice(self.begin, self.end) if i == ax else slice(None)
                    for i in range(x.ndim))
        return [x[idx]], []


@register_op("Flip", aliases=("flip",))
class Flip(Operator):
    """reference flip (matrix_op-inl.h): reverse one axis."""

    name_hint = "flip"
    PARAMS = {"axis": Param(int, REQUIRED)}

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("flip: data shape unknown")
        if not (-len(data) <= self.axis < len(data)):
            raise MXNetError("flip: axis %d out of range for %d-d input"
                             % (self.axis, len(data)))
        return [data], [data], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        if not (-x.ndim <= self.axis < x.ndim):
            raise MXNetError("flip: axis %d out of range for %d-d input"
                             % (self.axis, x.ndim))
        idx = tuple(slice(None, None, -1) if i == self.axis % x.ndim
                    else slice(None) for i in range(x.ndim))
        return [x[idx]], []


# ---------------------------------------------------------------------------
# reductions (reference broadcast_reduce_op-inl.h)
# ---------------------------------------------------------------------------
class _ReduceOp(Operator):
    PARAMS = {
        "axis": Param("shape", None),
        "keepdims": Param(bool, False),
    }
    jname = "sum"

    def _axes(self, ndim):
        if self.axis is None:
            return tuple(range(ndim))
        return tuple(a % ndim for a in self.axis)

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("reduce: data shape unknown")
        axes = self._axes(len(data))
        if self.keepdims:
            out = tuple(1 if i in axes else s for i, s in enumerate(data))
        else:
            out = tuple(s for i, s in enumerate(data) if i not in axes)
            if not out:
                out = (1,)
        return [data], [out], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        x = inputs[0]
        axes = self._axes(x.ndim)
        r = getattr(jnp, self.jname)(x, axis=axes, keepdims=self.keepdims)
        if r.ndim == 0:
            r = r.reshape((1,))
        return [r], []


for _name, _jname in [("sum", "sum"), ("max", "max"), ("min", "min")]:
    _cls = type("Reduce_" + _name, (_ReduceOp,), {"jname": _jname,
                                                  "name_hint": _name})
    register_op(_name, aliases=["%s_axis" % _name])(_cls)


@register_op("broadcast_axis")
class BroadcastAxis(Operator):
    name_hint = "broadcast_axis"
    PARAMS = {"axis": Param("shape", ()), "size": Param("shape", ())}

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("broadcast_axis: data shape unknown")
        out = list(data)
        for a, s in zip(self.axis, self.size):
            out[a] = s
        return [data], [tuple(out)], []

    def apply(self, ctx, inputs, aux):
        x = inputs[0]
        out = list(x.shape)
        for a, s in zip(self.axis, self.size):
            out[a] = s
        return [_jnp().broadcast_to(x, tuple(out))], []


# ---------------------------------------------------------------------------
# matrix ops
# ---------------------------------------------------------------------------
@register_op("dot")
class Dot(Operator):
    name_hint = "dot"
    PARAMS = {
        "transpose_a": Param(bool, False),
        "transpose_b": Param(bool, False),
    }

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        a, b = in_shapes
        if a is None or b is None:
            raise MXNetError("dot: input shapes unknown")
        ar = tuple(reversed(a)) if self.transpose_a else a
        br = tuple(reversed(b)) if self.transpose_b else b
        if len(ar) == 1 and len(br) == 1:
            out = (1,)
        elif len(br) == 1:
            out = ar[:-1]
        elif len(ar) == 1:
            out = br[1:]
        else:
            out = ar[:-1] + br[1:]
        return [a, b], [out], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        a, b = inputs
        if self.transpose_a:
            a = a.T
        if self.transpose_b:
            b = b.T
        r = jnp.dot(a, b)
        if r.ndim == 0:
            r = r.reshape((1,))
        return [r], []


@register_op("batch_dot")
class BatchDot(Operator):
    name_hint = "batch_dot"
    PARAMS = {
        "transpose_a": Param(bool, False),
        "transpose_b": Param(bool, False),
    }

    def list_arguments(self):
        return ["lhs", "rhs"]

    def infer_shape(self, in_shapes):
        a, b = in_shapes
        if a is None or b is None:
            raise MXNetError("batch_dot: input shapes unknown")
        m = a[2] if self.transpose_a else a[1]
        k = b[1] if self.transpose_b else b[2]
        return [a, b], [(a[0], m, k)], []

    def apply(self, ctx, inputs, aux):
        jnp = _jnp()
        a, b = inputs
        if self.transpose_a:
            a = jnp.swapaxes(a, 1, 2)
        if self.transpose_b:
            b = jnp.swapaxes(b, 1, 2)
        return [jnp.einsum("bij,bjk->bik", a, b)], []


# ---------------------------------------------------------------------------
# gradient-control ops
# ---------------------------------------------------------------------------
@register_op("BlockGrad")
class BlockGrad(Operator):
    """Identity forward, zero gradient (reference block_grad-inl.h)."""

    name_hint = "blockgrad"

    def apply(self, ctx, inputs, aux):
        return [_jax().lax.stop_gradient(inputs[0])], []


@register_op("MakeLoss")
class MakeLoss(Operator):
    """Forward identity; gradient is grad_scale regardless of head grad
    (reference make_loss-inl.h) — turns any symbol into a loss."""

    name_hint = "makeloss"
    PARAMS = {"grad_scale": Param(float, 1.0)}

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        scale = self.grad_scale

        @jax.custom_vjp
        def f(x):
            return x

        def f_fwd(x):
            return x, None

        def f_bwd(_, g):
            return (_jnp().full_like(g, scale),)

        f.defvjp(f_fwd, f_bwd)
        return [f(inputs[0])], []


@register_op("IdentityAttachKLSparseReg")
class IdentityAttachKLSparseReg(Operator):
    """Identity with KL sparsity regularization gradient added
    (reference identity_attach_KL_sparse_reg-inl.h)."""

    name_hint = "identityattachklsparsereg"
    PARAMS = {
        "sparseness_target": Param(float, 0.1),
        "penalty": Param(float, 0.001),
        "momentum": Param(float, 0.9),
    }

    def list_auxiliary_states(self):
        return ["moving_avg"]

    def infer_shape(self, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise MXNetError("IdentityAttachKLSparseReg: data shape unknown")
        return [data], [data], [(data[1],)]

    def apply(self, ctx, inputs, aux):
        jax = _jax()
        jnp = _jnp()
        x = inputs[0]
        moving = aux[0]
        rho_hat = jnp.mean(x, axis=tuple(i for i in range(x.ndim) if i != 1))
        if ctx.is_train:
            new_aux = [moving * self.momentum + rho_hat * (1 - self.momentum)]
        else:
            new_aux = [moving]
        rho = self.sparseness_target
        penalty = self.penalty
        bshape = (1, -1) + (1,) * (x.ndim - 2)

        @jax.custom_vjp
        def f(x, rho_hat):
            return x

        def f_fwd(x, rho_hat):
            return x, rho_hat

        def f_bwd(rho_hat_res, g):
            kl_grad = penalty * (-rho / rho_hat_res + (1 - rho) / (1 - rho_hat_res))
            return g + kl_grad.reshape(bshape), jnp.zeros_like(rho_hat_res)

        f.defvjp(f_fwd, f_bwd)
        return [f(x, jax.lax.stop_gradient(rho_hat))], new_aux
